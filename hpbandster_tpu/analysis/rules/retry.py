"""retry-backoff — retry loops whose failure path can never exit.

The elastic fleet retries *everywhere* — result delivery, requeues,
nameserver bootstrap — and the repo's contract (docs/fault_tolerance.md
"Retry and backoff knobs") is that every retry is **bounded**: a capped
attempt count (``for attempt in range(n)``) or a monotonic deadline
(``while time.monotonic() < deadline``). An unbounded retry turns a
permanently-dead peer into a thread spinning forever — worse than a
crash, because the heartbeat collector sees a live process and the
anomaly detector sees nothing at all.

Flagged — a constant-true loop (``while True:`` / ``while 1:``) that

* contains a ``try`` with at least one ``except`` handler (it retries
  something that fails), and
* whose *failure region* — except handlers, ``else``/``finally`` blocks,
  and every statement outside the ``try`` body — contains no ``raise``,
  ``return``, or loop-level ``break``: once the attempt fails, nothing
  can ever stop the loop.

The ``try`` **body** is the attempt itself — its ``break``/``return`` is
the *success* exit and proves nothing about failure, so exits there do
not clear the loop. Bounded idioms are never flagged: ``for attempt in
range(n)`` (bounded by construction), a non-constant loop condition
(deadline or flag), a handler that re-raises after a cap check, or a
counter check after the ``try`` that raises/breaks. Nested ``def``/
``class`` bodies are opaque (their ``return`` exits the callee, not the
loop); ``break`` inside a nested loop exits that loop only.

A deliberate forever-server (an accept loop that must outlive any
failure) takes a suppression naming that intent::

    while True:  # graftlint: disable=retry-backoff — accept loop, lives as long as the process
"""

from __future__ import annotations

import ast
from typing import List

from hpbandster_tpu.analysis.core import Finding, Rule, SourceModule, register


def _is_constant_true(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value) is True


class _FailureRegionScan:
    """One walk of a constant-true loop body, classifying regions.

    ``has_handler`` — some ``try`` in the loop catches (it's a retry
    loop); ``can_exit`` — the failure region holds an exit (the retry is
    bounded). Tracked context: ``in_attempt`` (inside a ``try`` body —
    the attempt, where exits are the success path) and ``loop_depth``
    (``break`` only exits the flagged loop at depth 0).
    """

    def __init__(self) -> None:
        self.has_handler = False
        self.can_exit = False

    def scan(self, stmts, in_attempt: bool = False, loop_depth: int = 0) -> None:
        for stmt in stmts:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue  # opaque: exits there leave the callee, not the loop
            if isinstance(stmt, ast.Try):
                if stmt.handlers:
                    self.has_handler = True
                # everything under an attempt stays attempt: a raise in a
                # nested handler is still caught by the outer try
                self.scan(stmt.body, True, loop_depth)
                for h in stmt.handlers:
                    self.scan(h.body, in_attempt, loop_depth)
                self.scan(stmt.orelse, in_attempt, loop_depth)
                self.scan(stmt.finalbody, in_attempt, loop_depth)
                continue
            if isinstance(stmt, (ast.Raise, ast.Return)):
                if not in_attempt:
                    self.can_exit = True
                continue
            if isinstance(stmt, ast.Break):
                if not in_attempt and loop_depth == 0:
                    self.can_exit = True
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self.scan(stmt.body, in_attempt, loop_depth + 1)
                self.scan(stmt.orelse, in_attempt, loop_depth)
                continue
            if isinstance(stmt, (ast.If,)):
                self.scan(stmt.body, in_attempt, loop_depth)
                self.scan(stmt.orelse, in_attempt, loop_depth)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self.scan(stmt.body, in_attempt, loop_depth)
                continue
            if isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    self.scan(case.body, in_attempt, loop_depth)
                continue
            # simple statements (Expr, Assign, AugAssign, Pass, Continue,
            # Delete, Global, ...) neither exit nor nest


@register
class RetryBackoffRule(Rule):
    name = "retry-backoff"
    description = (
        "unbounded retry loop: a while-True retry whose failure path has "
        "no attempt cap, deadline, raise, return, or break — a dead peer "
        "spins this thread forever"
    )

    def check(self, module: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        for node in module.walk():
            if not isinstance(node, ast.While):
                continue
            if not _is_constant_true(node.test):
                continue
            scan = _FailureRegionScan()
            scan.scan(node.body)
            if scan.has_handler and not scan.can_exit:
                findings.append(
                    self.finding(
                        module, node,
                        "constant-true retry loop whose failure path can "
                        "never exit: cap the attempts (for attempt in "
                        "range(n)), loop on a monotonic deadline, or "
                        "re-raise after a budget check (suppress with "
                        "justification for deliberate forever-servers)",
                    )
                )
        return findings
