"""obs-emit-in-jit — event emission inside traced JAX code.

``hpbandster_tpu.obs`` emission (``emit``/``span``/``get_bus().emit``) is
host work: it reads host clocks, takes host locks, and may write files.
Inside a ``jit``/``vmap``/``pmap``-ed body it either runs once at TRACE
time (the event fires at compile, silently never again — telemetry that
lies) or, under callback-style escapes, forces a host round-trip per
device step. The supported pattern is emitting AROUND the jit boundary:
the caller opens a span, the traced function stays pure (exactly how
``parallel/batched_worker.py`` wraps ``backend.evaluate``).

Detection reuses jit-host-sync's traced-function discovery (decorated
with, or passed into, a jit/vmap/pmap wrapper in this module). Inside a
traced body it flags:

* calls resolving through the import map into ``hpbandster_tpu.obs``
  (``emit(...)``, ``span(...)``, ``obs.emit(...)``, the timeline span
  API ``phase_span(...)``/``mark(...)``, aliased imports);
* ``.emit(...)``, ``.phase_span(...)`` and ``.mark(...)`` method calls —
  including on the result of ``get_bus()`` — but only in modules that
  import ``hpbandster_tpu.obs`` at all, so unrelated APIs elsewhere
  stay unflagged.
"""

from __future__ import annotations

import ast
from typing import List

from hpbandster_tpu.analysis.core import Finding, Rule, SourceModule, register
from hpbandster_tpu.analysis.rules._util import ImportMap, import_map_for
from hpbandster_tpu.analysis.rules.jit_purity import traced_functions_for

_OBS_PREFIX = "hpbandster_tpu.obs"

#: emission-shaped attribute calls flagged in obs-importing modules:
#: the bus API (``.emit``) and the timeline span API
#: (``obs/timeline.py`` ``phase_span``/``mark``) — both are host clock
#: reads + sink dispatch, equally wrong inside a traced body
_EMIT_ATTRS = frozenset({"emit", "phase_span", "mark"})


def _module_imports_obs(imports: ImportMap) -> bool:
    return any(v.startswith(_OBS_PREFIX) or v == "hpbandster_tpu"
               for v in imports.aliases.values())


def _resolves_to_obs(node: ast.expr, imports: ImportMap) -> bool:
    resolved = imports.resolve(node) or ""
    # `from hpbandster_tpu import obs` resolves `obs.emit` to
    # "hpbandster_tpu.obs.emit"; `from hpbandster_tpu.obs import emit`
    # resolves `emit` to "hpbandster_tpu.obs.emit"
    return resolved.startswith(_OBS_PREFIX)


@register
class ObsEmitInJitRule(Rule):
    name = "obs-emit-in-jit"
    description = (
        "obs event emission (emit/span/bus.emit or the timeline span API "
        "phase_span/mark) inside a jit/vmap/pmap-ed body — fires at trace "
        "time, not per execution; emit around the jit boundary instead"
    )

    def check(self, module: SourceModule) -> List[Finding]:
        # sound prefilter: both a trace wrapper and an obs mention required
        if "obs" not in module.text or not any(
            t in module.text for t in ("jit", "pmap", "vmap", "vectorize")
        ):
            return []
        imports = import_map_for(module)
        imports_obs = _module_imports_obs(imports)
        findings: List[Finding] = []
        for fn in traced_functions_for(module):
            for node in module.subtree(fn):
                if not isinstance(node, ast.Call):
                    continue
                if _resolves_to_obs(node.func, imports):
                    what = ast.unparse(node.func)
                    findings.append(self._flag(module, node, fn, what))
                elif (
                    imports_obs
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMIT_ATTRS
                ):
                    findings.append(
                        self._flag(module, node, fn, f".{node.func.attr}()")
                    )
        return findings

    def _flag(
        self, module: SourceModule, node: ast.Call, fn: ast.FunctionDef, what: str
    ) -> Finding:
        return self.finding(
            module, node,
            f"{what} inside traced function {fn.name!r} runs at trace time "
            "(once per compile), not per execution — move the emission "
            "outside the jit boundary",
        )
