"""Bundled graftlint rule pack — importing this package registers every rule.

Add a rule by dropping a module here that defines a ``Rule`` subclass
decorated with ``@register`` and importing it below (see
``docs/static_analysis.md`` for the walkthrough).
"""

from hpbandster_tpu.analysis.rules import (  # noqa: F401
    donation,
    exceptions,
    jit_loop,
    jit_purity,
    lockorder,
    locks,
    markers,
    obs_emit,
    obs_reserved,
    prng,
    retry,
    trace_escape,
    wallclock,
)
