"""trace-escape — host syncs and obs emits reached *through* helper calls
from traced bodies.

``jit-host-sync`` and ``obs-emit-in-jit`` stop at the function boundary:
a traced body that calls ``self._score(batch)`` looks pure even when
``_score`` does ``float(x)`` three frames down. This rule walks the
whole-program call graph (``analysis/graph.py``) from every traced root
(jit/vmap/pmap-decorated function, or one passed into a wrapper /
``lax`` combinator) and re-runs the same taint-and-sink engine
(``jit_purity.analyze_body``) inside each callee, with the callee's
taint seed derived from which *arguments* were traced at the call site:

* positional and keyword arguments are mapped onto parameter names
  (bound calls skip the self slot);
* a callee is analyzed once per distinct traced-parameter set — the
  per-function summary cache the fast-lane bar depends on;
* chains are followed to ``_MAX_DEPTH`` call hops (the bounded-depth
  contract; deeper sinks are out of contract, see
  docs/static_analysis.md);
* callees that are themselves traced roots are skipped — they are
  audited as their own root, and findings would duplicate.

Findings are two-location: the **primary** location is the call site
inside (or downstream of) the traced body — where the trace boundary is
breached and where the fix goes — and the **related** location is the
sink itself (the ``float()``, ``.item()``, ``np.``, branch, or
``obs.emit``). Suppressions at either location mute the finding.

The obs leg needs no taint: emitting from anywhere beneath a traced body
fires at trace time (once per compile) regardless of what the arguments
are, so any chain from a traced root into ``hpbandster_tpu.obs`` call
machinery is flagged.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from hpbandster_tpu.analysis.core import Finding, ProjectRule, register
from hpbandster_tpu.analysis.graph import CallSite, FunctionInfo, Project
from hpbandster_tpu.analysis.rules._util import import_map_for
from hpbandster_tpu.analysis.rules.jit_purity import (
    analyze_body,
    traced_param_seed,
)
from hpbandster_tpu.analysis.rules.obs_emit import _OBS_PREFIX

#: call-graph hops followed below a traced body (root body = hop 0)
_MAX_DEPTH = 4


@dataclasses.dataclass(frozen=True)
class _Sink:
    """A witnessed escape inside some callee: ``what`` at ``path:line``,
    reached through ``hops`` call edges from the traced body."""

    what: str
    path: str
    line: int
    hops: int


class _EscapeIndex:
    """Per-project summary caches shared across roots (and across the two
    legs), keyed so repeated helpers — the common case — analyze once."""

    def __init__(self, project: Project):
        self.project = project
        #: (qname, frozen traced params) -> first host-sync sink, or None
        self.sync_memo: Dict[Tuple[str, FrozenSet[str]], Optional[_Sink]] = {}
        #: qname -> first obs-call sink, or None (taint-free leg)
        self.emit_memo: Dict[str, Optional[_Sink]] = {}
        self.traced_qnames: Set[str] = {
            info.qname for info, _static in project.traced_roots()
        }

    # ------------------------------------------------------------ host sync
    def sync_sink(
        self, info: FunctionInfo, tainted: FrozenSet[str], depth: int
    ) -> Optional[_Sink]:
        """First host-sync sink reachable when ``info`` is entered with
        ``tainted`` parameters carrying tracers; None when provably clean
        within the depth budget (or on a cycle — under-approximate)."""
        key = (info.qname, tainted)
        if key in self.sync_memo:
            return self.sync_memo[key]
        self.sync_memo[key] = None  # cycle guard: in-progress reads as clean
        sink = self._sync_sink_uncached(info, tainted, depth)
        self.sync_memo[key] = sink
        return sink

    def _sync_sink_uncached(
        self, info: FunctionInfo, tainted: FrozenSet[str], depth: int
    ) -> Optional[_Sink]:
        module = info.module
        traced, sinks = analyze_body(
            module, import_map_for(module), info.node, set(tainted)
        )
        if sinks:
            node, what = sinks[0]
            return _Sink(what, module.path, node.lineno, 1)
        if depth >= _MAX_DEPTH:
            return None
        for site in self.project.callees(info.qname):
            if site.via_partial or site.callee.qname in self.traced_qnames:
                continue
            sub = _tainted_params(site, traced)
            if not sub:
                continue
            found = self.sync_sink(site.callee, frozenset(sub), depth + 1)
            if found is not None:
                return dataclasses.replace(found, hops=found.hops + 1)
        return None

    # ------------------------------------------------------------- obs emit
    def emit_sink(self, info: FunctionInfo, depth: int) -> Optional[_Sink]:
        """First call into ``hpbandster_tpu.obs`` machinery reachable from
        ``info`` — no taint required, trace-time execution is the bug."""
        if info.qname in self.emit_memo:
            return self.emit_memo[info.qname]
        self.emit_memo[info.qname] = None
        sink = self._emit_sink_uncached(info, depth)
        self.emit_memo[info.qname] = sink
        return sink

    def _emit_sink_uncached(self, info: FunctionInfo, depth: int) -> Optional[_Sink]:
        module = info.module
        imports = import_map_for(module)
        for node in self.project.fn_calls.get(info.qname, ()):
            resolved = imports.resolve(node.func) or ""
            if resolved.startswith(_OBS_PREFIX):
                return _Sink(ast.unparse(node.func) + "()", module.path, node.lineno, 1)
        if depth >= _MAX_DEPTH:
            return None
        for site in self.project.callees(info.qname):
            callee = site.callee
            if site.via_partial or callee.qname in self.traced_qnames:
                continue
            if callee.qname.startswith(_OBS_PREFIX + "."):
                return _Sink(
                    f"{callee.qname.rsplit('.', 1)[-1]}()",
                    module.path,
                    site.line,
                    1,
                )
            found = self.emit_sink(callee, depth + 1)
            if found is not None:
                return dataclasses.replace(found, hops=found.hops + 1)
        return None


def _escape_index(project: Project) -> _EscapeIndex:
    index = project.cache.get("trace_escape")
    if index is None:
        index = _EscapeIndex(project)
        project.cache["trace_escape"] = index
    return index


def _tainted_params(site: CallSite, traced: Set[str]) -> Set[str]:
    """Callee parameter names that receive a traced value at ``site``."""

    def is_traced(expr: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Name) and n.id in traced for n in ast.walk(expr)
        )

    callee = site.callee
    params = callee.positional_params(site.bound)
    out: Set[str] = set()
    for idx, arg in enumerate(site.node.args):
        if isinstance(arg, ast.Starred):
            if is_traced(arg.value) and callee.has_vararg:
                out.update(params[idx:])
            continue
        if not is_traced(arg):
            continue
        if idx < len(params):
            out.add(params[idx])
        elif callee.has_vararg:
            out.add("*")  # lands in the vararg; seed every remaining slot
            out.update(params[idx:])
    for kw in site.node.keywords:
        if not is_traced(kw.value):
            continue
        if kw.arg is None:  # **kwargs splat: could land anywhere
            out.update(params)
            out.update(callee.kwonly)
        elif kw.arg in params or kw.arg in callee.kwonly or callee.has_kwarg:
            out.add(kw.arg)
    out.discard("*")
    return out


@register
class TraceEscapeRule(ProjectRule):
    name = "trace-escape"
    description = (
        "host sync or obs emission reached through helper calls from a "
        "jit/vmap/pmap-traced body — invisible to the intraprocedural rules"
    )

    def check_project(self, project: Project) -> List[Finding]:
        index = _escape_index(project)
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()

        for info, static in project.traced_roots():
            module = info.module
            seed = traced_param_seed(info.node, static)
            traced, _root_sinks = analyze_body(
                module, import_map_for(module), info.node, seed
            )  # root-level sinks belong to jit-host-sync — not re-reported
            for site in project.callees(info.qname):
                if site.via_partial or site.callee.qname in index.traced_qnames:
                    continue
                callee = site.callee
                tainted = _tainted_params(site, traced)
                if tainted:
                    sink = index.sync_sink(callee, frozenset(tainted), 1)
                    if sink is not None:
                        key = (module.path, site.line, "sync")
                        if key not in seen:
                            seen.add(key)
                            findings.append(
                                Finding(
                                    rule=self.name,
                                    path=module.path,
                                    line=site.line,
                                    message=(
                                        f"traced value escapes {info.name!r} through "
                                        f"{callee.name!r}: {sink.what} "
                                        f"{sink.hops} call(s) down forces a host "
                                        "sync inside the trace — hoist the host "
                                        "work out of the traced body"
                                    ),
                                    related_path=sink.path,
                                    related_line=sink.line,
                                    related_note=f"{sink.what} happens here",
                                )
                            )
                emit = (
                    _Sink(f"{callee.name}()", module.path, site.line, 1)
                    if callee.qname.startswith(_OBS_PREFIX + ".")
                    else index.emit_sink(callee, 1)
                )
                if emit is not None:
                    key = (module.path, site.line, "emit")
                    if key not in seen:
                        seen.add(key)
                        findings.append(
                            Finding(
                                rule=self.name,
                                path=module.path,
                                line=site.line,
                                message=(
                                    f"call into {callee.name!r} from traced body "
                                    f"{info.name!r} reaches obs emission "
                                    f"({emit.what}) — fires at trace time, once "
                                    "per compile, not per execution; emit around "
                                    "the jit boundary"
                                ),
                                related_path=emit.path,
                                related_line=emit.line,
                                related_note=f"{emit.what} happens here",
                            )
                        )
        return findings
