"""swallowed-exception — over-broad handlers that drop the error on the floor.

A bare ``except:`` or ``except Exception:`` whose body neither re-raises,
nor logs, nor even *looks at* the exception turns a dead worker, a
truncated RPC frame, or a crashed wave into silence. In this codebase the
contract is explicit (see ``parallel/rpc.py``): exceptions are marshalled,
logged, or re-queued — never ignored.

Flagged: handlers catching ``Exception``/``BaseException`` (bare, named,
or inside a tuple) whose body contains none of

* a ``raise``,
* a call to anything that smells like reporting (``log``/``warn``/
  ``error``/``exception``/``print``/``fail``/``format_exc``/``crash``…),
* a use of the bound exception name (``except Exception as e`` whose body
  reads ``e`` is *handling* it — marshalling counts).

Narrow handlers (``except (CommunicationError, RPCError):``) are never
flagged: naming the failure mode is the point.
"""

from __future__ import annotations

import ast
from typing import List

from hpbandster_tpu.analysis.core import Finding, Rule, SourceModule, register
from hpbandster_tpu.analysis.rules._util import ImportMap, dotted_name, import_map_for

_BROAD = {"Exception", "BaseException", "builtins.Exception", "builtins.BaseException"}
#: exact tokens (whole callee tail, or one of its _-separated words) that
#: count as reporting — substring matching would let `close_dialog` or
#: `catalog` masquerade as logging
_REPORTING_TOKENS = {
    "log",
    "warn",
    "warning",
    "error",
    "exception",
    "critical",
    "fatal",
    "print",
    "pprint",
    "info",
    "debug",
    "fail",
    "failed",
    "format_exc",
    "print_exc",
    "crash",
    "report",
    "traceback",
}


def _is_broad(handler: ast.ExceptHandler, imports: ImportMap) -> bool:
    if handler.type is None:
        return True
    types = (
        handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    )
    return any((imports.resolve(t) or "") in _BROAD for t in types)


def _handles(module: SourceModule, handler: ast.ExceptHandler) -> bool:
    exc_name = handler.name
    for node in module.subtree(handler):
        if isinstance(node, ast.Raise):
            return True
        if exc_name and isinstance(node, ast.Name) and node.id == exc_name:
            return True
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            tail = callee.rsplit(".", 1)[-1].lower()
            if tail in _REPORTING_TOKENS or any(
                part in _REPORTING_TOKENS for part in tail.split("_")
            ):
                return True
    return False


@register
class SwallowedExceptionRule(Rule):
    name = "swallowed-exception"
    description = (
        "bare/over-broad except that neither re-raises, logs, nor uses the "
        "exception — the error vanishes"
    )

    def check(self, module: SourceModule) -> List[Finding]:
        if "except" not in module.text:
            return []
        imports = import_map_for(module)
        findings: List[Finding] = []
        for node in module.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node, imports) or _handles(module, node):
                continue
            caught = "bare except" if node.type is None else (
                f"except {ast.unparse(node.type)}"
            )
            findings.append(
                self.finding(
                    module, node,
                    f"{caught} swallows the error: re-raise, log it, or narrow "
                    "the exception type (suppress with justification if "
                    "best-effort silence is genuinely intended)",
                )
            )
        return findings
