"""jit-host-sync — host synchronization inside traced JAX code.

``float(x)``, ``int(x)``, ``bool(x)``, ``x.item()``, ``x.tolist()``,
``np.asarray(x)`` / ``np.array(x)`` and ``jax.device_get(x)`` applied to a
traced value inside a ``jit``/``vmap``/``pmap``-ed function either raise a
``TracerConversionError`` at trace time or — worse, under ``io_callback``
style escapes — silently force a device round-trip per call. Python
``if``/``while`` on a traced value is the same bug wearing control-flow
clothes.

What counts as *traced* is inferred conservatively, so the rule stays
quiet on the static-shape arithmetic idiomatic in this repo (``float(
budgets[s])`` on a closed-over Python tuple is fine and not flagged):

* a function is traced when it is decorated with ``jax.jit``/``pmap``/
  ``vmap`` (directly or via ``functools.partial``), or its name appears
  inside the arguments of such a wrapper call anywhere in the module
  (``jax.jit(batch_fn)``, ``jax.jit(shard_map(ring, ...))``);
* inside it, traced values are the non-static parameters
  (``static_argnames``/``static_argnums`` are parsed and excluded) plus
  anything assigned from an expression that references a traced name.

Cross-module wrapping (``jax.jit(imported_fn)``) is out of scope — the
rule runs per module; the wrapped module gets its own scan when its own
jit sites are declared there.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from hpbandster_tpu.analysis.core import Finding, Rule, SourceModule, register
from hpbandster_tpu.analysis.rules._util import ImportMap, import_map_for

_JIT_WRAPPERS = {
    "jax.jit",
    "jax.pmap",
    "jax.vmap",
    "jax.numpy.vectorize",
    "jit",
    "pmap",
    "vmap",
    # obs.runtime.tracked_jit is jax.jit plus compile telemetry — a body
    # it wraps is traced exactly like a jit-decorated one
    "tracked_jit",
    "hpbandster_tpu.obs.tracked_jit",
    "hpbandster_tpu.obs.runtime.tracked_jit",
}

_CASTS = {"float", "int", "bool", "complex"}
_NUMPY_SINKS = {"asarray", "array", "copy", "ascontiguousarray"}
_METHOD_SINKS = {"item", "tolist", "__array__"}

#: lax control-flow combinators whose FUNCTION arguments run in-trace:
#: a scan/while/fori body (the resident outer-loop idiom, ops/sweep.py)
#: is traced exactly like a jit-decorated function — host syncs inside
#: it raise at trace time or force a device round-trip per iteration,
#: which inside a loop body is the worst place to pay one
_LAX_BODY_WRAPPERS = {
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.map",
    "lax.scan",
    "lax.while_loop",
    "lax.fori_loop",
    "lax.cond",
    "lax.switch",
    "lax.map",
}

#: tracer attributes whose value is trace-time METADATA, not device data:
#: ``float(x.shape[0])`` / ``float(len(x))`` are concrete at trace time
#: and must not be flagged (the static-shape arithmetic idiomatic here)
_STATIC_TRACER_ATTRS = {"shape", "ndim", "size", "dtype"}


def _is_jit_expr(node: ast.AST, imports: ImportMap) -> bool:
    """True for ``jax.jit`` / ``partial(jax.jit, ...)`` / ``jax.jit(...)``
    expressions (decorator or callee position)."""
    resolved = imports.resolve(node)
    if resolved in _JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        fn = imports.resolve(node.func)
        if fn in _JIT_WRAPPERS:
            return True
        if fn in ("functools.partial", "partial"):
            return any(_is_jit_expr(a, imports) for a in node.args)
    return False


def traced_functions(
    tree: ast.Module, imports: ImportMap, nodes=None
) -> Dict[ast.FunctionDef, Set[str]]:
    """Every function the module jits/vmaps/pmaps (decorator or wrapper-call
    position) -> its static parameter names. Shared by jit-host-sync and
    obs-emit-in-jit: 'is this body traced?' is one question, answered once
    (:func:`traced_functions_for` memoizes it per module).

    ``nodes`` optionally supplies the module's pre-walked node sequence
    (``SourceModule.walk()``) so this does not re-walk the whole tree."""
    if nodes is None:
        nodes = list(ast.walk(tree))
    by_name: Dict[str, List[ast.FunctionDef]] = {}
    for node in nodes:
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, []).append(node)

    traced: Dict[ast.FunctionDef, Set[str]] = {}

    def mark(fn: ast.FunctionDef, static: Set[str]) -> None:
        traced.setdefault(fn, set()).update(static)

    for node in nodes:
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if _is_jit_expr(dec, imports):
                    mark(node, _static_params(dec, node))
        if isinstance(node, ast.Call) and _is_jit_expr(node.func, imports):
            for arg in node.args:
                for inner in ast.walk(arg):
                    if isinstance(inner, ast.Name) and inner.id in by_name:
                        for fn in by_name[inner.id]:
                            mark(fn, _static_params(node, fn))
        # lax control-flow combinators trace their function arguments:
        # a scan/while/fori/cond body is a traced function with no
        # static-argnames escape hatch. The name pre-check keeps the
        # resolve() off the hot path — most calls pass no module-level
        # function names at all (the 5s fast-lane bar)
        if (
            isinstance(node, ast.Call)
            and any(
                isinstance(a, ast.Name) and a.id in by_name
                for a in node.args
            )
            and imports.resolve(node.func) in _LAX_BODY_WRAPPERS
        ):
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    for fn in by_name[arg.id]:
                        mark(fn, set())
    return traced


def traced_functions_for(module, nodes=None) -> Dict[ast.FunctionDef, Set[str]]:
    """Per-module :func:`traced_functions`, built once and memoized on the
    SourceModule (two rules ask the same question of every module).

    ``nodes`` optionally narrows the scan to a pre-collected census — the
    call graph hands over its per-module FunctionDef/Call list, which is
    all :func:`traced_functions` ever inspects."""
    traced = module.cache.get("traced_functions")
    if traced is None:
        # cheap text prefilter first: a module whose source never mentions
        # a trace wrapper cannot define a traced function, and skipping it
        # here keeps whole-program traced-root discovery (analysis/graph)
        # from paying a full walk of every call-graph-context module
        if not any(
            marker in module.text
            for marker in ("jit", "pmap", "vmap", "vectorize", "lax.")
        ):
            traced = {}
        else:
            if nodes is None:
                nodes = module.walk() if "dfs" in module.cache else None
            traced = traced_functions(
                module.tree, import_map_for(module), nodes=nodes
            )
        module.cache["traced_functions"] = traced
    return traced


def _static_params(dec: ast.AST, fn: ast.FunctionDef) -> Set[str]:
    """Parameter names excluded from tracing by static_argnames/argnums."""
    static: Set[str] = set()
    calls = [dec] if isinstance(dec, ast.Call) else []
    for call in calls:
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                try:
                    val = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                names = [val] if isinstance(val, str) else list(val)
                static.update(str(n) for n in names)
            elif kw.arg == "static_argnums":
                try:
                    val = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                nums = [val] if isinstance(val, int) else list(val)
                params = [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)]
                for i in nums:
                    if isinstance(i, int) and 0 <= i < len(params):
                        static.add(params[i])
    return static


@register
class JitHostSyncRule(Rule):
    name = "jit-host-sync"
    description = (
        "host-sync call (float/int/bool/.item/np.asarray/device_get or Python "
        "branch) on a traced value inside a jit/vmap/pmap-ed function"
    )

    def check(self, module: SourceModule) -> List[Finding]:
        # sound prefilter: a traced function requires one of these tokens
        # ("lax" without the dot: `from jax.lax import while_loop` never
        # contains "lax." — soundness beats the few extra admissions)
        if not any(
            t in module.text
            for t in ("jit", "pmap", "vmap", "vectorize", "lax")
        ):
            return []
        imports = import_map_for(module)
        traced_fns = traced_functions_for(module)
        findings: List[Finding] = []
        for fn, static in traced_fns.items():
            findings.extend(self._check_traced_fn(module, imports, fn, static))
        return findings

    # -------------------------------------------------------------- analysis
    def _check_traced_fn(
        self,
        module: SourceModule,
        imports: ImportMap,
        fn: ast.FunctionDef,
        static: Set[str],
    ) -> List[Finding]:
        _, sinks = analyze_body(module, imports, fn, traced_param_seed(fn, static))
        return [
            self.finding(
                module,
                node,
                f"{what} on a traced value inside traced function "
                f"{fn.name!r} forces a host sync (or raises at trace time)",
            )
            for node, what in sinks
        ]


def traced_param_seed(fn: ast.FunctionDef, static: Set[str]) -> Set[str]:
    """The parameter names that carry tracers into ``fn``'s body: every
    non-static parameter except self/cls."""
    traced: Set[str] = {
        a.arg
        for a in (
            list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs)
        )
        if a.arg not in static and a.arg not in ("self", "cls")
    }
    if fn.args.vararg is not None:
        traced.add(fn.args.vararg.arg)
    return traced


def analyze_body(
    module: SourceModule,
    imports: ImportMap,
    fn: ast.FunctionDef,
    seed: Set[str],
) -> "Tuple[Set[str], List[Tuple[ast.AST, str]]]":
    """The taint-and-sink engine behind jit-host-sync, factored out so the
    interprocedural trace-escape rule can run it per (function, traced
    parameter set) summary: starting from ``seed`` traced names, propagate
    taint through assignments and return ``(traced_names, sinks)`` where
    each sink is ``(node, what)`` — a host-sync applied to a traced value.
    """
    traced = set(seed)
    fn_nodes = tuple(module.subtree(fn))

    def refs_traced(node: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Name) and n.id in traced
            for n in module.subtree(node)
        )

    def value_traced(node: ast.AST) -> bool:
        """Shield-aware ``refs_traced`` for assignment RHS: a value that
        only reaches traced names through static metadata extractors
        (``x.shape[0]``, ``len(x)``, ``x.dtype``) is concrete at trace
        time and must not propagate taint — ``n_rows = x.shape[0]`` then
        ``if n_rows < n0:`` is legal trace-time shape arithmetic."""
        if isinstance(node, ast.Name):
            return node.id in traced
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_TRACER_ATTRS:
                return False
            return value_traced(node.value)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
        ):
            return False
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
            for op in node.ops
        ):
            # identity is a static trace-time fact; membership on a pytree
            # container (`b in warm_n` on a dict of arrays) is static dict
            # arithmetic, and on an actual tracer `in` raises LOUDLY at
            # trace time — either way no silent escape flows out of it
            return False
        return any(value_traced(c) for c in ast.iter_child_nodes(node))

    def taint_target(tgt: ast.expr) -> None:
        # a subscript store taints the container, never the index names
        # (`counts[b] = traced` says nothing about `b`)
        while isinstance(tgt, (ast.Subscript, ast.Starred)):
            tgt = tgt.value
        if isinstance(tgt, ast.Name):
            traced.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                taint_target(el)

    # two forward passes: assignments referencing traced names taint
    # their targets (handles use-before-def between helpers once)
    for _ in range(2):
        for node in fn_nodes:
            if isinstance(node, ast.Assign) and value_traced(node.value):
                for tgt in node.targets:
                    taint_target(tgt)
            elif isinstance(node, ast.AugAssign) and value_traced(node.value):
                taint_target(node.target)

    sinks: List[Tuple[ast.AST, str]] = []

    def flag(node: ast.AST, what: str) -> None:
        sinks.append((node, what))

    def cast_arg_traced(node: ast.AST) -> bool:
        """Can this expression's VALUE be a tracer? Static metadata
        extractors shield: ``len(x)``, ``x.shape``/``ndim``/``size``/
        ``dtype`` are concrete at trace time even on a tracer, so
        ``float(x.shape[0])`` stays legal while ``float(x[0])`` and
        ``float(x.sum())`` are flagged."""
        if isinstance(node, ast.Name):
            return node.id in traced
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_TRACER_ATTRS:
                return False
            return cast_arg_traced(node.value)
        if isinstance(node, ast.Subscript):
            return cast_arg_traced(node.value)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and node.func.id == "len":
                return False
            parts = [node.func, *node.args]
            parts += [kw.value for kw in node.keywords]
            return any(cast_arg_traced(p) for p in parts)
        if isinstance(node, ast.BinOp):
            return cast_arg_traced(node.left) or cast_arg_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return cast_arg_traced(node.operand)
        # anything else (constants, tuples, comprehensions): quiet —
        # the rule stays conservative on forms it cannot judge
        return False

    #: BoolOp nodes already judged as an If/While/IfExp/Assert test —
    #: the owning statement reports them; the generic and/or check
    #: below must not double-flag the same coercion
    judged_tests = set()
    for node in fn_nodes:
        if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            if isinstance(node.test, ast.BoolOp):
                judged_tests.add(id(node.test))

    for node in fn_nodes:
        if isinstance(node, ast.Call):
            callee = imports.resolve(node.func)
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in _CASTS
                and node.args
                and cast_arg_traced(node.args[0])
            ):
                flag(node, f"{node.func.id}()")
            elif (
                callee is not None
                and node.args
                and refs_traced(node.args[0])
                and (
                    callee == "jax.device_get"
                    or (
                        callee.startswith(("numpy.", "np."))
                        and callee.rsplit(".", 1)[-1] in _NUMPY_SINKS
                    )
                )
            ):
                flag(node, callee)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHOD_SINKS
                and refs_traced(node.func.value)
            ):
                flag(node, f".{node.func.attr}()")
        elif isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            # only bare traced names as direct operands: `if x:` /
            # `if x > 0:` are tracer bool-coercions; `if f(x) ...` is
            # left alone (f may be static — shape math, trained_split).
            # IfExp (`a if x else b`) and Assert are the same implicit
            # __bool__ wearing expression/statement clothes.
            test = node.test
            operands: List[ast.expr] = [test]
            if isinstance(test, ast.Compare):
                if all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
                ):
                    # `x is None` on a tracer is Python IDENTITY — a
                    # static trace-time fact, no __bool__ coercion
                    continue
                operands = [test.left, *test.comparators]
            elif isinstance(test, ast.BoolOp):
                operands = list(test.values)
            elif isinstance(test, ast.UnaryOp):
                operands = [test.operand]
            if any(
                isinstance(op, ast.Name) and op.id in traced for op in operands
            ):
                what = (
                    "Python branch" if isinstance(node, (ast.If, ast.While))
                    else "conditional expression"
                    if isinstance(node, ast.IfExp) else "assert"
                )
                flag(node, what)
        elif (
            isinstance(node, ast.BoolOp)
            and id(node) not in judged_tests
            and any(
                isinstance(v, ast.Name) and v.id in traced
                for v in node.values
            )
        ):
            # bare `x and y` / `x or y` on a tracer coerces __bool__
            # exactly like `if x:` — the short-circuit needs a value
            flag(node, "and/or")
    return traced, sinks
