"""jit-host-sync — host synchronization inside traced JAX code.

``float(x)``, ``int(x)``, ``bool(x)``, ``x.item()``, ``x.tolist()``,
``np.asarray(x)`` / ``np.array(x)`` and ``jax.device_get(x)`` applied to a
traced value inside a ``jit``/``vmap``/``pmap``-ed function either raise a
``TracerConversionError`` at trace time or — worse, under ``io_callback``
style escapes — silently force a device round-trip per call. Python
``if``/``while`` on a traced value is the same bug wearing control-flow
clothes.

What counts as *traced* is inferred conservatively, so the rule stays
quiet on the static-shape arithmetic idiomatic in this repo (``float(
budgets[s])`` on a closed-over Python tuple is fine and not flagged):

* a function is traced when it is decorated with ``jax.jit``/``pmap``/
  ``vmap`` (directly or via ``functools.partial``), or its name appears
  inside the arguments of such a wrapper call anywhere in the module
  (``jax.jit(batch_fn)``, ``jax.jit(shard_map(ring, ...))``);
* inside it, traced values are the non-static parameters
  (``static_argnames``/``static_argnums`` are parsed and excluded) plus
  anything assigned from an expression that references a traced name.

Cross-module wrapping (``jax.jit(imported_fn)``) is out of scope — the
rule runs per module; the wrapped module gets its own scan when its own
jit sites are declared there.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from hpbandster_tpu.analysis.core import Finding, Rule, SourceModule, register
from hpbandster_tpu.analysis.rules._util import ImportMap, import_map_for

_JIT_WRAPPERS = {
    "jax.jit",
    "jax.pmap",
    "jax.vmap",
    "jax.numpy.vectorize",
    "jit",
    "pmap",
    "vmap",
    # obs.runtime.tracked_jit is jax.jit plus compile telemetry — a body
    # it wraps is traced exactly like a jit-decorated one
    "tracked_jit",
    "hpbandster_tpu.obs.tracked_jit",
    "hpbandster_tpu.obs.runtime.tracked_jit",
}

_CASTS = {"float", "int", "bool", "complex"}
_NUMPY_SINKS = {"asarray", "array", "copy", "ascontiguousarray"}
_METHOD_SINKS = {"item", "tolist", "__array__"}


def _is_jit_expr(node: ast.AST, imports: ImportMap) -> bool:
    """True for ``jax.jit`` / ``partial(jax.jit, ...)`` / ``jax.jit(...)``
    expressions (decorator or callee position)."""
    resolved = imports.resolve(node)
    if resolved in _JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        fn = imports.resolve(node.func)
        if fn in _JIT_WRAPPERS:
            return True
        if fn in ("functools.partial", "partial"):
            return any(_is_jit_expr(a, imports) for a in node.args)
    return False


def traced_functions(
    tree: ast.Module, imports: ImportMap, nodes=None
) -> Dict[ast.FunctionDef, Set[str]]:
    """Every function the module jits/vmaps/pmaps (decorator or wrapper-call
    position) -> its static parameter names. Shared by jit-host-sync and
    obs-emit-in-jit: 'is this body traced?' is one question, answered once
    (:func:`traced_functions_for` memoizes it per module).

    ``nodes`` optionally supplies the module's pre-walked node sequence
    (``SourceModule.walk()``) so this does not re-walk the whole tree."""
    if nodes is None:
        nodes = list(ast.walk(tree))
    by_name: Dict[str, List[ast.FunctionDef]] = {}
    for node in nodes:
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, []).append(node)

    traced: Dict[ast.FunctionDef, Set[str]] = {}

    def mark(fn: ast.FunctionDef, static: Set[str]) -> None:
        traced.setdefault(fn, set()).update(static)

    for node in nodes:
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if _is_jit_expr(dec, imports):
                    mark(node, _static_params(dec, node))
        if isinstance(node, ast.Call) and _is_jit_expr(node.func, imports):
            for arg in node.args:
                for inner in ast.walk(arg):
                    if isinstance(inner, ast.Name) and inner.id in by_name:
                        for fn in by_name[inner.id]:
                            mark(fn, _static_params(node, fn))
    return traced


def traced_functions_for(module) -> Dict[ast.FunctionDef, Set[str]]:
    """Per-module :func:`traced_functions`, built once and memoized on the
    SourceModule (two rules ask the same question of every module)."""
    traced = module.cache.get("traced_functions")
    if traced is None:
        traced = traced_functions(
            module.tree, import_map_for(module), nodes=module.walk()
        )
        module.cache["traced_functions"] = traced
    return traced


def _static_params(dec: ast.AST, fn: ast.FunctionDef) -> Set[str]:
    """Parameter names excluded from tracing by static_argnames/argnums."""
    static: Set[str] = set()
    calls = [dec] if isinstance(dec, ast.Call) else []
    for call in calls:
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                try:
                    val = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                names = [val] if isinstance(val, str) else list(val)
                static.update(str(n) for n in names)
            elif kw.arg == "static_argnums":
                try:
                    val = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                nums = [val] if isinstance(val, int) else list(val)
                params = [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)]
                for i in nums:
                    if isinstance(i, int) and 0 <= i < len(params):
                        static.add(params[i])
    return static


@register
class JitHostSyncRule(Rule):
    name = "jit-host-sync"
    description = (
        "host-sync call (float/int/bool/.item/np.asarray/device_get or Python "
        "branch) on a traced value inside a jit/vmap/pmap-ed function"
    )

    def check(self, module: SourceModule) -> List[Finding]:
        # sound prefilter: a traced function requires one of these tokens
        if not any(t in module.text for t in ("jit", "pmap", "vmap", "vectorize")):
            return []
        imports = import_map_for(module)
        traced_fns = traced_functions_for(module)
        findings: List[Finding] = []
        for fn, static in traced_fns.items():
            findings.extend(self._check_traced_fn(module, imports, fn, static))
        return findings

    # -------------------------------------------------------------- analysis
    def _check_traced_fn(
        self,
        module: SourceModule,
        imports: ImportMap,
        fn: ast.FunctionDef,
        static: Set[str],
    ) -> List[Finding]:
        traced: Set[str] = {
            a.arg
            for a in (
                list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs)
            )
            if a.arg not in static and a.arg not in ("self", "cls")
        }
        if fn.args.vararg is not None:
            traced.add(fn.args.vararg.arg)

        fn_nodes = tuple(module.subtree(fn))

        def refs_traced(node: ast.AST) -> bool:
            return any(
                isinstance(n, ast.Name) and n.id in traced
                for n in module.subtree(node)
            )

        def taint_target(tgt: ast.expr) -> None:
            # a subscript store taints the container, never the index names
            # (`counts[b] = traced` says nothing about `b`)
            while isinstance(tgt, (ast.Subscript, ast.Starred)):
                tgt = tgt.value
            if isinstance(tgt, ast.Name):
                traced.add(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for el in tgt.elts:
                    taint_target(el)

        # two forward passes: assignments referencing traced names taint
        # their targets (handles use-before-def between helpers once)
        for _ in range(2):
            for node in fn_nodes:
                if isinstance(node, ast.Assign) and refs_traced(node.value):
                    for tgt in node.targets:
                        taint_target(tgt)
                elif isinstance(node, ast.AugAssign) and refs_traced(node.value):
                    taint_target(node.target)

        findings: List[Finding] = []

        def flag(node: ast.AST, what: str) -> None:
            findings.append(
                self.finding(
                    module,
                    node,
                    f"{what} on a traced value inside traced function "
                    f"{fn.name!r} forces a host sync (or raises at trace time)",
                )
            )

        for node in fn_nodes:
            if isinstance(node, ast.Call):
                callee = imports.resolve(node.func)
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _CASTS
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in traced
                ):
                    flag(node, f"{node.func.id}()")
                elif (
                    callee is not None
                    and node.args
                    and refs_traced(node.args[0])
                    and (
                        callee == "jax.device_get"
                        or (
                            callee.startswith(("numpy.", "np."))
                            and callee.rsplit(".", 1)[-1] in _NUMPY_SINKS
                        )
                    )
                ):
                    flag(node, callee)
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHOD_SINKS
                    and refs_traced(node.func.value)
                ):
                    flag(node, f".{node.func.attr}()")
            elif isinstance(node, (ast.If, ast.While)):
                # only bare traced names as direct operands: `if x:` /
                # `if x > 0:` are tracer bool-coercions; `if f(x) ...` is
                # left alone (f may be static — shape math, trained_split)
                test = node.test
                operands: List[ast.expr] = [test]
                if isinstance(test, ast.Compare):
                    operands = [test.left, *test.comparators]
                elif isinstance(test, ast.BoolOp):
                    operands = list(test.values)
                elif isinstance(test, ast.UnaryOp):
                    operands = [test.operand]
                if any(
                    isinstance(op, ast.Name) and op.id in traced for op in operands
                ):
                    flag(node, "Python branch")
        return findings
