"""pytest-marker — compile-heavy tests missing the ``slow`` marker.

The fast lane (``pytest -m 'not slow'``, the tier-1 gate) must stay under
control: one unmarked ``pmap`` test or hundred-bracket sweep quietly adds
minutes for every future PR. This rule encodes the repo's marking policy
(``pytest.ini``) as thresholds calibrated to the current suite — every
fast-lane test today sits well under them:

* calls ``jax.pmap`` (multi-device compile: always slow on CPU meshes);
* passes ``n_iterations=N`` with ``N >= 16`` (a bracket per iteration —
  each a compile + full SH ladder);
* passes ``max_budget=B`` with ``B >= 243`` (the eta=3 ladder grows a rung:
  compile-heavier fused sweeps, longer training loops);
* a ``for _ in range(N>=64)`` loop whose body jits.

Only files named ``test_*.py`` are inspected. A ``slow`` marker on the
function, its class, or the module-level ``pytestmark`` clears it.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from hpbandster_tpu.analysis.core import Finding, Rule, SourceModule, register
from hpbandster_tpu.analysis.rules._util import dotted_name

_N_ITERATIONS_MAX = 16
_MAX_BUDGET_MAX = 243
_RANGE_LOOP_MAX = 64


def _has_slow_marker(decorators: List[ast.expr]) -> bool:
    for dec in decorators:
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(node) or ""
        if name.endswith("mark.slow") or name == "slow":
            return True
    return False


def _pytestmark_is_slow(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark" for t in stmt.targets
        ):
            for node in ast.walk(stmt.value):
                if (dotted_name(node) or "").endswith("mark.slow"):
                    return True
    return False


def _const_number(node: ast.expr) -> Optional[float]:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return float(node.value)
    return None


@register
class PytestMarkerRule(Rule):
    name = "pytest-marker"
    description = (
        "test compiles/pmaps or exceeds iteration/budget thresholds but lacks "
        "@pytest.mark.slow"
    )

    def check(self, module: SourceModule) -> List[Finding]:
        if not os.path.basename(module.path).startswith("test_"):
            return []
        if _pytestmark_is_slow(module.tree.body):
            return []
        findings: List[Finding] = []
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                if _has_slow_marker(node.decorator_list) or _pytestmark_is_slow(node.body):
                    continue
                for sub in node.body:
                    self._check_test(module, sub, findings)
            else:
                self._check_test(module, node, findings)
        return findings

    def _check_test(
        self, module: SourceModule, node: ast.stmt, findings: List[Finding]
    ) -> None:
        if not isinstance(node, ast.FunctionDef) or not node.name.startswith("test"):
            return
        if _has_slow_marker(node.decorator_list):
            return
        reason = self._slow_reason(module, node)
        if reason is not None:
            findings.append(
                self.finding(
                    module, node,
                    f"test {node.name!r} {reason} but has no @pytest.mark.slow — "
                    "mark it (or shrink it under the fast-lane thresholds)",
                )
            )

    def _slow_reason(
        self, module: SourceModule, fn: ast.FunctionDef
    ) -> Optional[str]:
        for node in module.subtree(fn):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func) or ""
                if callee in ("jax.pmap", "pmap"):
                    return "calls jax.pmap (multi-device compile)"
                for kw in node.keywords:
                    val = _const_number(kw.value) if kw.arg else None
                    if kw.arg == "n_iterations" and val is not None and val >= _N_ITERATIONS_MAX:
                        return f"runs n_iterations={int(val)} (>= {_N_ITERATIONS_MAX} brackets)"
                    if kw.arg == "max_budget" and val is not None and val >= _MAX_BUDGET_MAX:
                        return f"uses max_budget={val:g} (>= {_MAX_BUDGET_MAX})"
            if isinstance(node, (ast.For, ast.AsyncFor)):
                n = self._range_bound(node.iter)
                if n is not None and n >= _RANGE_LOOP_MAX and self._body_jits(node):
                    return f"jit-compiles inside a range({int(n)}) loop"
        return None

    @staticmethod
    def _range_bound(iter_expr: ast.expr) -> Optional[float]:
        if (
            isinstance(iter_expr, ast.Call)
            and isinstance(iter_expr.func, ast.Name)
            and iter_expr.func.id == "range"
            and iter_expr.args
        ):
            stop = iter_expr.args[1] if len(iter_expr.args) >= 2 else iter_expr.args[0]
            return _const_number(stop)
        return None

    @staticmethod
    def _body_jits(loop: ast.stmt) -> bool:
        for node in ast.walk(loop):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func) or ""
                if callee in ("jax.jit", "jit", "jax.pmap", "pmap"):
                    return True
        return False
