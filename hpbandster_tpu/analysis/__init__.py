"""graftlint — the repo-native static-analysis subsystem.

Usage::

    python -m hpbandster_tpu.analysis [paths...]      # exit 1 on findings

    from hpbandster_tpu.analysis import run, format_report
    findings = run(["hpbandster_tpu", "tests"])

See ``docs/static_analysis.md`` for the rule catalogue, the suppression
syntax, and how to add a rule.
"""

from hpbandster_tpu.analysis.core import (
    DEFAULT_EXCLUDE_DIRS,
    Finding,
    ProjectRule,
    Rule,
    SourceModule,
    all_rules,
    collect_files,
    format_report,
    register,
    run,
)

__all__ = [
    "DEFAULT_EXCLUDE_DIRS",
    "Finding",
    "ProjectRule",
    "Rule",
    "SourceModule",
    "all_rules",
    "collect_files",
    "format_report",
    "register",
    "run",
]
