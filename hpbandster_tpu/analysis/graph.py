"""Whole-program call graph for graftlint's interprocedural rules.

The intraprocedural rule pack (PR 1) answers "is this line wrong given
this module?" — but the bug classes that actually cost review rounds are
cross-module: a helper that host-syncs, called three frames below a
``lax.scan`` body; an RPC issued by a method whose *caller* holds the
dispatcher condition. Answering those needs one structure the per-module
rules cannot build: a project-wide call graph.

This module constructs it from the same memoized ``SourceModule`` walk
the rule pack already uses — stdlib-only, no imports executed, no jax —
so the interprocedural pass stays fast-lane material:

* :func:`load_module` — process-wide ``SourceModule`` cache keyed on
  ``(path, mtime_ns, size)``: the selfcheck's repeated full scans parse
  each file once per process, not once per scan;
* :func:`get_project` — memoized :class:`Project` over a file set: the
  per-module function tables, class/attribute types, alias tables (with
  re-export following through package ``__init__`` modules), and the
  resolved call-site list per function;
* :class:`Project` queries — ``resolve_dotted`` / ``method`` /
  ``calls`` / ``reachable`` / ``traced_roots`` / ``lock_ids`` — the
  primitives the lock-order and trace-escape rules are written against.

Resolution is deliberately *under*-approximate: an edge exists only when
the callee is provable from the AST (local name, import alias, ``self.``
method through the base-class chain, a variable or attribute whose class
is pinned by a visible constructor call, or a ``functools.partial`` over
any of those). Dynamic dispatch through stored callables resolves to
nothing — a missing edge can hide a bug (reviewers still exist) but
never invents one, which is what keeps the interprocedural rules quiet
enough to gate the tree.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from hpbandster_tpu.analysis.core import SourceModule

__all__ = [
    "FunctionInfo",
    "CallSite",
    "LockDecl",
    "Project",
    "load_module",
    "get_project",
    "clear_caches",
]

#: factories whose result is a mutual-exclusion object; the bool marks
#: reentrancy (Condition() defaults to an RLock, so re-entry is legal)
_LOCK_FACTORIES: Dict[str, bool] = {
    "threading.Lock": False,
    "threading.RLock": True,
    "threading.Condition": True,
    "threading.Semaphore": False,
    "threading.BoundedSemaphore": False,
    "multiprocessing.Lock": False,
    "multiprocessing.RLock": True,
}

_MAX_BASE_DEPTH = 8  # base-class chains / re-export chains are short


@dataclasses.dataclass
class FunctionInfo:
    """One function or method, addressable by its dotted qualified name."""

    qname: str  # "pkg.mod.Class.meth" / "pkg.mod.fn" / "pkg.mod.fn.<locals>.g"
    name: str
    module: SourceModule
    module_name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls_qname: Optional[str] = None  # immediate enclosing class, if a method
    #: positional parameter names in call order (posonly + args), self/cls
    #: included — callers map arguments through :meth:`positional_params`
    params: Tuple[str, ...] = ()
    kwonly: Tuple[str, ...] = ()
    has_vararg: bool = False
    has_kwarg: bool = False

    def positional_params(self, bound: bool) -> Tuple[str, ...]:
        """Parameter names positional arguments land on; ``bound`` drops
        the self/cls slot (``obj.m(x)`` style calls)."""
        if bound and self.cls_qname is not None and self.params:
            return self.params[1:]
        return self.params

    def __repr__(self) -> str:  # debugging aid, not part of the contract
        return f"FunctionInfo({self.qname})"


@dataclasses.dataclass
class CallSite:
    """One resolved call edge: ``caller`` invokes ``callee`` at ``line``."""

    caller: str
    callee: "FunctionInfo"
    node: ast.Call
    line: int
    #: True when the receiver is implicit (``self.m()`` / ``obj.m()``) so
    #: positional arguments skip the self slot
    bound: bool = False
    #: True when the edge is a ``functools.partial`` construction, not a
    #: direct invocation — the call may happen later, elsewhere
    via_partial: bool = False
    #: True for constructor edges (``C()`` -> ``C.__init__``)
    is_init: bool = False


@dataclasses.dataclass(frozen=True)
class LockDecl:
    """One mutual-exclusion object the project owns.

    ``lock_id`` is the defining scope's dotted name plus the attribute:
    ``pkg.mod.Class._lock`` for instance locks (one id per *class*, the
    granularity lock-ordering is defined at), ``pkg.mod._LOCK`` for
    module-level locks.
    """

    lock_id: str
    reentrant: bool
    path: str
    line: int


class Project:
    """The whole-program index: modules, functions, classes, call edges."""

    def __init__(self) -> None:
        self.modules: Dict[str, SourceModule] = {}  # path -> module
        self.module_names: Dict[str, str] = {}  # path -> dotted name
        self.path_by_module: Dict[str, str] = {}  # dotted name -> path
        self.functions: Dict[str, FunctionInfo] = {}
        self.fn_by_node: Dict[int, FunctionInfo] = {}
        self.methods: Dict[str, Dict[str, FunctionInfo]] = {}  # cls -> name -> fn
        self.classes: Dict[str, ast.ClassDef] = {}  # cls_qname -> node
        self.class_module: Dict[str, SourceModule] = {}
        self.class_bases: Dict[str, List[str]] = {}  # resolved base qnames
        self.attr_types: Dict[str, Dict[str, str]] = {}  # cls -> attr -> cls
        self.calls: Dict[str, List[CallSite]] = {}  # caller qname -> sites
        self.site_by_node: Dict[int, CallSite] = {}  # id(ast.Call) -> site
        self.locks: Dict[str, LockDecl] = {}  # lock_id -> decl
        #: cls_qname -> attr name -> lock_id (inherited attrs resolve
        #: through bases at query time, see :meth:`lock_for_attr`)
        self.class_locks: Dict[str, Dict[str, str]] = {}
        self.alias_tables: Dict[str, Dict[str, str]] = {}  # path -> alias map
        #: per-function node attribution, filled by the single pass-1 walk
        #: (nested defs own their nodes; absent key == none of that kind)
        self.fn_calls: Dict[str, List[ast.Call]] = {}
        self.fn_assigns: Dict[str, List[ast.Assign]] = {}
        self.fn_has_with: Set[str] = set()
        #: path -> every FunctionDef/Call node in the module (any scope) —
        #: the exact census traced-root discovery scans, so it never
        #: re-walks full trees
        self.scan_nodes: Dict[str, List[ast.AST]] = {}
        #: scratch memos for rules (summary caches live here so they share
        #: the project's lifetime, not a rule instance's)
        self.cache: Dict[str, object] = {}

    # ------------------------------------------------------------ queries
    def resolve_dotted(self, dotted: str) -> Optional[FunctionInfo]:
        """Canonical dotted name -> FunctionInfo, following re-exports
        (``pkg.obs.emit`` lands on ``pkg.obs.events.emit`` when the
        package ``__init__`` imports it)."""
        seen: Set[str] = set()
        while dotted not in self.functions:
            if dotted in seen or len(seen) > _MAX_BASE_DEPTH:
                return None
            seen.add(dotted)
            head, _, attr = dotted.rpartition(".")
            if not head:
                return None
            # ClassName.method spelled through a module alias
            if head in self.classes:
                return self.method(head, attr)
            path = self.path_by_module.get(head)
            if path is None:
                return None
            alias = self.alias_tables.get(path, {}).get(attr)
            if alias is None:
                return None
            dotted = alias
        return self.functions[dotted]

    def resolve_class(self, dotted: str) -> Optional[str]:
        """Canonical dotted name -> class qname, following re-exports."""
        seen: Set[str] = set()
        while dotted not in self.classes:
            if dotted in seen or len(seen) > _MAX_BASE_DEPTH:
                return None
            seen.add(dotted)
            head, _, attr = dotted.rpartition(".")
            if not head:
                return None
            path = self.path_by_module.get(head)
            if path is None:
                return None
            alias = self.alias_tables.get(path, {}).get(attr)
            if alias is None:
                return None
            dotted = alias
        return dotted

    def resolve_class_in(self, dotted: str, module_name: str) -> Optional[str]:
        """:meth:`resolve_class`, with a bare (undotted) name also tried
        as module-local — ``Base`` inside ``m`` resolves to ``m.Base``."""
        found = self.resolve_class(dotted)
        if found is None and "." not in dotted:
            found = self.resolve_class(f"{module_name}.{dotted}")
        return found

    def method(self, cls_qname: str, name: str, _depth: int = 0) -> Optional[FunctionInfo]:
        """Look ``name`` up on ``cls_qname``, walking the base chain."""
        found = self.methods.get(cls_qname, {}).get(name)
        if found is not None or _depth >= _MAX_BASE_DEPTH:
            return found
        for base in self.class_bases.get(cls_qname, ()):
            found = self.method(base, name, _depth + 1)
            if found is not None:
                return found
        return None

    def lock_for_attr(self, cls_qname: str, attr: str, _depth: int = 0) -> Optional[str]:
        """``self.<attr>`` inside ``cls_qname`` -> lock_id, walking bases
        so a lock declared on a base class unifies across subclasses."""
        lock = self.class_locks.get(cls_qname, {}).get(attr)
        if lock is not None or _depth >= _MAX_BASE_DEPTH:
            return lock
        for base in self.class_bases.get(cls_qname, ()):
            lock = self.lock_for_attr(base, attr, _depth + 1)
            if lock is not None:
                return lock
        return None

    def callees(self, qname: str) -> List[CallSite]:
        return self.calls.get(qname, [])

    def reachable(self, roots: Iterable[str], max_depth: int = 32) -> Set[str]:
        """Qnames reachable from ``roots`` over resolved call edges."""
        seen: Set[str] = set()
        frontier = [(q, 0) for q in roots]
        while frontier:
            qname, depth = frontier.pop()
            if qname in seen or depth > max_depth:
                continue
            seen.add(qname)
            for site in self.calls.get(qname, ()):
                frontier.append((site.callee.qname, depth + 1))
        return seen

    def traced_roots(self) -> List[Tuple[FunctionInfo, Set[str]]]:
        """Every project function whose body runs in-trace (jit/vmap/pmap
        decorated or passed into a wrapper / lax combinator), with its
        static parameter names — the entry points of trace-escape."""
        roots = self.cache.get("traced_roots")
        if roots is None:
            from hpbandster_tpu.analysis.rules.jit_purity import traced_functions_for

            roots = []
            for path, module in self.modules.items():
                traced = traced_functions_for(
                    module, nodes=self.scan_nodes.get(path, ())
                )
                for fn_node, static in traced.items():
                    info = self.fn_by_node.get(id(fn_node))
                    if info is not None:
                        roots.append((info, set(static)))
            roots.sort(key=lambda pair: pair[0].qname)
            self.cache["traced_roots"] = roots
        return roots  # type: ignore[return-value]

    def lock_ids(self) -> List[str]:
        return sorted(self.locks)

    def body_nodes(self, info: FunctionInfo) -> Tuple[ast.AST, ...]:
        """``info``'s executable body in preorder: the function's subtree
        minus nested function/class definitions (those execute in their
        own frames — a lock held here is not held there) but including
        lambda bodies (they usually run inline)."""
        memo: Dict[int, Tuple[ast.AST, ...]] = self.cache.setdefault("body_nodes", {})  # type: ignore[assignment]
        cached = memo.get(id(info.node))
        if cached is None:
            out: List[ast.AST] = []
            stack: List[ast.AST] = list(ast.iter_child_nodes(info.node))
            stack.reverse()
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                out.append(node)
                children = list(ast.iter_child_nodes(node))
                children.reverse()
                stack.extend(children)
            cached = tuple(out)
            memo[id(info.node)] = cached
        return cached


# ------------------------------------------------------------ module names
def _module_name_for(path: str) -> str:
    """Dotted module name by walking up through ``__init__.py`` packages;
    a file outside any package is just its stem (fixtures, tmp files)."""
    path = os.path.abspath(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts: List[str] = [] if stem == "__init__" else [stem]
    d = os.path.dirname(path)
    for _ in range(32):
        if not os.path.isfile(os.path.join(d, "__init__.py")):
            break
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(reversed(parts)) or stem


def _alias_table(module: SourceModule, module_name: str) -> Dict[str, str]:
    """Local name -> canonical dotted path, relative imports resolved
    against ``module_name`` (``from . import x`` inside ``pkg.mod`` maps
    ``x`` to ``pkg.x``)."""
    table: Dict[str, str] = {}
    pkg_parts = module_name.split(".")
    # statement-level traversal only: import statements cannot nest inside
    # expressions, so skipping expression subtrees visits ~10% of the
    # nodes a full ast.walk would
    stack: List[ast.stmt] = list(module.tree.body)
    while stack:
        node = stack.pop()
        for field in ("body", "orelse", "finalbody"):
            stack.extend(getattr(node, field, ()))
        for handler in getattr(node, "handlers", ()):
            stack.extend(handler.body)
        if isinstance(node, ast.Import):
            for a in node.names:
                table[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                # pkg.sub.mod, level=1 -> pkg.sub; level=2 -> pkg
                anchor = pkg_parts[: len(pkg_parts) - node.level]
                base = ".".join(anchor + ([node.module] if node.module else []))
            if not base:
                continue
            for a in node.names:
                if a.name != "*":
                    table[a.asname or a.name] = f"{base}.{a.name}"
    return table


def _resolve_alias(table: Dict[str, str], dotted: str) -> str:
    head, _, rest = dotted.partition(".")
    base = table.get(head, head)
    return f"{base}.{rest}" if rest else base


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ----------------------------------------------------------- project build
def _index_module(project: Project, module: SourceModule) -> None:
    """Populate function/class/lock tables for one module (pass 1)."""
    module_name = project.module_names[module.path]
    aliases = project.alias_tables[module.path]

    infos: List[FunctionInfo] = []
    scan = project.scan_nodes.setdefault(module.path, [])

    # hot path of the cold scan: hand-inlined child iteration (no
    # iter_child_nodes generator stack) and exact-type dispatch — AST
    # nodes are never subclassed here, so ``type(x) is C`` is safe
    _Cls, _Fn, _AFn = ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef
    _Call, _Assign, _With, _AWith = ast.Call, ast.Assign, ast.With, ast.AsyncWith
    _AST, _list = ast.AST, list

    def visit(
        node: ast.AST,
        scope: Tuple[str, ...],
        cls: Optional[str],
        fn: Optional[str],
        fn_calls: Optional[List[ast.Call]],
        fn_assigns: Optional[List[ast.Assign]],
        in_function: bool,
        in_class: bool,
    ) -> None:
        d = node.__dict__
        for field in node._fields:
            value = d.get(field)
            for child in value if type(value) is _list else (value,):
                t = type(child)
                if t is _Cls:
                    cls_qname = ".".join(scope + (child.name,))
                    project.classes[cls_qname] = child
                    project.class_module[cls_qname] = module
                    bases: List[str] = []
                    for b in child.bases:
                        name = _dotted(b)
                        if name is not None:
                            resolved = _resolve_alias(aliases, name)
                            if "." not in resolved:
                                # bare, unaliased: a module-local base
                                resolved = f"{module_name}.{resolved}"
                            bases.append(resolved)
                    project.class_bases[cls_qname] = bases
                    visit(
                        child,
                        scope + (child.name,),
                        cls_qname,
                        fn,
                        fn_calls,
                        fn_assigns,
                        False,
                        True,
                    )
                elif t is _Fn or t is _AFn:
                    if t is _Fn:
                        scan.append(child)
                    seg = ("<locals>", child.name) if in_function else (child.name,)
                    qname = ".".join(scope + seg)
                    args = child.args
                    info = FunctionInfo(
                        qname=qname,
                        name=child.name,
                        module=module,
                        module_name=module_name,
                        node=child,
                        cls_qname=cls if in_class else None,
                        params=tuple(
                            a.arg for a in (*args.posonlyargs, *args.args)
                        ),
                        kwonly=tuple(a.arg for a in args.kwonlyargs),
                        has_vararg=args.vararg is not None,
                        has_kwarg=args.kwarg is not None,
                    )
                    project.functions[qname] = info
                    project.fn_by_node[id(child)] = info
                    infos.append(info)
                    if info.cls_qname is not None:
                        project.methods.setdefault(info.cls_qname, {})[
                            child.name
                        ] = info
                    calls: List[ast.Call] = []
                    assigns: List[ast.Assign] = []
                    project.fn_calls[qname] = calls
                    project.fn_assigns[qname] = assigns
                    visit(child, scope + seg, None, qname, calls, assigns, True, False)
                elif isinstance(child, _AST):
                    # per-function node attribution, recorded during THIS
                    # walk so pass 2 and the lock rules never re-traverse
                    if t is _Call:
                        scan.append(child)
                        if fn_calls is not None:
                            fn_calls.append(child)
                    elif fn is not None:
                        if t is _Assign:
                            fn_assigns.append(child)
                        elif t is _With or t is _AWith:
                            project.fn_has_with.add(fn)
                    visit(child, scope, cls, fn, fn_calls, fn_assigns, in_function, in_class)

    visit(module.tree, (module_name,), None, None, None, None, False, False)

    # lock declarations + self-attr constructor types, from the per-method
    # assignment lists the walk above just recorded
    for fn_info in infos:
        cls_qname = fn_info.cls_qname
        if cls_qname is None:
            continue
        for node in project.fn_assigns.get(fn_info.qname, ()):
            if not isinstance(node.value, ast.Call):
                continue
            callee = _dotted(node.value.func)
            resolved = _resolve_alias(aliases, callee) if callee else None
            for tgt in node.targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                if resolved in _LOCK_FACTORIES:
                    lock_id = f"{cls_qname}.{tgt.attr}"
                    project.locks[lock_id] = LockDecl(
                        lock_id=lock_id,
                        reentrant=_condition_reentrant(node.value, aliases)
                        if resolved == "threading.Condition"
                        else _LOCK_FACTORIES[resolved],
                        path=module.path,
                        line=node.lineno,
                    )
                    project.class_locks.setdefault(cls_qname, {})[tgt.attr] = lock_id
                elif resolved is not None:
                    # remember `self.x = ClassName(...)` receiver types for
                    # pass 2 (resolved lazily — the class may live anywhere)
                    project.attr_types.setdefault(cls_qname, {}).setdefault(
                        tgt.attr, resolved
                    )

    # module-level locks: NAME = threading.Lock()
    for node in ast.iter_child_nodes(module.tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        callee = _dotted(node.value.func)
        resolved = _resolve_alias(aliases, callee) if callee else None
        if resolved not in _LOCK_FACTORIES:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                lock_id = f"{module_name}.{tgt.id}"
                project.locks[lock_id] = LockDecl(
                    lock_id=lock_id,
                    reentrant=_condition_reentrant(node.value, aliases)
                    if resolved == "threading.Condition"
                    else _LOCK_FACTORIES[resolved],
                    path=module.path,
                    line=node.lineno,
                )


def _condition_reentrant(call: ast.Call, aliases: Dict[str, str]) -> bool:
    """``Condition()`` wraps an RLock (reentrant) unless explicitly handed
    a non-reentrant lock: ``Condition(threading.Lock())``."""
    if not call.args:
        return True
    arg = call.args[0]
    if isinstance(arg, ast.Call):
        inner = _dotted(arg.func)
        resolved = _resolve_alias(aliases, inner) if inner else None
        if resolved in _LOCK_FACTORIES:
            return _LOCK_FACTORIES[resolved]
    return True


def _extract_calls(project: Project, info: FunctionInfo) -> List[CallSite]:
    """Resolve every call in ``info``'s body to project functions (pass 2)."""
    module = info.module
    aliases = project.alias_tables[module.path]

    # local constructor types: `v = ClassName(...)` pins v's class
    var_types: Dict[str, str] = {}
    for node in project.fn_assigns.get(info.qname, ()):
        if (
            isinstance(node.value, ast.Call)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            callee = _dotted(node.value.func)
            if callee is not None:
                cls = project.resolve_class_in(
                    _resolve_alias(aliases, callee), info.module_name
                )
                if cls is not None:
                    var_types[node.targets[0].id] = cls

    # nested defs of this function are callable by bare name in its body
    local_defs: Dict[str, FunctionInfo] = {}
    for child in ast.iter_child_nodes(info.node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested = project.fn_by_node.get(id(child))
            if nested is not None:
                local_defs[nested.name] = nested

    # enclosing-scope locals: the jit-factory idiom defines sibling
    # helpers next to the traced closure (`make_*_fn` defines
    # `run_bracket` AND `sweep`; `sweep` calls `run_bracket`), so a bare
    # name also resolves against each enclosing function's locals,
    # innermost first
    enclosing_scopes: List[str] = []
    scope = info.qname
    while ".<locals>." in scope:
        scope = scope.rsplit(".<locals>.", 1)[0]
        enclosing_scopes.append(scope)

    def receiver_class(expr: ast.AST, depth: int = 0) -> Optional[str]:
        if depth > 4:
            return None
        if isinstance(expr, ast.Name):
            if expr.id in ("self", "cls") and info.cls_qname is not None:
                return info.cls_qname
            return var_types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = receiver_class(expr.value, depth + 1)
            if base is not None:
                dotted = self_attr_type(base, expr.attr)
                if dotted is not None:
                    return dotted
            return None
        if isinstance(expr, ast.Call):
            name = _dotted(expr.func)
            if name is not None:
                return project.resolve_class_in(
                    _resolve_alias(aliases, name), info.module_name
                )
        return None

    def self_attr_type(cls_qname: str, attr: str, _depth: int = 0) -> Optional[str]:
        if _depth > _MAX_BASE_DEPTH:
            return None
        dotted = project.attr_types.get(cls_qname, {}).get(attr)
        if dotted is not None:
            # stored unresolved at index time; canonicalize through the
            # DEFINING module's aliases (bare names are module-local there)
            defining = project.class_module.get(cls_qname)
            table = project.alias_tables.get(defining.path, {}) if defining else {}
            defining_mod = (
                project.module_names.get(defining.path, "") if defining else ""
            )
            return project.resolve_class_in(
                _resolve_alias(table, dotted), defining_mod
            )
        for base in project.class_bases.get(cls_qname, ()):
            found = self_attr_type(base, attr, _depth + 1)
            if found is not None:
                return found
        return None

    def resolve_callable(func: ast.AST) -> Tuple[Optional[FunctionInfo], bool, bool]:
        """-> (callee, bound, is_init); bound means the receiver fills the
        self slot."""
        if isinstance(func, ast.Name):
            if func.id in local_defs:
                return local_defs[func.id], False, False
            for enclosing in enclosing_scopes:
                sibling = project.functions.get(
                    f"{enclosing}.<locals>.{func.id}"
                )
                if sibling is not None:
                    return sibling, False, False
            mod_level = project.functions.get(f"{info.module_name}.{func.id}")
            if mod_level is not None:
                return mod_level, False, False
            resolved = _resolve_alias(aliases, func.id)
            cls = project.resolve_class_in(resolved, info.module_name)
            if cls is not None:
                ctor = project.method(cls, "__init__")
                return ctor, True, True
            return project.resolve_dotted(resolved), False, False
        if isinstance(func, ast.Attribute):
            rcls = receiver_class(func.value)
            if rcls is not None:
                return project.method(rcls, func.attr), True, False
            name = _dotted(func)
            if name is not None:
                resolved = _resolve_alias(aliases, name)
                cls = project.resolve_class(resolved)
                if cls is not None:
                    ctor = project.method(cls, "__init__")
                    return ctor, True, True
                return project.resolve_dotted(resolved), False, False
        return None, False, False

    sites: List[CallSite] = []
    for node in project.fn_calls.get(info.qname, ()):
        callee, bound, is_init = resolve_callable(node.func)
        if callee is not None:
            sites.append(
                CallSite(
                    caller=info.qname,
                    callee=callee,
                    node=node,
                    line=node.lineno,
                    bound=bound,
                    is_init=is_init,
                )
            )
            continue
        # functools.partial(f, ...): the partial is (almost always) called
        # later — record the edge at construction, flagged via_partial
        fname = _dotted(node.func)
        if fname is not None and _resolve_alias(aliases, fname) in (
            "functools.partial",
            "partial",
        ):
            if node.args:
                target, bound, is_init = resolve_callable(node.args[0])
                if target is not None and not is_init:
                    sites.append(
                        CallSite(
                            caller=info.qname,
                            callee=target,
                            node=node,
                            line=node.lineno,
                            bound=bound,
                            via_partial=True,
                        )
                    )
    return sites


# ------------------------------------------------------------------ caches
_MODULE_CACHE: Dict[str, Tuple[Tuple[int, int], SourceModule]] = {}
_PROJECT_CACHE: Dict[Tuple[Tuple[str, int, int], ...], Project] = {}
_CACHE_LIMIT = 4096  # tmp-file churn in long pytest runs must stay bounded


def load_module(path: str) -> SourceModule:
    """Parse ``path`` into a :class:`SourceModule`, memoized process-wide
    on ``(mtime_ns, size)`` so repeated scans share one parse (and every
    per-module rule memo riding ``SourceModule.cache``)."""
    st = os.stat(path)
    key = (st.st_mtime_ns, st.st_size)
    hit = _MODULE_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    module = SourceModule(path, text)
    if len(_MODULE_CACHE) >= _CACHE_LIMIT:
        _MODULE_CACHE.clear()
    _MODULE_CACHE[path] = (key, module)
    return module


def get_project(files: Sequence[str]) -> Project:
    """Build (or fetch) the :class:`Project` over ``files``. The cache key
    is the file set plus each file's ``(mtime_ns, size)``, so an edited
    file invalidates the graph while the selfcheck's repeated scans hit."""
    entries: List[Tuple[str, int, int]] = []
    for path in sorted(set(os.path.abspath(p) for p in files)):
        try:
            st = os.stat(path)
        except OSError:
            continue
        entries.append((path, st.st_mtime_ns, st.st_size))
    key = tuple(entries)
    project = _PROJECT_CACHE.get(key)
    if project is not None:
        return project

    project = Project()
    for path, _, _ in entries:
        try:
            module = load_module(path)
        except (OSError, SyntaxError, ValueError):
            continue  # the runner reports parse errors; the graph skips them
        project.modules[path] = module
        name = _module_name_for(path)
        project.module_names[path] = name
        project.path_by_module.setdefault(name, path)
        table = _alias_table(module, name)
        project.alias_tables[path] = table
        if "import_map" not in module.cache:
            # the alias table IS an import map (plus resolved relative
            # imports); seeding the per-module memo here spares every rule
            # a redundant full-tree walk per module
            from hpbandster_tpu.analysis.rules._util import ImportMap

            imports = ImportMap.__new__(ImportMap)
            imports.aliases = dict(table)
            module.cache["import_map"] = imports
    for module in project.modules.values():
        _index_module(project, module)
    for info in list(project.functions.values()):
        sites = _extract_calls(project, info)
        project.calls[info.qname] = sites
        for site in sites:
            project.site_by_node[id(site.node)] = site

    if len(_PROJECT_CACHE) >= 64:
        _PROJECT_CACHE.clear()
    _PROJECT_CACHE[key] = project
    return project


def clear_caches() -> None:
    """Drop the process-wide module and project caches (perf tests use
    this to measure a genuinely cold scan)."""
    _MODULE_CACHE.clear()
    _PROJECT_CACHE.clear()
