"""Fused successive-halving bracket: all stages in ONE device computation.

The north-star capability (SURVEY.md §0, BASELINE.json): "per-bracket
allocation decided on-device". Stage evaluations, the top-k promotion
decision, and the gather of surviving configs all happen inside a single
jitted function — zero host round-trips between stages, so a whole bracket
is one dispatch regardless of depth.

Shapes are fully static: ``num_configs``/``budgets`` are Python tuples
closed over at trace time, each stage's survivor batch has its statically
known size, and budget-dependent training loops see a *concrete* budget
(enabling static trip counts). Crashed configs surface as NaN losses and
rank behind every clean loss in the on-device promotion (but ahead of
mesh-padding rows), index-stably — matching ``sh_promotion_mask``.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hpbandster_tpu.obs.runtime import note_transfer, tracked_jit

__all__ = ["StatefulEval", "fused_sh_bracket", "make_fused_bracket_fn",
           "shard_rows", "stage_telemetry"]

#: crashed (NaN) losses map here for ranking: behind any real loss, ahead of
#: the +inf padding rows, ties broken index-stably by top_k — the same
#: ordering sh_promotion_mask's argsort produces host-side. numpy, NOT a
#: jnp scalar: module-level device-array creation would initialize the jax
#: backend at import time (see workloads/toys.py).
_CRASH_RANK = np.float32(3.0e38)


def shard_rows(x: jax.Array, mesh, axis: str = "config") -> jax.Array:
    """Constrain a leading batch dim to stay sharded over ``axis``.

    Identity on values (a sharding constraint never changes bits) and a
    no-op without a mesh or when the row count does not divide evenly —
    XLA is then free to choose its own layout for that (small) stage.
    Inserted between the stages of a sharded fused bracket so the config
    axis stays distributed for the whole rung ladder: survivor gathers and
    the rank reduction become ICI collectives instead of XLA deciding to
    home the batch on one device.
    """
    if mesh is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    from hpbandster_tpu.parallel.mesh import is_multiprocess_mesh, shard_count

    m = shard_count(mesh, axis)
    if m <= 1 or x.shape[0] % m != 0:
        return x
    if is_multiprocess_mesh(mesh) and jax.default_backend() == "cpu":
        # CPU PJRT does not implement multiprocess computations at all
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"), so forcing cross-process layouts here can only add
        # failure modes — the DCN-on-CPU test pods keep XLA's own layout
        # choice, the pre-constraint behavior. Real pods (TPU/GPU) keep
        # the constraints: that is where the ICI/DCN reduction lives.
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(axis))
    )


def stage_telemetry(
    losses: jax.Array, edges
) -> Tuple[jax.Array, jax.Array]:
    """Jittable one-stage telemetry: ``(histogram i32[len(edges)+1],
    crash_count i32[])`` over one rung's losses — the device half of the
    metrics plane (``obs/device_metrics.py`` owns the schema; ``edges``
    are its ``bin_edges()``, the ONE definition host and device bin
    against).

    NaN (crashed) losses are excluded from the histogram and counted in
    the crash counter; +/-inf are finite-for-binning (they land in the
    overflow/underflow bins — a diverged loss is still a loss). A loss
    equal to a bin's upper bound lands IN that bin (<= against the upper
    bound, matching ``obs.metrics.Histogram``'s ``bisect_left``).

    Deliberately scatter-free: the histogram is a cumulative
    ``count(loss <= edge)`` compare-matrix reduced over the loss axis,
    then adjacent-differenced — XLA lowers it to vectorized partial sums
    (and, when the losses are sharded over the config axis, to per-shard
    partials + one tiny cross-shard reduction), where a scatter-add
    lowers to a serial loop (measured ~2x slower on CPU and hostile to
    sharding). Output shape is fixed by the bin count alone, so
    accumulating this per rung keeps the telemetry payload independent
    of the config count — the resident tier's flat-host-link contract.
    """
    edges = jnp.asarray(edges, jnp.float32)
    losses = losses.astype(jnp.float32)
    crashed = jnp.isnan(losses)
    w = jnp.where(crashed, 0, 1).astype(jnp.int32)
    # NaN compares false against every edge, but the weight mask is the
    # authoritative exclusion (it also keeps the total-count arithmetic
    # honest for the overflow bin)
    le = (losses[:, None] <= edges[None, :]).astype(jnp.int32) * w[:, None]
    cum = jnp.sum(le, axis=0)  # finite losses at or below each edge
    total = jnp.sum(w)
    hist = jnp.concatenate(
        [cum[:1], jnp.diff(cum), (total - cum[-1])[None]]
    )
    return hist, jnp.sum(crashed).astype(jnp.int32)


class StatefulEval(NamedTuple):
    """Stateful-evaluation seam beside ``eval_fn``: real-model training
    whose live state (weight/optimizer pytrees) threads through the rung
    ladder so promoted configs CONTINUE training instead of restarting.

    ``init_fn(vectors f32[n, d]) -> state`` builds the rung's ensemble:
    a pytree whose every leaf carries a leading config axis of size ``n``
    (one lane per config row, padding rows included).

    ``step_fn(state, vectors f32[k, d], budget, prev_budget) ->
    (state, losses f32[k])`` advances each lane from cumulative budget
    ``prev_budget`` to ``budget`` (both CONCRETE floats — static trip
    counts for the inner ``lax.scan``) and returns the lanes' current
    validation losses. Lane ``i`` of the state corresponds to row ``i``
    of ``vectors``; a crashed (diverged) lane reports NaN and must not
    influence any other lane — the bracket ranks it with the shared
    crash key, exactly like the stateless path.

    The bracket gathers surviving state leaves with the SAME top-k
    indices the rung ranked by (``jax.tree.map(lambda l: l[top], state)``),
    so promotion selects among live training states — warm continuation.
    Evicted lanes simply drop out of the gather; the next bracket's
    ``init_fn`` re-creates fresh lanes in-trace. See
    ``workloads/ensemble.py`` for the vmapped-SGD reference
    implementation and ``docs/workloads.md`` for the protocol contract.
    """

    init_fn: Callable[[jax.Array], Any]
    step_fn: Callable[[Any, jax.Array, float, float], Tuple[Any, jax.Array]]


def _shard_state(state, mesh, axis: str):
    """Naive per-leaf sharding of an ensemble state: every leaf's leading
    config axis stays distributed over ``axis`` (the SNIPPETS
    ``shard_params`` path — shard when divisible, else leave XLA free).
    A 2-D model x config layout via ``match_partition_rules``-style regex
    trees is deliberately NOT wired here yet (reserved for a real
    model-parallel mesh); one axis is the honest current scope."""
    if mesh is None:
        return state
    return jax.tree.map(lambda leaf: shard_rows(leaf, mesh, axis), state)


def fused_sh_bracket(
    eval_fn: Callable[[jax.Array, float], jax.Array],
    vectors: jax.Array,
    num_configs: Sequence[int],
    budgets: Sequence[float],
    rank_fn: Callable[[jax.Array, jax.Array, float], jax.Array] = None,
    mesh=None,
    axis: str = "config",
    stateful: "StatefulEval" = None,
    return_final_state: bool = False,
) -> List[Tuple[jax.Array, jax.Array]]:
    """Trace one whole bracket. Returns per-stage ``(indices, losses)``
    where ``indices`` index the original (unpadded) stage-0 rows.

    ``vectors`` may carry extra padding rows beyond ``num_configs[0]`` (for
    mesh divisibility); they are evaluated but can never be promoted. Must
    run under ``jit`` (see :func:`make_fused_bracket_fn`).

    ``rank_fn(budgets_so_far f32[s+1], history f32[n_cur, s+1],
    final_budget) -> scores f32[n_cur]`` overrides the promotion scores
    (lower = better; NaN = never promote). Default: the current stage's raw
    losses — plain successive halving. ``FusedH2BO`` passes the power-law
    learning-curve extrapolation here.

    ``mesh``/``axis`` pin each stage's survivor batch to stay sharded over
    the config axis (:func:`shard_rows`) — bit-identical results (a
    constraint never changes values; a 1-device mesh is the unsharded
    program), but the rung reduction and survivor gather lower to ICI
    collectives instead of a single-device round-trip.

    ``stateful`` (a :class:`StatefulEval`, exclusive with ``eval_fn``)
    switches every stage to the warm-continuation protocol: stage 0 runs
    ``init_fn`` then ``step_fn(state, vecs, budgets[0], 0.0)``; stage ``s``
    gathers the surviving state leaves by the promotion's ``top`` indices
    and runs ``step_fn(state, vecs, budgets[s], budgets[s-1])`` — each lane
    trains only the INCREMENTAL budget, carrying its weights across rungs.
    State leaves keep the per-stage sharding constraints the loss batches
    get. ``return_final_state=True`` additionally returns the last stage's
    surviving state (``(stages, state)``) for callers that extract trained
    weights — the fused sweep itself leaves it device-internal.
    """
    if (eval_fn is None) == (stateful is None):
        raise ValueError(
            "provide exactly one evaluation seam: eval_fn (stateless) or "
            "stateful (StatefulEval warm continuation)"
        )
    if return_final_state and stateful is None:
        raise ValueError("return_final_state=True requires stateful")
    n0 = int(num_configs[0])
    n_rows = vectors.shape[0]
    if n_rows < n0:
        raise ValueError(f"need >= {n0} stage-0 vectors, got {n_rows}")

    def eval_stage(vecs: jax.Array, budget: float) -> jax.Array:
        return jax.vmap(lambda v: eval_fn(v, budget))(vecs).astype(jnp.float32)

    def rank_key(scores: jax.Array, is_pad: jax.Array) -> jax.Array:
        key = jnp.where(jnp.isnan(scores), _CRASH_RANK, scores)
        return jnp.where(is_pad, jnp.inf, key)

    def scores_for(history_cols: List[jax.Array], s: int) -> jax.Array:
        """Promotion scores after stage ``s`` from the survivors' loss
        history ``[n_cur, s+1]``; crashed (NaN-loss) configs stay NaN."""
        hist = jnp.stack(history_cols, axis=1)
        if rank_fn is None or s == 0:
            scores = hist[:, -1]
        else:
            scores = rank_fn(
                jnp.asarray(budgets[: s + 1], jnp.float32), hist,
                float(budgets[-1]),
            )
            # host H2BO parity (optimizers/h2bo.py): where extrapolation is
            # undefined (e.g. an earlier-stage crash left NaN in the
            # history), fall back to the raw current-stage loss ...
            scores = jnp.where(jnp.isnan(scores), hist[:, -1], scores)
            # ... and a crashed CURRENT stage dominates any extrapolation
            scores = jnp.where(jnp.isnan(hist[:, -1]), jnp.nan, scores)
        return scores

    vectors = shard_rows(vectors, mesh, axis)
    state = None
    if stateful is not None:
        # one lane per row (padding rows train too — they can never be
        # promoted, so their lanes are dead weight the mesh alignment pays)
        state = _shard_state(stateful.init_fn(vectors), mesh, axis)
        state, losses0 = stateful.step_fn(
            state, vectors, float(budgets[0]), 0.0
        )
        losses0 = losses0.astype(jnp.float32)
    else:
        losses0 = eval_stage(vectors, float(budgets[0]))
    cur_idx = jnp.arange(n_rows, dtype=jnp.int32)
    history = [losses0]  # per-stage losses of the CURRENT survivor set
    cur_key = rank_key(scores_for(history, 0), cur_idx >= n0)
    out = [(jnp.arange(n0, dtype=jnp.int32), losses0[:n0])]

    for s in range(1, len(num_configs)):
        k = int(num_configs[s])
        _, top = jax.lax.top_k(-cur_key, k)
        top = jnp.sort(top)  # preserve original ordering among survivors
        sel_idx = cur_idx[top]
        sel_vecs = shard_rows(vectors[sel_idx], mesh, axis)
        if stateful is not None:
            # warm continuation: gather the SURVIVING lanes' live state by
            # the same local top-k indices the rank just promoted, then
            # train only the incremental budget from where they left off —
            # evicted lanes simply drop out of the gather
            state = _shard_state(
                jax.tree.map(lambda leaf: leaf[top], state), mesh, axis
            )
            state, losses_s = stateful.step_fn(
                state, sel_vecs, float(budgets[s]), float(budgets[s - 1])
            )
            losses_s = losses_s.astype(jnp.float32)
        else:
            losses_s = eval_stage(sel_vecs, float(budgets[s]))
        cur_idx = sel_idx
        history = [col[top] for col in history] + [losses_s]
        cur_key = rank_key(
            scores_for(history, s), jnp.zeros_like(sel_idx, dtype=bool)
        )
        out.append((cur_idx, losses_s))
    if return_final_state:
        return out, state
    return out


def _pack_stages(stages):
    """Concatenate per-stage (idx, losses) into two flat arrays — a single
    pair of device->host transfers instead of two per stage (the transfer
    count, not bytes, dominates on high-latency links)."""
    return (
        jnp.concatenate([s[0] for s in stages]),
        jnp.concatenate([s[1] for s in stages]),
    )


def _unpack_stages(packed, num_configs):
    # one device_get over the pair: both transfers issue together instead of
    # the second blocking behind the first (round-trips dominate on
    # high-latency links)
    idx_flat, loss_flat = jax.device_get(tuple(packed))
    note_transfer("d2h", idx_flat.nbytes + loss_flat.nbytes, buffers=2)
    out, off = [], 0
    for k in num_configs:
        out.append((idx_flat[off:off + k], loss_flat[off:off + k]))
        off += k
    return out


#: process-wide compiled-bracket cache: optimizer/executor instances come
#: and go (warmups, repeated runs), but a (objective, bracket shape, mesh)
#: combination should compile exactly once per process. Bounded so misses
#: from throwaway closures cannot pin datasets/executables forever.
from hpbandster_tpu.utils.lru import LRUCache as _LRUCache

_FUSED_FN_CACHE: _LRUCache = _LRUCache(maxsize=64)


def make_fused_bracket_fn(
    eval_fn: Callable[[jax.Array, float], jax.Array],
    num_configs: Sequence[int],
    budgets: Sequence[float],
    mesh=None,
    axis: str = "config",
):
    """Compile a fused-bracket runner for one bracket shape.

    Returns ``fn(vectors[n0, d]) -> [(indices, losses), ...]``. With a mesh,
    the stage-0 batch is padded to the mesh size and sharded over ``axis``;
    XLA inserts the all-gathers the cross-shard top-k needs.
    """
    import numpy as np

    num_configs = tuple(int(n) for n in num_configs)
    budgets = tuple(float(b) for b in budgets)
    cache_key = (eval_fn, num_configs, budgets, mesh, axis)
    cached = _FUSED_FN_CACHE.get(cache_key)
    if cached is not None:
        return cached
    n0 = num_configs[0]

    def bracket(vectors: jax.Array):
        return _pack_stages(
            fused_sh_bracket(
                eval_fn, vectors, num_configs, budgets, mesh=mesh, axis=axis
            )
        )

    # donation contract (docs/perf_notes.md): the packed (idx, loss)
    # outputs cannot alias the [n0, d] vectors input, so donating it would
    # be a warning-only no-op — declined explicitly. The state-threading
    # donation lives where an alias exists (ops/sweep.py return_state).
    if mesh is None:
        jitted_plain = tracked_jit(
            bracket, name="fused_bracket", donate_argnums=()
        )

        def dispatch(vectors):
            """Launch the bracket; returns packed DEVICE arrays without
            blocking — callers may overlap several brackets before fetching."""
            note_transfer("h2d", int(getattr(vectors, "nbytes", 0)))
            return jitted_plain(vectors)

    else:
        from jax.sharding import NamedSharding, PartitionSpec

        m = int(np.prod(list(mesh.shape.values())))
        n_pad = ((n0 + m - 1) // m) * m
        shard = NamedSharding(mesh, PartitionSpec(axis))
        jitted = tracked_jit(
            bracket, name="fused_bracket_sharded", in_shardings=(shard,),
            donate_argnums=(),
        )

        def dispatch(vectors):
            vectors = np.asarray(vectors, np.float32)
            if vectors.shape[0] != n0:
                raise ValueError(
                    f"expected {n0} stage-0 vectors, got {vectors.shape[0]}"
                )
            if n_pad != n0:
                vectors = np.concatenate(
                    [vectors, np.zeros((n_pad - n0, vectors.shape[1]), np.float32)]
                )
            note_transfer("h2d", vectors.nbytes)
            from hpbandster_tpu.parallel.mesh import is_multiprocess_mesh

            if is_multiprocess_mesh(mesh):
                # multiprocess meshes reject raw numpy against a sharded
                # in_sharding — build the global array explicitly (every
                # rank holds identical rows), like _BucketRunner.dispatch
                host = vectors
                vectors = jax.make_array_from_callback(
                    host.shape, shard, lambda idx: host[idx]
                )
            return jitted(vectors)

    def runner(vectors):
        return _unpack_stages(dispatch(vectors), num_configs)

    runner.dispatch = dispatch
    _FUSED_FN_CACHE[cache_key] = runner
    return runner
