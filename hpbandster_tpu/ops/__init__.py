"""Pure jittable array kernels: bracket math and the BOHB KDE model."""

from hpbandster_tpu.ops.bracket import (  # noqa: F401
    BracketPlan,
    budget_ladder,
    hyperband_bracket,
    hyperband_schedule,
    max_sh_iterations,
    pareto_promotion_mask,
    pareto_promotion_mask_np,
    pareto_rank,
    pareto_rank_np,
    sh_promotion_mask,
    sh_promotion_mask_np,
    sh_resample_mask,
)
from hpbandster_tpu.ops.buckets import (  # noqa: F401
    BucketPlan,
    BucketSet,
    build_bucket_set,
    make_bucketed_bracket_fn,
    precompile_buckets,
)
from hpbandster_tpu.ops.kde import (  # noqa: F401
    KDE,
    LOG_PDF_FLOOR,
    fit_kde_pair_masked,
    kde_logpdf,
    normal_reference_bandwidths,
    propose,
    propose_batch,
    propose_batch_seeded,
    propose_batch_seeded_scored,
    refit_propose_batch_seeded,
    sample_around,
)
