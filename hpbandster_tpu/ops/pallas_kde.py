"""Pallas TPU kernel for the BOHB acquisition scorer.

The proposal hot loop scores ``n_configs x num_samples`` candidates against
two mixed-type KDEs (good/bad) — for a 128-proposal stage with the default
64 samples that is ~8k candidates x 2 KDEs x up to 256 observations x d
dims of product-kernel work plus two logsumexps. This kernel fuses the
whole thing: one VMEM-resident pass per candidate tile computes both
mixture log-densities dim-by-dim (Gaussian / Aitchison–Aitken /
Wang–van Ryzin selected per dim, matching ``ops.kde``) and emits the
floored acquisition score ``max(lg, F) - max(lb, F)`` directly.

Layout notes (see /opt/skills/guides/pallas_guide.md):
* candidates tile over the grid, 128 rows per program;
* observation matrices are passed TRANSPOSED (``[d, n_obs]``) so each dim
  is one lane-aligned row broadcast against the candidate column;
* the dim loop is a static Python unroll (d is small in HPO spaces);
* dims are padded to the 128-lane width with vartype code 3 = "inert"
  (zero log-kernel contribution), observations with mask 0.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hpbandster_tpu.ops.kde import KDE, LOG_PDF_FLOOR

__all__ = [
    "pallas_score_candidates",
    "pallas_score_candidates_traced",
    "pallas_propose_batch",
    "pallas_propose_batch_seeded",
    "pallas_refit_propose_batch_seeded",
    "pallas_normal_reference_bandwidths",
    "pallas_available",
]

_LOG_SQRT_2PI = 0.5 * math.log(2.0 * math.pi)
_TILE_S = 128
_LANE = 128


def pallas_available() -> bool:
    """Pallas TPU lowering requires a TPU-family backend."""
    try:
        platform = jax.devices()[0].platform.lower()
    # capability probe: ANY failure (no backend, uninitialized runtime)
    # means "not available", and the caller falls back to the XLA scorer
    except Exception:  # graftlint: disable=swallowed-exception
        return False
    return platform in ("tpu", "axon")


def _pad_to(x: np.ndarray, shape: Tuple[int, ...], fill: float) -> np.ndarray:
    out = np.full(shape, fill, dtype=np.float32)
    out[tuple(slice(0, s) for s in x.shape)] = x
    return out


def _score_kernel(
    d_actual: int,
    cand_ref,
    goodT_ref,
    gmask_ref,
    gbw_ref,
    badT_ref,
    bmask_ref,
    bbw_ref,
    vt_ref,
    card_ref,
    out_ref,
):
    ts = cand_ref.shape[0]

    def mixture_logpdf(dataT_ref, mask_ref, bw_ref):
        n = dataT_ref.shape[1]
        acc = jnp.zeros((ts, n), jnp.float32)
        for j in range(d_actual):  # static unroll over real dims
            x = cand_ref[:, j:j + 1]  # [TS, 1]
            mu = dataT_ref[j:j + 1, :]  # [1, N]
            bw = jnp.maximum(bw_ref[0, j], 1e-10)
            vt = vt_ref[0, j]
            km1 = jnp.maximum(card_ref[0, j] - 1.0, 1.0)
            diff = x - mu  # [TS, N]

            log_c = -0.5 * jnp.square(diff / bw) - jnp.log(bw) - _LOG_SQRT_2PI
            same = jnp.square(diff) < 0.25
            lam = jnp.clip(bw, 1e-10, 1.0 - 1e-7)
            log_u = jnp.where(
                same, jnp.log1p(-lam), jnp.log(lam) - jnp.log(km1)
            )
            log_o = jnp.where(
                same,
                jnp.log1p(-lam),
                math.log(0.5) + jnp.log1p(-lam) + jnp.abs(diff) * jnp.log(lam),
            )
            term = jnp.where(
                vt == 0.0,
                log_c,
                jnp.where(vt == 1.0, log_u, jnp.where(vt == 2.0, log_o, 0.0)),
            )
            acc = acc + term
        log_w = jnp.where(mask_ref[0:1, :] > 0.0, 0.0, -jnp.inf)
        ll = acc + log_w
        m = jnp.max(ll, axis=1, keepdims=True)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        s = jnp.sum(jnp.exp(ll - m_safe), axis=1, keepdims=True)
        n_eff = jnp.maximum(jnp.sum(mask_ref[:]), 1.0)
        return m_safe + jnp.log(jnp.maximum(s, 1e-38)) - jnp.log(n_eff)

    lg = mixture_logpdf(goodT_ref, gmask_ref, gbw_ref)
    lb = mixture_logpdf(badT_ref, bmask_ref, bbw_ref)
    out_ref[:] = jnp.maximum(lg, LOG_PDF_FLOOR) - jnp.maximum(lb, LOG_PDF_FLOOR)


@functools.partial(
    jax.jit, static_argnames=("d_actual", "interpret")
)
def _score_padded(
    cands,  # [S_pad, D_pad]
    goodT,  # [D_pad, Ng_pad]
    gmask,  # [1, Ng_pad]
    gbw,    # [1, D_pad]
    badT,
    bmask,
    bbw,
    vt,     # [1, D_pad] float codes (3.0 = inert pad dim)
    cards,  # [1, D_pad]
    d_actual: int,
    interpret: bool,
):
    from jax.experimental import pallas as pl

    try:
        from jax.experimental.pallas import tpu as pltpu

        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = None

    s_pad, d_pad = cands.shape
    grid = (s_pad // _TILE_S,)

    def spec(shape, index_map):
        if vmem is None:
            return pl.BlockSpec(shape, index_map)
        return pl.BlockSpec(shape, index_map, memory_space=vmem)

    full = lambda arr: spec(arr.shape, lambda i: (0, 0))  # noqa: E731

    return pl.pallas_call(
        functools.partial(_score_kernel, d_actual),
        out_shape=jax.ShapeDtypeStruct((s_pad, 1), jnp.float32),
        grid=grid,
        in_specs=[
            spec((_TILE_S, d_pad), lambda i: (i, 0)),
            full(goodT),
            full(gmask),
            full(gbw),
            full(badT),
            full(bmask),
            full(bbw),
            full(vt),
            full(cards),
        ],
        out_specs=spec((_TILE_S, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(cands, goodT, gmask, gbw, badT, bmask, bbw, vt, cards)


def pallas_score_candidates_traced(
    cands: jax.Array,
    good: KDE,
    bad: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """Trace-safe twin of :func:`pallas_score_candidates`: all padding is
    jnp (static shapes), so the scorer can live INSIDE a larger jitted
    program — e.g. the fused whole-sweep (``ops/sweep.py``)."""
    cands = cands.astype(jnp.float32)
    s, d = cands.shape
    s_pad = ((s + _TILE_S - 1) // _TILE_S) * _TILE_S
    d_pad = _LANE

    def prep(kde: KDE):
        data = kde.data.astype(jnp.float32)
        n = data.shape[0]
        n_pad = ((n + _LANE - 1) // _LANE) * _LANE
        dataT = jnp.zeros((d_pad, n_pad), jnp.float32).at[:d, :n].set(data.T)
        mask2 = jnp.zeros((1, n_pad), jnp.float32).at[0, :n].set(
            kde.mask.astype(jnp.float32)
        )
        bw2 = jnp.ones((1, d_pad), jnp.float32).at[0, :d].set(
            kde.bw.astype(jnp.float32)
        )
        return dataT, mask2, bw2

    goodT, gmask, gbw = prep(good)
    badT, bmask, bbw = prep(bad)
    vt = jnp.full((1, d_pad), 3.0, jnp.float32).at[0, :d].set(
        jnp.asarray(vartypes, jnp.float32)
    )
    cd = jnp.ones((1, d_pad), jnp.float32).at[0, :d].set(
        jnp.asarray(cards, jnp.float32)
    )
    cpad = jnp.zeros((s_pad, d_pad), jnp.float32).at[:s, :d].set(cands)

    out = _score_padded(
        cpad, goodT, gmask, gbw, badT, bmask, bbw, vt, cd,
        d_actual=d, interpret=interpret,
    )
    return out[:s, 0]


def pallas_propose_batch(
    key: jax.Array,
    good: KDE,
    bad: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    n: int,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
    interpret: bool = False,
) -> jax.Array:
    """A whole stage of BOHB proposals with Pallas-scored acquisition:
    generate ``n * num_samples`` candidates (``ops.kde.generate_candidates``),
    score them in the fused kernel, return the per-proposal argmax —
    ``f32[n, d]``, fully trace-safe (the fused sweep calls this inside its
    program; the host path wraps it via :func:`pallas_propose_batch_seeded`).

    RNG stream differs from the per-proposal :func:`ops.kde.propose` path
    (one flat candidate draw instead of per-proposal splits) — same
    distribution, different numbers.
    """
    from hpbandster_tpu.ops.kde import generate_candidates

    cands = generate_candidates(
        key, good, vartypes, cards, n * num_samples,
        bandwidth_factor, min_bandwidth,
    )
    scores = pallas_score_candidates_traced(
        cands, good, bad, vartypes, cards, interpret=interpret
    ).reshape(n, num_samples)
    best = jnp.argmax(scores, axis=1)
    return cands.reshape(n, num_samples, -1)[jnp.arange(n), best]


@functools.partial(
    jax.jit, static_argnames=("n", "num_samples", "interpret")
)
def pallas_propose_batch_seeded(
    seed: jax.Array,
    good: KDE,
    bad: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    n: int,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
    interpret: bool = False,
) -> jax.Array:
    """:func:`pallas_propose_batch` keyed from one scalar seed (same key
    derivation as ``ops.kde.generate_candidates_seeded``)."""
    return pallas_propose_batch(
        jax.random.key(seed), good, bad, vartypes, cards, n, num_samples,
        bandwidth_factor, min_bandwidth, interpret,
    )


def pallas_refit_propose_batch_seeded(
    seed: jax.Array,
    obs_v: jax.Array,
    obs_l: jax.Array,
    count: jax.Array,
    n_good: jax.Array,
    n_bad: jax.Array,
    vartypes: jax.Array,
    cards: jax.Array,
    n: int,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
    min_bandwidth_fit: float = 1e-3,
    impute_seed=None,
    interpret: bool = False,
) -> jax.Array:
    """Pallas twin of ``ops.kde.refit_propose_batch_seeded``: the KDE
    refit (good/bad split + bandwidths over raw observation buffers, all
    traced counts) AND the fused-kernel acquisition scoring happen in one
    compiled dispatch — the refit state never visits the host. Returns
    the selected proposals ``f32[n, d]`` (the Pallas pipeline is
    score-less on the host side, like :func:`pallas_propose_batch`).
    """
    from hpbandster_tpu.ops.kde import fit_kde_pair_masked

    impute_key = (
        None if impute_seed is None else jax.random.key(impute_seed)
    )
    good, bad = fit_kde_pair_masked(
        obs_v, obs_l, count, n_good, n_bad, cards, min_bandwidth_fit,
        impute_key=impute_key,
    )
    return pallas_propose_batch(
        jax.random.key(seed), good, bad, vartypes, cards, n, num_samples,
        bandwidth_factor, min_bandwidth, interpret,
    )


# --------------------------------------------------------- bandwidth fit
#: row tile for the masked-moment reduction — bigger than the scorer's
#: candidate tile because the moment kernel is pure streaming reduction
#: (no [TS, N] intermediate), so VMEM pressure is one [TILE_R, LANE] block
_TILE_R = 512


def _moments_kernel(data_ref, mask_ref, out_ref):
    """Accumulate per-dim masked sum / sum-of-squares / count across row
    tiles. TPU grid execution is sequential, so every program may
    accumulate into the SAME output block (initialized by program 0) —
    the canonical Pallas reduction layout."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():  # noqa: ANN202 — pallas when-block
        out_ref[:] = jnp.zeros_like(out_ref)

    d = data_ref[:]          # [TILE_R, LANE]
    m = mask_ref[:]          # [TILE_R, LANE] (mask broadcast to lanes)
    dm = d * m
    out_ref[0:1, :] += jnp.sum(dm, axis=0, keepdims=True)
    out_ref[1:2, :] += jnp.sum(dm * d, axis=0, keepdims=True)
    out_ref[2:3, :] += jnp.sum(m, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _masked_moments_padded(data, mask2, interpret: bool):
    """``data`` f32[C_pad, LANE], ``mask2`` f32[C_pad, LANE] ->
    f32[8, LANE] whose rows 0/1/2 are per-dim masked sum / sumsq /
    count (rows 3+ are sublane padding)."""
    from jax.experimental import pallas as pl

    try:
        from jax.experimental.pallas import tpu as pltpu

        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = None

    c_pad = data.shape[0]
    grid = (c_pad // _TILE_R,)

    def spec(shape, index_map):
        if vmem is None:
            return pl.BlockSpec(shape, index_map)
        return pl.BlockSpec(shape, index_map, memory_space=vmem)

    return pl.pallas_call(
        _moments_kernel,
        out_shape=jax.ShapeDtypeStruct((8, _LANE), jnp.float32),
        grid=grid,
        in_specs=[
            spec((_TILE_R, _LANE), lambda i: (i, 0)),
            spec((_TILE_R, _LANE), lambda i: (i, 0)),
        ],
        out_specs=spec((8, _LANE), lambda i: (0, 0)),
        interpret=interpret,
    )(data, mask2)


def pallas_normal_reference_bandwidths(
    data: jax.Array,
    mask: jax.Array,
    cards: jax.Array,
    min_bandwidth: float = 1e-3,
    interpret: bool = False,
) -> jax.Array:
    """Pallas twin of ``ops.kde.normal_reference_bandwidths`` — the
    truncnorm-KDE FIT's reduction half as one VMEM-resident streaming
    pass over the observation buffer.

    At 1M observations the XLA fit materializes two [C, d] intermediates
    (masked data and its square) through HBM; this kernel computes the
    per-dim masked moments in one pass and finishes the ~d-element
    bandwidth arithmetic in plain jnp. Variance comes from the one-pass
    identity ``E[x^2] - E[x]^2`` (clamped at 0) instead of the XLA
    path's two-pass form, so the fitted bandwidths are a distinct — not
    bit-identical — consumer; gate it with ``HPB_PALLAS_KDE_FIT`` (see
    ``ops.kde.fit_kde_pair_masked``) and re-baseline budgets when
    flipping the flag. Trace-safe (jnp padding only), so it can live
    inside the fused/resident sweep program.
    """
    data = jnp.asarray(data, jnp.float32)
    mask = jnp.asarray(mask, jnp.float32)
    c, d = data.shape
    c_pad = ((c + _TILE_R - 1) // _TILE_R) * _TILE_R
    dpad = jnp.zeros((c_pad, _LANE), jnp.float32).at[:c, :d].set(data)
    mpad = jnp.zeros((c_pad, _LANE), jnp.float32).at[:c, :d].set(
        jnp.broadcast_to(mask[:, None], (c, d))
    )
    from hpbandster_tpu.ops.kde import _discrete_bw_cap

    mom = _masked_moments_padded(dpad, mpad, interpret=interpret)
    s1, s2, cnt = mom[0, :d], mom[1, :d], mom[2, :d]
    n = jnp.maximum(cnt, 1.0)
    mean = s1 / n
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    sigma = jnp.sqrt(var)
    bw = 1.06 * sigma * n ** (-1.0 / (4.0 + d))
    # the Aitchison–Aitken cap has ONE definition (ops/kde.py) — the
    # Pallas twin must clamp exactly like the XLA path it is benchmarked
    # against
    return jnp.clip(bw, min_bandwidth, _discrete_bw_cap(jnp.asarray(cards)))


def pallas_score_candidates(
    cands: jax.Array,
    good: KDE,
    bad: KDE,
    vartypes,
    cards,
    interpret: bool = False,
) -> jax.Array:
    """Score ``f32[S, d]`` candidates; returns ``f32[S]`` acquisition scores.

    Drop-in replacement for the XLA path
    ``max(logpdf_good, F) - max(logpdf_bad, F)`` (see ``ops.kde.propose``).
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    cands = np.asarray(cands, np.float32)
    s, d = cands.shape
    s_pad = ((s + _TILE_S - 1) // _TILE_S) * _TILE_S
    d_pad = _LANE

    def prep(kde: KDE):
        data = np.asarray(kde.data, np.float32)
        mask = np.asarray(kde.mask, np.float32)
        bw = np.asarray(kde.bw, np.float32)
        n_pad = ((data.shape[0] + _LANE - 1) // _LANE) * _LANE
        dataT = _pad_to(data.T, (d_pad, n_pad), 0.0)
        mask2 = _pad_to(mask[None, :], (1, n_pad), 0.0)
        bw2 = _pad_to(bw[None, :], (1, d_pad), 1.0)
        return dataT, mask2, bw2

    goodT, gmask, gbw = prep(good)
    badT, bmask, bbw = prep(bad)
    vt = _pad_to(
        np.asarray(vartypes, np.float32)[None, :], (1, d_pad), 3.0
    )
    cd = _pad_to(np.asarray(cards, np.float32)[None, :], (1, d_pad), 1.0)
    cpad = _pad_to(cands, (s_pad, d_pad), 0.0)

    out = _score_padded(
        jnp.asarray(cpad), jnp.asarray(goodT), jnp.asarray(gmask),
        jnp.asarray(gbw), jnp.asarray(badT), jnp.asarray(bmask),
        jnp.asarray(bbw), jnp.asarray(vt), jnp.asarray(cd),
        d_actual=d, interpret=interpret,
    )
    return out[:s, 0]
