"""Whole-sweep fusion: an entire multi-bracket BOHB run as ONE device program.

The key observation: for the batched executor (static worker set, one
bracket at a time) the complete BOHB sweep has a **static dataflow**. Bracket
shapes come from the HyperBand arithmetic, observation counts per budget
accumulate deterministically stage by stage, and therefore the good/bad KDE
split sizes, the "largest budget with a trained model" choice, and every
``top_k`` promotion width are Python constants at trace time. Only the data
values are dynamic. So the *whole sweep* — proposal sampling, KDE fits,
stage evaluations, promotion decisions — jits into a single XLA computation
taking one uint32 seed and returning every bracket's configs and losses.

Why it matters: the per-bracket path pays ~3 host<->device round-trips per
bracket (proposal fetch + packed-result fetch), which dominates wall-clock
on high-latency links (a tunneled TPU: ~75 ms each). The fused sweep pays
ONE dispatch + one result fetch for the entire run.

Reference semantics reproduced on-device (SURVEY.md §2 "BOHB config
generator", §3.4): per-budget good/bad KDE split at ``top_n_percent``,
``min_points_in_model`` gate, largest-trained-budget model selection,
``random_fraction`` interleave, truncnorm-around-good-points candidates
scored by ``l(x)/g(x)``, crashed runs recorded as maximally bad. Conditional
spaces ARE supported: the condition DAG compiles to an on-device activity
predicate (:func:`compile_active_mask`), inactive dims evaluate as 0 and are
donor-imputed before KDE fits (host parity with
``BOHBKDE.impute_conditional_data``); forbidden clauses compile to a device
predicate with in-trace rejection resampling
(:func:`compile_forbidden_mask`). Condition forms without a numeric device
representation (e.g. order comparisons on categorical parents) raise at
construction — the per-bracket path remains the fallback for those.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hpbandster_tpu.obs.runtime import tracked_jit
from hpbandster_tpu.ops.bracket import BracketPlan
from hpbandster_tpu.ops.fused import (
    _CRASH_RANK,
    _pack_stages,
    StatefulEval,
    fused_sh_bracket,
    shard_rows,
    stage_telemetry,
)
from hpbandster_tpu.ops.kde import (
    KDE,
    fit_kde_pair_masked,
    impute_conditional_masked,
    normal_reference_bandwidths,
    propose,
)

__all__ = ["SpaceCodec", "build_space_codec", "quantize_unit", "random_unit",
           "random_unit_sharded", "compile_active_mask",
           "compile_forbidden_mask", "make_fused_sweep_fn",
           "SweepBracketOutput", "SweepIncumbent", "plan_additions",
           "pow2_capacities", "ResidentSweepOutputs", "resident_rotation",
           "unstack_resident_outputs", "DeviceMetrics",
           "init_device_metrics", "init_lane_state", "decode_lane_state",
           "sweep_donation_safe", "StatefulEval"]


def pow2_capacities(counts: dict, floor: int = 256) -> dict:
    """Pow2-bucketed observation capacities with a generous floor — THE
    one definition of the dynamic tier's buffer-shape policy (see the
    rationale at the chunked driver's call site): ``FusedBOHB.run`` /
    ``run_incumbent``, the sharded driver, and the parity tests must all
    agree on it or executable sharing (and the checkpoint-resume shape
    guarantee) silently breaks."""
    floor = max(int(floor), 1)
    return {
        float(b): 1 << max(int(n) - 1, floor - 1).bit_length()
        for b, n in counts.items()
    }


def plan_additions(plans: Sequence[BracketPlan]) -> dict:
    """Per-budget observation counts a plan sequence appends — the ONE
    definition shared by capacity seeding, the dynamic warm-count clamp,
    and ``FusedBOHB``'s bucket sizing (they must agree or the three
    silently drift)."""
    out: dict = {}
    for plan in plans:
        for k, b in zip(plan.num_configs, plan.budgets):
            out[float(b)] = out.get(float(b), 0) + int(k)
    return out


class SpaceCodec(NamedTuple):
    """Static per-dim description of a search space, enough to quantize and
    sample unit-hypercube vectors entirely on-device (conditions and
    forbiddens live in separately compiled predicates, not in the codec).

    Built host-side from a ``ConfigurationSpace`` (:func:`build_space_codec`)
    and closed over at trace time — all arrays are plain numpy.

    dim kinds: 0 = float, 1 = integer, 2 = categorical/ordinal (index repr),
    3 = constant.
    """

    kind: np.ndarray      # int32[d]
    log: np.ndarray       # bool[d]
    lower: np.ndarray     # float64[d] (1.0-safe for non-log dims)
    upper: np.ndarray     # float64[d]
    q: np.ndarray         # float64[d]; NaN = no quantization
    cards: np.ndarray     # int32[d] choices per discrete dim (0 = continuous)
    vartypes: np.ndarray  # int32[d] KDE vartype codes ('c'=0,'u'=1,'o'=2)
    logits: np.ndarray    # float32[d, kmax] sampling log-probs, -inf padded

    @property
    def signature(self) -> Tuple:
        """Hashable identity for compile caches."""
        return tuple(
            (a.tobytes(), a.shape) for a in self
        )


def build_space_codec(configspace) -> SpaceCodec:
    """Extract the static codec. Conditions are supported on-device via
    :func:`compile_active_mask`, forbiddens via :func:`compile_forbidden_mask`
    + in-trace rejection resampling (``make_fused_sweep_fn``)."""
    from hpbandster_tpu.space.hyperparameters import (
        CategoricalHyperparameter,
        Constant,
        OrdinalHyperparameter,
        UniformFloatHyperparameter,
        UniformIntegerHyperparameter,
    )

    hps = configspace.get_hyperparameters()
    d = len(hps)
    kind = np.zeros(d, np.int32)
    log = np.zeros(d, bool)
    lower = np.ones(d, np.float64)
    upper = np.full(d, 2.0, np.float64)
    q = np.full(d, np.nan, np.float64)
    cards = np.zeros(d, np.int32)
    kmax = max([hp.num_choices for hp in hps] + [1])
    logits = np.full((d, kmax), -np.inf, np.float32)

    for i, hp in enumerate(hps):
        if isinstance(hp, Constant):
            kind[i] = 3
            cards[i] = 1
            logits[i, 0] = 0.0
        elif isinstance(hp, UniformFloatHyperparameter):
            kind[i] = 0
            log[i] = hp.log
            lower[i], upper[i] = hp.lower, hp.upper
            if hp.q is not None:
                q[i] = hp.q
        elif isinstance(hp, UniformIntegerHyperparameter):
            kind[i] = 1
            log[i] = hp.log
            lower[i], upper[i] = hp.lower, hp.upper
        elif isinstance(hp, CategoricalHyperparameter):
            kind[i] = 2
            cards[i] = hp.num_choices
            logits[i, : hp.num_choices] = np.log(
                np.maximum(np.asarray(hp.probabilities, np.float64), 1e-300)
            )
        elif isinstance(hp, OrdinalHyperparameter):
            kind[i] = 2
            cards[i] = hp.num_choices
            logits[i, : hp.num_choices] = 0.0
        else:
            raise ValueError(f"unsupported hyperparameter type {type(hp).__name__}")
    return SpaceCodec(
        kind=kind, log=log, lower=lower, upper=upper, q=q, cards=cards,
        vartypes=np.asarray(configspace.vartypes()), logits=logits,
    )


def _int_log_bounds(codec: SpaceCodec) -> Tuple[np.ndarray, np.ndarray]:
    """The reference codec's widened log bounds for integer dims
    (hyperparameters.py UniformIntegerHyperparameter)."""
    lo = np.where(
        codec.lower > 1, codec.lower - 0.4999, np.maximum(codec.lower, 1) * 0.5001
    )
    hi = codec.upper + 0.4999
    return lo, hi


def quantize_unit(codec: SpaceCodec, u: jax.Array) -> jax.Array:
    """Jittable twin of host ``to_vector(from_vector(u))``: snap
    unit-hypercube vectors to representable configurations. (Activity of
    conditional dims is decided separately by :func:`compile_active_mask`.)

    ``u`` is ``f32[..., d]``. Bit-level parity with the host codec is not
    required (both are fixed points of each other's rounding; the bin-center
    integer convention makes the decode robust to f32 rounding).
    """
    kind = jnp.asarray(codec.kind)
    u_raw = u.astype(jnp.float32)
    # float/int dims live in [0,1]; categorical dims hold raw choice indices
    u = jnp.clip(u_raw, 0.0, 1.0)

    # floats: identity unless quantized (q), then value-space snap
    lo = jnp.asarray(codec.lower, jnp.float32)
    hi = jnp.asarray(codec.upper, jnp.float32)
    safe_lo = jnp.maximum(lo, 1e-30)
    log_lo, log_hi = jnp.log(safe_lo), jnp.log(jnp.maximum(hi, 1e-30))
    val_lin = lo + u * (hi - lo)
    val_log = jnp.exp(log_lo + u * (log_hi - log_lo))
    val = jnp.where(jnp.asarray(codec.log), val_log, val_lin)
    qs = jnp.asarray(np.nan_to_num(codec.q, nan=1.0), jnp.float32)
    has_q = jnp.asarray(np.isfinite(codec.q))
    val_q = jnp.clip(jnp.round(val / qs) * qs, lo, hi)
    enc_lin = (val_q - lo) / jnp.maximum(hi - lo, 1e-30)
    enc_log = (jnp.log(jnp.maximum(val_q, 1e-30)) - log_lo) / jnp.maximum(
        log_hi - log_lo, 1e-30
    )
    u_float = jnp.where(
        has_q,
        jnp.clip(jnp.where(jnp.asarray(codec.log), enc_log, enc_lin), 0.0, 1.0),
        u,
    )

    # integers: decode (bin-center / widened-log), round, re-encode
    ilo, ihi = _int_log_bounds(codec)
    ilo = jnp.asarray(ilo, jnp.float32)
    ihi = jnp.asarray(ihi, jnp.float32)
    n_int = jnp.maximum(hi - lo + 1.0, 1.0)
    v_lin = lo - 0.5 + u * n_int
    log_ilo = jnp.log(jnp.maximum(ilo, 1e-30))
    log_ihi = jnp.log(jnp.maximum(ihi, 1e-30))
    v_log = jnp.exp(log_ilo + u * (log_ihi - log_ilo))
    vi = jnp.clip(jnp.round(jnp.where(jnp.asarray(codec.log), v_log, v_lin)), lo, hi)
    enc_i_lin = (vi - lo + 0.5) / n_int
    enc_i_log = jnp.clip(
        (jnp.log(jnp.maximum(vi, 1e-30)) - log_ilo)
        / jnp.maximum(log_ihi - log_ilo, 1e-30),
        0.0,
        1.0,
    )
    u_int = jnp.where(jnp.asarray(codec.log), enc_i_log, enc_i_lin)

    # categorical / ordinal: snap to the nearest index
    kf = jnp.maximum(jnp.asarray(codec.cards, jnp.float32), 1.0)
    u_cat = jnp.clip(jnp.round(u_raw), 0.0, kf - 1.0)

    out = jnp.where(kind == 0, u_float, u)
    out = jnp.where(kind == 1, u_int, out)
    out = jnp.where(kind == 2, u_cat, out)
    out = jnp.where(kind == 3, 0.0, out)
    return out


def random_unit(codec: SpaceCodec, key: jax.Array, n: int) -> jax.Array:
    """``n`` uniform configuration vectors, matching the host sampler's
    semantics per dim (uniform unit for float/int, weighted categorical,
    uniform ordinal, 0 for constants). Returns un-quantized ``f32[n, d]`` —
    pass through :func:`quantize_unit` before evaluating."""
    d = codec.kind.shape[0]
    k_u, k_c = jax.random.split(key)
    u = jax.random.uniform(k_u, (n, d))
    idx = jax.random.categorical(
        k_c, jnp.asarray(codec.logits)[None, :, :], axis=-1, shape=(n, d)
    ).astype(jnp.float32)
    kind = jnp.asarray(codec.kind)
    out = jnp.where(kind == 2, idx, u)
    out = jnp.where(kind == 3, 0.0, out)
    return out


def random_unit_sharded(
    codec: SpaceCodec, key: jax.Array, n: int, n_shards: int
) -> jax.Array:
    """Per-shard PRNG derivation of :func:`random_unit` for a config batch
    sharded ``n_shards`` ways.

    Shard ``s`` draws its ``n // n_shards`` rows from
    ``jax.random.fold_in(key, s)`` — each shard's stream is independent of
    the others and of the batch's total size, so under a sharded jit every
    device generates exactly its own rows locally (no sampled bytes cross
    the ICI before evaluation). With ``n_shards == 1`` the base key is used
    UNFOLDED, so the sharded sampler on a 1-device mesh is bit-identical
    to :func:`random_unit` (the parity bar in ``tests/test_sharded.py``).
    Different shard counts are distinct — equally valid — RNG consumers,
    the same contract as the dynamic-count tier (docs/perf_notes.md).
    """
    n_shards = max(int(n_shards), 1)
    if n_shards == 1:
        return random_unit(codec, key, n)
    if n % n_shards != 0:
        raise ValueError(
            f"sharded sampling needs n % n_shards == 0, got {n} rows over "
            f"{n_shards} shards — pad the stage-0 count to a mesh multiple "
            "(parallel.mesh.pad_to_shards / ops.bracket.mesh_aligned_plan)"
        )
    per = n // n_shards
    return jnp.concatenate(
        [
            random_unit(codec, jax.random.fold_in(key, s), per)
            for s in range(n_shards)
        ]
    )


def _decode_values(codec: SpaceCodec, q: jax.Array) -> jax.Array:
    """Decode one quantized unit vector to the numeric values conditions
    compare against: floats/ints to their real value, categorical/ordinal
    dims to their choice INDEX (value-level comparisons are resolved to
    indices at compile time), constants to 0."""
    kind = jnp.asarray(codec.kind)
    lo = jnp.asarray(codec.lower, jnp.float32)
    hi = jnp.asarray(codec.upper, jnp.float32)
    log_lo = jnp.log(jnp.maximum(lo, 1e-30))
    log_hi = jnp.log(jnp.maximum(hi, 1e-30))
    v_lin = lo + q * (hi - lo)
    v_log = jnp.exp(log_lo + q * (log_hi - log_lo))
    v_float = jnp.where(jnp.asarray(codec.log), v_log, v_lin)

    ilo, ihi = _int_log_bounds(codec)
    ilo = jnp.asarray(ilo, jnp.float32)
    ihi = jnp.asarray(ihi, jnp.float32)
    n_int = jnp.maximum(hi - lo + 1.0, 1.0)
    vi_lin = lo - 0.5 + q * n_int
    vi_log = jnp.exp(
        jnp.log(jnp.maximum(ilo, 1e-30))
        + q * (jnp.log(jnp.maximum(ihi, 1e-30)) - jnp.log(jnp.maximum(ilo, 1e-30)))
    )
    v_int = jnp.clip(
        jnp.round(jnp.where(jnp.asarray(codec.log), vi_log, vi_lin)), lo, hi
    )

    out = jnp.where(kind == 0, v_float, q)
    out = jnp.where(kind == 1, v_int, out)
    out = jnp.where(kind == 2, jnp.round(q), out)
    out = jnp.where(kind == 3, 0.0, out)
    return out


def compile_active_mask(configspace, codec: SpaceCodec):
    """Compile the space's condition DAG to a jittable activity predicate.

    Returns ``mask_fn(q: f32[d]) -> bool[d]`` (vmap over batches) deciding,
    from a QUANTIZED unit vector, which dims are conditionally active —
    the device twin of ``ConfigurationSpace._active_set`` (a child is
    active iff every condition on it holds, and a condition on an inactive
    parent is false). Raises ``ValueError`` for condition forms without a
    numeric device representation (e.g. order comparisons on non-numeric
    ordinals) — callers fall back to the per-bracket path.
    """
    from hpbandster_tpu.space.conditions import (
        AndConjunction,
        EqualsCondition,
        GreaterThanCondition,
        InCondition,
        LessThanCondition,
        NotEqualsCondition,
        OrConjunction,
    )
    from hpbandster_tpu.space.hyperparameters import (
        CategoricalHyperparameter,
        Constant,
        OrdinalHyperparameter,
    )

    hps = configspace.get_hyperparameters()
    names = configspace.get_hyperparameter_names()
    index = {n: i for i, n in enumerate(names)}
    hp_by_name = dict(zip(names, hps))

    def cond_value_to_number(parent_name: str, value) -> float:
        """Resolve a condition's comparison value to the decoded-number
        domain of :func:`_decode_values` for that parent dim."""
        hp = hp_by_name[parent_name]
        if isinstance(hp, (CategoricalHyperparameter, OrdinalHyperparameter)):
            return float(hp.index(value))  # compare by choice index
        if isinstance(hp, Constant):
            return 0.0 if value == hp.value else float("nan")  # never equal
        return float(value)

    def ordinal_order_value(parent_name: str, value) -> float:
        """Greater/Less on an ordinal compares VALUES host-side; on device
        we compare indices, which is order-faithful only if the sequence is
        numerically sorted."""
        hp = hp_by_name[parent_name]
        seq = hp.sequence
        try:
            numeric = [float(v) for v in seq]
        except (TypeError, ValueError):
            raise ValueError(
                f"device conditions need a numeric ordinal sequence for "
                f"order comparisons on {parent_name!r}"
            )
        if numeric != sorted(numeric):
            raise ValueError(
                f"ordinal {parent_name!r} is not numerically sorted; order "
                f"comparisons have no index representation"
            )
        return float(hp.index(value))

    def compile_cond(c):
        if isinstance(c, AndConjunction):
            subs = [compile_cond(x) for x in c.components]
            return lambda dec, act: jnp.all(
                jnp.stack([f(dec, act) for f in subs])
            )
        if isinstance(c, OrConjunction):
            subs = [compile_cond(x) for x in c.components]
            return lambda dec, act: jnp.any(
                jnp.stack([f(dec, act) for f in subs])
            )
        j = index[c.parent_name]
        parent_hp = hp_by_name[c.parent_name]
        is_ord = isinstance(parent_hp, OrdinalHyperparameter)
        if isinstance(c, EqualsCondition):
            v = cond_value_to_number(c.parent_name, c.value)
            test = lambda x: x == v  # noqa: E731
        elif isinstance(c, NotEqualsCondition):
            v = cond_value_to_number(c.parent_name, c.value)
            test = lambda x: x != v  # noqa: E731
        elif isinstance(c, InCondition):
            vals = [cond_value_to_number(c.parent_name, v) for v in c.value]
            test = lambda x: jnp.any(  # noqa: E731
                jnp.stack([x == v for v in vals])
            )
        elif isinstance(c, (GreaterThanCondition, LessThanCondition)):
            # the decoded number for a categorical dim is its choice INDEX;
            # comparing float(c.value) against an index would silently build
            # a wrong activity mask (host compares raw values) — no device
            # representation, so reject and let callers fall back.
            if isinstance(parent_hp, CategoricalHyperparameter):
                raise ValueError(
                    f"order condition on categorical parent "
                    f"{c.parent_name!r} has no device representation"
                )
            v = (
                ordinal_order_value(c.parent_name, c.value)
                if is_ord else float(c.value)
            )
            if isinstance(c, GreaterThanCondition):
                test = lambda x, v=v: x > v  # noqa: E731
            else:
                test = lambda x, v=v: x < v  # noqa: E731
        else:
            raise ValueError(
                f"condition type {type(c).__name__} has no device compilation"
            )
        return lambda dec, act, j=j, test=test: act[j] & test(dec[j])

    # per-dim compiled condition list, evaluated in topological order so a
    # parent's activity is decided before any of its children
    topo = configspace._topological_order()
    per_dim = {
        index[name]: [
            compile_cond(c)
            for c in configspace.get_conditions()
            if c.child_name == name
        ]
        for name in topo
    }

    def mask_fn(q: jax.Array) -> jax.Array:
        dec = _decode_values(codec, q)
        act = jnp.ones(len(names), bool)
        for name in topo:
            j = index[name]
            for fn in per_dim[j]:
                act = act.at[j].set(act[j] & fn(dec, act))
        return act

    return mask_fn


def compile_forbidden_mask(configspace, codec: SpaceCodec):
    """Compile the space's forbidden clauses to a jittable predicate.

    Returns ``forbidden_fn(q: f32[d], act: bool[d]) -> bool[]`` — True when
    the QUANTIZED vector violates any forbidden clause — the device twin of
    ``ConfigurationSpace.is_forbidden``. A clause term on an inactive dim is
    False (host parity: ``is_forbidden`` only sees active values). Equality
    on a continuous dim uses a 1e-6 relative tolerance (the f32 decode
    cannot reproduce host float64 values exactly; host equality on a
    continuous draw is measure-zero anyway); discrete dims compare their
    choice indices exactly. Raises ``ValueError`` for clause types without
    a device compilation — callers fall back to the per-bracket path.
    """
    from hpbandster_tpu.space.forbidden import (
        ForbiddenAndConjunction,
        ForbiddenEqualsClause,
        ForbiddenInClause,
    )
    from hpbandster_tpu.space.hyperparameters import (
        CategoricalHyperparameter,
        Constant,
        OrdinalHyperparameter,
    )

    names = configspace.get_hyperparameter_names()
    index = {n: i for i, n in enumerate(names)}
    hp_by_name = dict(zip(names, configspace.get_hyperparameters()))

    def value_to_number(name: str, value) -> float:
        hp = hp_by_name[name]
        if isinstance(hp, (CategoricalHyperparameter, OrdinalHyperparameter)):
            return float(hp.index(value))
        if isinstance(hp, Constant):
            return 0.0 if value == hp.value else float("nan")  # never equal
        return float(value)

    def eq_term(name: str, value):
        if name not in index:
            raise ValueError(f"forbidden clause on unknown parameter {name!r}")
        j = index[name]
        v = value_to_number(name, value)
        if int(codec.kind[j]) == 0:  # continuous: f32-tolerant equality
            # tolerance must track the f32 DECODE error model per scale
            # kind: a linear decode (lo + u*(hi-lo)) has absolute error
            # ~ulps of max(|lo|,|hi|,range); a log decode (exp of a lerp in
            # log space) has error RELATIVE to the decoded value. A single
            # absolute tolerance would either let forbidden configs slip
            # through on wide linear ranges or over-forbid log dims near
            # small clause values. 1e-5 ≈ 80 f32 ulps of headroom.
            lo, hi = float(codec.lower[j]), float(codec.upper[j])
            if bool(codec.log[j]):
                tol = 1e-5 * max(abs(v), 1e-30)
            else:
                tol = 1e-5 * max(hi - lo, abs(lo), abs(hi))
            return lambda dec, act, j=j, v=v, tol=tol: act[j] & (
                jnp.abs(dec[j] - v) <= tol
            )
        return lambda dec, act, j=j, v=v: act[j] & (dec[j] == v)

    def compile_clause(c):
        if isinstance(c, ForbiddenAndConjunction):
            subs = [compile_clause(x) for x in c.components]
            return lambda dec, act: jnp.all(
                jnp.stack([f(dec, act) for f in subs])
            )
        if isinstance(c, ForbiddenEqualsClause):
            return eq_term(c.name, c.value)
        if isinstance(c, ForbiddenInClause):
            terms = [eq_term(c.name, v) for v in c.values]
            return lambda dec, act: jnp.any(
                jnp.stack([f(dec, act) for f in terms])
            )
        raise ValueError(
            f"forbidden clause type {type(c).__name__} has no device compilation"
        )

    clauses = [compile_clause(c) for c in configspace.get_forbiddens()]

    def forbidden_fn(q: jax.Array, act: jax.Array) -> jax.Array:
        if not clauses:
            return jnp.zeros((), bool)
        dec = _decode_values(codec, q)
        return jnp.any(jnp.stack([f(dec, act) for f in clauses]))

    return forbidden_fn


def _sweep_donation_safe() -> bool:
    """Whether the state-threading sweep may donate its warm buffers.

    On this jax (0.4.37) the CPU PJRT backend intermittently corrupts the
    heap when a donated dict-pytree aliases the returned state after heavy
    allocator churn — bisected empirically: 3/6 suite runs died in
    malloc_consolidate/SIGSEGV with donation on, 0/6 with it off, same
    program otherwise. The state thread itself (keeping the buffers
    device-resident between chunks) is safe everywhere and carries the
    transfer win; donation only adds the in-place alias, so it enables
    where accelerator backends handle aliasing (TPU/GPU) and stays off on
    CPU. ``HPB_SWEEP_DONATE=1``/``0`` forces either way (a chip run that
    reproduces the corruption can switch it off without a patch).
    """
    import os

    env = os.environ.get("HPB_SWEEP_DONATE", "")
    if env in ("0", "1"):
        return env == "1"
    import jax

    try:
        return jax.default_backend() != "cpu"
    # no backend at all: the jit below would fail first; stay undonated
    except Exception:  # graftlint: disable=swallowed-exception — probe; donation defaults off when the backend is unknowable
        return False


class SweepBracketOutput(NamedTuple):
    """Per-bracket device outputs of the fused sweep."""

    #: quantized stage-0 configuration vectors, f32[n0, d]
    vectors: jax.Array
    #: True where the proposal was model-based, bool[n0]
    model_based: jax.Array
    #: stage-major concatenation of original-row indices, i32[sum(ns)]
    idx_packed: jax.Array
    #: matching losses (NaN = crashed), f32[sum(ns)]
    loss_packed: jax.Array


class SweepIncumbent(NamedTuple):
    """The ``incumbent_only=True`` sweep's entire device->host payload.

    At 100k-1M configs the per-stage records are the transfer bill (and
    the host bookkeeping bill); the 100k/1M tiers only need the winner.
    The incumbent is the best FINAL-stage (largest-budget) loss across
    every bracket — crashed (NaN) rows rank behind any real loss via the
    shared crash-rank constant, so an all-crashed sweep still returns a
    row (with a NaN loss) rather than garbage.
    """

    #: the winning configuration's quantized vector, f32[d]
    vector: jax.Array
    #: its final-stage loss (NaN = every candidate crashed), f32[]
    loss: jax.Array
    #: which bracket (index into ``plans``) produced it, i32[]
    bracket: jax.Array
    #: each bracket's best final-stage loss, f32[len(plans)]
    per_bracket_loss: jax.Array


class ResidentSweepOutputs(NamedTuple):
    """Full (non-incumbent) outputs of a ``resident=True`` sweep.

    ``stacked`` holds one :class:`SweepBracketOutput` per ROTATION
    position whose leaves carry a leading round axis (``lax.scan``'s
    stacking); ``tail`` holds the per-bracket outputs of the partial
    final round, unrolled. :func:`unstack_resident_outputs` flattens
    both into the per-bracket list the unrolled sweep returns.
    """

    stacked: Tuple[SweepBracketOutput, ...]
    tail: Tuple[SweepBracketOutput, ...]


class DeviceMetrics(NamedTuple):
    """The in-trace telemetry pytree — the sweep's metrics plane.

    Every leaf is sized by the SCHEDULE (brackets x rungs x bins), never
    by the config count, so carrying it through ``run_bracket`` and the
    resident ``lax.scan`` adds a constant to the final d2h payload
    whatever the sweep size — the resident flat-host-link contract
    (``bench.py`` ``resident_100k`` asserts it with telemetry ON). Rows
    beyond a bracket's actual rung count stay at their init value; the
    host decoder (``obs.device_metrics.decode_device_metrics``) walks
    the plan shapes and never reads them. Bin layout is owned by
    ``obs/device_metrics.py`` (``bin_edges()``): ONE schema for the
    in-trace accumulator and every host twin.
    """

    #: per-(bracket, rung) loss histogram over the log-spaced bins;
    #: NaN (crashed) losses are excluded (counted in ``crashes``)
    loss_hist: jax.Array   # i32[n_brackets, max_rungs, N_BINS]
    #: per-(bracket, rung) evaluation counts (the static stage widths,
    #: recorded so the decoded record is self-describing)
    evals: jax.Array       # i32[n_brackets, max_rungs]
    #: per-(bracket, rung) crashed (NaN-loss) evaluation counts
    crashes: jax.Array     # i32[n_brackets, max_rungs]
    #: per-(bracket, rung) promoted-config counts (rows advancing to the
    #: next rung; 0 at each bracket's final rung)
    promotions: jax.Array  # i32[n_brackets, max_rungs]
    #: per-bracket KDE-refit flag: 1 when the bracket's proposals came
    #: from a fit with an OPEN model gate (matches the host model's
    #: largest-trained-budget gate arithmetic)
    model_fits: jax.Array  # i32[n_brackets]
    #: per-bracket best FINAL-stage loss (NaN = every candidate crashed,
    #: same crash-rank ordering as the incumbent fold); the decoder
    #: derives the running incumbent / improvement deltas from it
    best_final: jax.Array  # f32[n_brackets]
    #: per-(bracket, rung) monotonically increasing sequence stamp: the
    #: rung's global position in the sweep's execution order (-1 = the
    #: rung never ran). The stamp is what lets the flight recorder
    #: (``obs/timeline.py``) lay resident-scan rungs out in true device
    #: order — the scan's stacked outputs lose it — and it rides the
    #: same O(schedule) payload, so the flat d2h bill is untouched
    rung_seq: jax.Array    # i32[n_brackets, max_rungs]


def init_device_metrics(n_brackets: int, max_rungs: int, n_bins: int) -> DeviceMetrics:
    """Zero-initialized metrics carry (``best_final`` inits to NaN — a
    bracket that has not run yet has no best; ``rung_seq`` inits to -1 —
    a rung that never ran has no position in the execution order)."""
    return DeviceMetrics(
        loss_hist=jnp.zeros((n_brackets, max_rungs, n_bins), jnp.int32),
        evals=jnp.zeros((n_brackets, max_rungs), jnp.int32),
        crashes=jnp.zeros((n_brackets, max_rungs), jnp.int32),
        promotions=jnp.zeros((n_brackets, max_rungs), jnp.int32),
        model_fits=jnp.zeros((n_brackets,), jnp.int32),
        best_final=jnp.full((n_brackets,), jnp.nan, jnp.float32),
        rung_seq=jnp.full((n_brackets, max_rungs), -1, jnp.int32),
    )


def init_lane_state(n_lanes: int) -> jax.Array:
    """Fresh per-lane incumbent carry for a continuous-batching program
    (``serve/continuous.py`` over ``ops.buckets.
    fused_sh_bracket_bucketed_packed_carry``): one RANK-SPACE f32 per
    lane, ``+inf`` = the lane has observed nothing yet.

    Rank space is the incumbent fold's ordering domain (the same
    convention as the resident sweep's incumbent carry): a real loss is
    itself, a crashed (NaN) evaluation is ``_CRASH_RANK`` (behind every
    real loss, ahead of emptiness), and ``+inf`` is untouched — so the
    in-trace fold is one ``minimum`` with no NaN special-casing, and the
    carry threads device-to-device across chunks exactly like the
    resident sweep's obs state. :func:`decode_lane_state` is the host
    twin that maps rank space back to loss-or-None.
    """
    return jnp.full((int(n_lanes),), jnp.inf, jnp.float32)


def decode_lane_state(carry) -> List[Optional[float]]:
    """Host decode of one rank-space lane carry: per lane, the running
    incumbent loss, ``float('nan')`` for a lane that has only ever
    crashed, or None for a lane that has observed nothing."""
    out: List[Optional[float]] = []
    for v in np.asarray(carry, np.float32):
        v = float(v)
        if v == float("inf"):
            out.append(None)
        elif v >= float(_CRASH_RANK):
            out.append(float("nan"))
        else:
            out.append(v)
    return out


#: public name for the donation gate (serve/continuous.py threads its
#: lane carry device-to-device and donates under the same CPU caveat)
sweep_donation_safe = _sweep_donation_safe


def resident_rotation(plans: Sequence[BracketPlan]) -> Tuple[int, int, int]:
    """``(period, n_rounds, n_tail)`` of a bracket schedule.

    The HyperBand rotation repeats its bracket shapes with a short
    period, so the resident sweep traces ONE round and ``lax.scan``-s it:
    program size O(period), not O(brackets). ``period`` is the smallest
    ``p`` with ``plans[i] == plans[i - p]`` for every ``i >= p`` (falls
    back to ``len(plans)`` for an aperiodic schedule — the scan then has
    a single round and the resident program degenerates to the unrolled
    one); ``n_tail = len(plans) - period * n_rounds`` brackets of the
    partial last round run unrolled after the scan.
    """
    plans = [BracketPlan(tuple(p.num_configs), tuple(p.budgets)) for p in plans]
    n = len(plans)
    if n == 0:
        raise ValueError("resident rotation needs at least one bracket")
    period = n
    for cand in range(1, n):
        if all(plans[i] == plans[i - cand] for i in range(cand, n)):
            period = cand
            break
    n_rounds = n // period
    return period, n_rounds, n - period * n_rounds


def unstack_resident_outputs(
    raw: ResidentSweepOutputs, n_rounds: int
) -> List[SweepBracketOutput]:
    """Flatten a (fetched) :class:`ResidentSweepOutputs` into the flat
    per-bracket output list the unrolled sweep returns, in bracket order
    (round-major over the rotation, then the tail)."""
    outs: List[SweepBracketOutput] = []
    for r in range(int(n_rounds)):
        for pos_out in raw.stacked:
            outs.append(SweepBracketOutput(*(leaf[r] for leaf in pos_out)))
    outs.extend(SweepBracketOutput(*o) for o in raw.tail)
    return outs


#: device imputation moved to ops/kde.py (the in-trace refit op needs it
#: too); the old private name stays importable for existing callers
_impute_conditional_device = impute_conditional_masked


def _fit_kde_pair_device(
    vecs: jax.Array,
    losses: jax.Array,
    n_good: int,
    n_bad: int,
    cards: jax.Array,
    min_bandwidth: float,
    impute_key: Optional[jax.Array] = None,
) -> Tuple[KDE, KDE]:
    """Device twin of BOHBKDE._fit_kde_pair/_make_kde: stable sort by loss,
    top ``n_good`` / bottom ``n_bad`` rows, normal-reference bandwidths.
    Pass ``impute_key`` for conditional spaces — NaN (inactive) dims are
    then donor-imputed per split side, like the host model."""
    n = vecs.shape[0]
    order = jnp.argsort(losses, stable=True)
    good = vecs[order[:n_good]]
    bad = vecs[order[n - n_bad:]]
    if impute_key is not None:
        kg, kb = jax.random.split(impute_key)
        good = _impute_conditional_device(kg, good, cards)
        bad = _impute_conditional_device(kb, bad, cards)

    def mk(data: jax.Array) -> KDE:
        mask = jnp.ones(data.shape[0], jnp.float32)
        bw = normal_reference_bandwidths(data, mask, cards, min_bandwidth)
        return KDE(data, mask, bw)

    return mk(good), mk(bad)


#: the traced-count fit moved to ops/kde.py (fit_kde_pair_masked) so the
#: in-trace refit+propose op and this sweep share one definition; the old
#: private name stays importable (tests/test_kde_oracle.py uses it)
_fit_kde_pair_dynamic = fit_kde_pair_masked


def make_fused_sweep_fn(
    eval_fn: Callable[[jax.Array, float], jax.Array],
    plans: Sequence[BracketPlan],
    codec: SpaceCodec,
    *,
    num_samples: int = 64,
    random_fraction: float = 1 / 3,
    top_n_percent: int = 15,
    min_points_in_model: Optional[int] = None,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
    mesh=None,
    axis: str = "config",
    warm_counts: Optional[dict] = None,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    rank_fn: Optional[Callable] = None,
    active_mask_fn: Optional[Callable] = None,
    forbidden_fn: Optional[Callable] = None,
    fallback_vector: Optional[np.ndarray] = None,
    max_forbidden_retries: int = 8,
    dynamic_counts: bool = False,
    capacities: Optional[dict] = None,
    return_state: bool = False,
    shard_sampling: bool = False,
    incumbent_only: bool = False,
    resident: bool = False,
    device_metrics: bool = False,
    stateful_eval=None,
    program_name: Optional[str] = None,
) -> Callable[..., List[SweepBracketOutput]]:
    """Trace + jit the whole sweep; returns ``fn(seed[, warm_v, warm_l])``.

    Model bookkeeping mirrors ``models/bohb_kde.py`` with all counts static:
    a budget's KDE pair exists once it holds ``min_points_in_model + 2``
    observations and both split sides exceed ``dim``; proposals use the
    largest such budget, refit at every bracket start from all observations
    accumulated so far (the batched path's stage-chunked model updates).

    ``warm_counts`` (budget -> n, static) enables warm starting: the jitted
    fn then takes two extra pytree args ``warm_v`` (budget -> f32[n, d]) and
    ``warm_l`` (budget -> f32[n]) whose leaves seed the observation buffers
    — traced inputs, so re-warming with fresh data of the same shape reuses
    the compiled program.

    ``forbidden_fn`` (from :func:`compile_forbidden_mask`) enables forbidden
    clauses on-device by rejection resampling INSIDE the trace: each
    bracket's proposals are checked, violating rows are redrawn uniformly up
    to ``max_forbidden_retries`` times, and any row still forbidden after
    that is replaced by ``fallback_vector`` (a host-verified valid
    configuration) — bounded work, static shapes, no host round-trip.

    ``dynamic_counts=True`` keeps observation COUNTS out of the compiled
    program: the jitted fn takes ``(seed, warm_v, warm_l, warm_n)`` where
    each ``warm_v[b]`` / ``warm_l[b]`` is a FULL-capacity buffer and
    ``warm_n[b]`` a traced i32 count. Model gating, good/bad split sizes
    and the largest-trained-budget selection all become traced arithmetic
    (:func:`_fit_kde_pair_dynamic`), so a chunked or warm-started sweep
    reuses ONE executable as observations accumulate instead of
    recompiling at every chunk boundary — the static path burns every
    count into the trace and a K-chunk run costs K compiles. Proposal math
    then runs over full capacity buffers (mask-weighted), a constant-factor
    cost the chunked tier accepts for compile reuse. ``capacities``
    (budget -> slots, must cover warm + every plan's additions) pins the
    buffer shapes so all chunks of one run agree on them.

    ``shard_sampling=True`` (requires ``mesh``) is the 100k-1M scale mode:
    stage-0 proposals are drawn per shard of the config axis
    (:func:`random_unit_sharded` — shard ``s`` folds its index into the
    bracket key, so every device generates its own rows locally and no
    candidate bytes ever cross the host link or the ICI before
    evaluation), and every bracket stage plus the dynamic observation
    buffers carry explicit sharding constraints over ``axis`` so the
    config batch stays distributed through the whole rung ladder — rung
    promotion masks lower to on-device reductions across shards, never a
    host gather. On a 1-device mesh this mode is BIT-IDENTICAL to the
    unsharded program (the parity bar in ``tests/test_sharded.py``);
    across mesh sizes it is a distinct RNG consumer (per-shard streams),
    like the dynamic tier.

    ``incumbent_only=True`` shrinks the device->host payload to a single
    :class:`SweepIncumbent` — the winning (vector, loss, bracket) plus
    per-bracket best losses — instead of per-stage records: at 1M configs
    the stage records ARE the transfer (and host-replay) bill, and only
    the final incumbent needs to leave the device loop. With
    ``return_state`` the fn returns ``(incumbent, state)``.

    ``resident=True`` is the whole-outer-loop fusion (ROADMAP "in-trace
    everything at 1M"): instead of unrolling every bracket into the
    trace (program size O(brackets); a chunked driver then surfaces to
    host per chunk), the HyperBand rotation's repeating round of bracket
    shapes is traced ONCE and driven by an in-trace ``lax.scan`` over
    rounds — bracket rotation, KDE refit (the traced-count
    ``fit_kde_pair_masked`` path), rung promotion, observation-state
    threading and the incumbent update all stay device-resident across
    the whole schedule. Requires ``dynamic_counts=True`` (observation
    counts evolve across scan iterations, so they must be traced). With
    ``incumbent_only=True`` the entire sweep's device->host traffic is
    one seed up and one :class:`SweepIncumbent` down, whatever the
    config count; without it the fn returns
    :class:`ResidentSweepOutputs` (scan-stacked per-rotation-position
    outputs + the unrolled tail) — flatten with
    :func:`unstack_resident_outputs`. Bracket ``b_i``'s RNG key is
    ``fold_in(key, b_i)`` with a TRACED ``b_i`` of the same value the
    unrolled trace folds concretely, so the resident and unrolled
    dynamic tiers are bit-identical on the same seed and capacities
    (the parity bar in ``tests/test_resident.py``).

    ``return_state=True`` (dynamic tier only) makes the jitted fn ALSO
    return the end-of-sweep observation state ``(obs_v, obs_l, counts)``
    — the same pytrees the warm inputs arrived as — so a chunked driver
    can thread the state device-to-device across chunk boundaries: the
    warm observation buffers stop round-tripping through the host (no
    h2d re-upload per chunk), the compile/transfer tax the runtime
    telemetry measured (ROADMAP). On accelerator backends the warm
    inputs are additionally DONATED to the returned state
    (``donate_argnums`` — XLA aliases each buffer to its updated twin in
    place); on CPU donation stays off (:func:`_sweep_donation_safe` — a
    jax 0.4.37 PJRT heap-corruption hazard, bisected empirically). When
    donation is active the inputs are CONSUMED per call; pass fresh
    arrays (or the previous call's returned state) each time.

    ``device_metrics=True`` threads a fixed-shape :class:`DeviceMetrics`
    accumulator through every bracket (and the resident scan carry): per
    rung, log-binned loss histograms, crash/evaluation/promotion counts;
    per bracket, KDE-refit flags and best-final losses. Payload size is
    O(brackets x rungs x bins) — independent of the config count, so it
    rides the existing final d2h without perturbing the resident
    flat-link bill's shape. The jitted fn then ALSO returns the metrics
    pytree: ``(result, metrics)``, or with ``return_state``
    ``(result, metrics, state)``. Every path (unrolled static, dynamic
    chunked, sharded, resident) accumulates through the same
    ``run_bracket`` body, so the schema is identical — and parity
    testable — by construction; decode host-side with
    ``obs.device_metrics.decode_device_metrics``.

    ``stateful_eval`` (an :class:`~hpbandster_tpu.ops.fused.StatefulEval`,
    exclusive with ``eval_fn``) switches every bracket's rung ladder to
    the warm-continuation protocol: each bracket's ensemble of live
    training states is built in-trace (``init_fn``), rung promotions
    gather the surviving weight/optimizer pytrees by the same top-k
    indices the rung ranked by, and each stage trains only its
    INCREMENTAL budget — see ``fused_sh_bracket`` and
    ``workloads/ensemble.py``. The ensemble state is bracket-local device
    scratch: it never enters the scan carry or the d2h payload, so the
    resident flat-host-link bill is untouched however large the models
    are. All sweep modes (static, dynamic, sharded, resident) compose
    with it unchanged.

    ``program_name`` overrides the base name the compiled program is
    tracked under (``obs.runtime`` ledger; default ``"fused_sweep"``) —
    the resident/spmd suffixes still apply. Distinct workloads get
    distinct ledger rows, which is what lets a bench tier find ITS
    program's cost analysis in ``obs.profile.roofline_report``.
    """
    from hpbandster_tpu.parallel.mesh import is_multiprocess_mesh, shard_count

    d = int(codec.kind.shape[0])
    if (eval_fn is None) == (stateful_eval is None):
        raise ValueError(
            "provide exactly one evaluation seam: eval_fn (stateless) or "
            "stateful_eval (StatefulEval warm continuation)"
        )
    if forbidden_fn is not None and fallback_vector is None:
        raise ValueError("forbidden_fn requires a fallback_vector")
    if return_state and not dynamic_counts:
        raise ValueError(
            "return_state=True requires dynamic_counts=True: the static "
            "tier burns counts into the trace, there is no reusable state"
        )
    if shard_sampling and mesh is None:
        raise ValueError("shard_sampling=True requires a mesh")
    if incumbent_only and not plans:
        raise ValueError("incumbent_only=True needs at least one bracket")
    if resident and not dynamic_counts:
        raise ValueError(
            "resident=True requires dynamic_counts=True: the scan carries "
            "observation counts across rounds, so they must be traced"
        )
    if resident and not plans:
        raise ValueError("resident=True needs at least one bracket")
    n_shards = shard_count(mesh, axis) if shard_sampling else 1
    if n_shards > 1:
        for p in plans:
            if int(p.num_configs[0]) % n_shards:
                raise ValueError(
                    f"shard_sampling: stage-0 count {p.num_configs[0]} is "
                    f"not a multiple of the {n_shards}-way '{axis}' axis — "
                    "build plans with ops.bracket.mesh_aligned_plan (or pad "
                    "via parallel.mesh.pad_to_shards)"
                )
    #: pin the dynamic observation state's boundary shardings over the
    #: config axis on single-process meshes: chunked drivers thread the
    #: returned state straight back into the (AOT-compiled) next call, so
    #: input and output shardings must be stable by CONSTRUCTION, not by
    #: XLA's whim. Multi-process meshes keep the replicated contract below.
    pin_state_shards = (
        dynamic_counts and mesh is not None and not is_multiprocess_mesh(mesh)
    )
    min_pts = (d + 1) if min_points_in_model is None else max(int(min_points_in_model), d + 1)
    plans = [BracketPlan(tuple(p.num_configs), tuple(p.budgets)) for p in plans]
    warm_counts = {float(b): int(n) for b, n in (warm_counts or {}).items() if n > 0}

    # static per-budget observation capacities across the whole sweep
    additions = plan_additions(plans)
    caps: dict = {float(b): int(n) for b, n in warm_counts.items()}
    for b, k in additions.items():
        caps[b] = caps.get(b, 0) + k
    if capacities is not None:
        for b, need in caps.items():
            if capacities.get(float(b), 0) < need:
                raise ValueError(
                    f"capacities[{b}]={capacities.get(float(b))} cannot hold "
                    f"the {need} observations this sweep accumulates there"
                )
        caps = {float(b): int(n) for b, n in capacities.items()}

    vartypes_dev = jnp.asarray(codec.vartypes)
    cards_dev = jnp.asarray(codec.cards)

    # metrics-plane constants: the bin schema is owned by the obs layer
    # (ONE definition for the in-trace accumulator and the host decoder)
    if device_metrics:
        from hpbandster_tpu.obs.device_metrics import N_BINS, bin_edges

        dm_edges = bin_edges().astype(np.float32)
        dm_rungs = max(len(p.num_configs) for p in plans) if plans else 0
        dm_bins = N_BINS
        # per-bracket base of the global rung sequence stamp: cumulative
        # rung counts over the STATIC schedule, indexed at (possibly
        # traced) b_i inside run_bracket — the resident scan's bracket
        # index is a scalar i32, and gathering from a static table is
        # how the stamp stays monotonic across rounds without carrying
        # an extra counter through the scan
        dm_seq_base = jnp.asarray(
            np.cumsum([0] + [len(p.num_configs) for p in plans])[:-1],
            jnp.int32,
        )

    def trained_split(n: int) -> Optional[Tuple[int, int]]:
        """Host-side static twin of the _fit_kde_pair gate."""
        # run_bracket reaches this only on the static tier
        # (dynamic_counts=False), where counts[b] are Python ints burned
        # into the trace; the traced-counts tier routes to dynamic_gate,
        # the i32 twin of this gate. The tier split is a closure constant
        # a path-insensitive analysis cannot correlate.
        # graftlint: disable=trace-escape — static-tier-only host gate (see above)
        if n < min_pts + 2:
            return None
        n_good = max(min_pts, (top_n_percent * n) // 100)
        n_bad = max(min_pts, ((100 - top_n_percent) * n) // 100)
        if n_good <= d or n_bad <= d:
            return None
        return n_good, n_bad

    def _propose_model_vecs(good: KDE, bad: KDE, k_prop: jax.Array, n0: int):
        if use_pallas:
            from hpbandster_tpu.ops.pallas_kde import pallas_propose_batch

            return pallas_propose_batch(
                k_prop, good, bad, vartypes_dev, cards_dev, n0,
                num_samples, bandwidth_factor, min_bandwidth,
                pallas_interpret,
            )
        keys = jax.random.split(k_prop, n0)
        return jax.vmap(
            lambda k: propose(
                k, good, bad, vartypes_dev, cards_dev,
                num_samples, bandwidth_factor, min_bandwidth,
            )[0]
        )(keys)

    # dynamic-count machinery: the gate arithmetic is the i32-traced twin of
    # trained_split (same integer formulas, so the model opens at exactly
    # the same observation counts as the static path and the host model)
    capmax = max(caps.values(), default=0)
    any_trainable = any(trained_split(c) is not None for c in caps.values())

    def dynamic_gate(cnt: jax.Array):
        n_good = jnp.maximum(min_pts, (top_n_percent * cnt) // 100)
        n_bad = jnp.maximum(min_pts, ((100 - top_n_percent) * cnt) // 100)
        has = (cnt >= min_pts + 2) & (n_good > d) & (n_bad > d)
        return has, n_good, n_bad

    def dynamic_proposals(
        obs_v, obs_l, counts, rand_vecs, k_prop, k_frac, k_fit, n0
    ):
        """Largest-trained-budget selection + fit + proposal, all traced.

        Budget priority is a static descending unroll; the selected
        budget's buffer is widened to ``capmax`` so one fit serves
        whichever budget wins. When no budget's gate is open the fit runs
        on empty buffers (harmless, NaN-free) and ``mb_mask`` discards
        every model pick — matching the static path's all-random bracket.
        """
        sel_v = jnp.zeros((capmax, d), jnp.float32)
        sel_l = jnp.full((capmax,), jnp.inf, jnp.float32)
        sel_n = jnp.zeros((), jnp.int32)
        any_model = jnp.zeros((), bool)
        for b in sorted(caps, reverse=True):
            has, _, _ = dynamic_gate(counts[b])
            take = has & ~any_model
            pad = capmax - caps[b]
            pv = jnp.pad(obs_v[b], ((0, pad), (0, 0)))
            pl = jnp.pad(obs_l[b], (0, pad), constant_values=jnp.inf)
            sel_v = jnp.where(take, pv, sel_v)
            sel_l = jnp.where(take, pl, sel_l)
            sel_n = jnp.where(take, counts[b], sel_n)
            any_model = any_model | has
        _, n_good, n_bad = dynamic_gate(sel_n)
        good, bad = _fit_kde_pair_dynamic(
            sel_v, sel_l, sel_n, n_good, n_bad, cards_dev, min_bandwidth,
            impute_key=k_fit if active_mask_fn is not None else None,
        )
        model_vecs = _propose_model_vecs(good, bad, k_prop, n0)
        mb_mask = any_model & (
            jax.random.uniform(k_frac, (n0,)) >= random_fraction
        )
        proposals = jnp.where(mb_mask[:, None], model_vecs, rand_vecs)
        # any_model rides along for the metrics plane: it is the traced
        # twin of "a KDE refit ran with an open gate this bracket"
        return proposals, mb_mask, any_model

    if resident:
        rotation, n_rounds, _tail_count = resident_rotation(plans)
        round_plans = plans[:rotation]
        tail_plans = plans[rotation * n_rounds:]

    def init_obs_state(warm_v, warm_l, warm_n):
        """Seed the per-budget observation buffers: full-capacity with
        traced counts on the dynamic tier, exact-count slices burned into
        the trace on the static tier."""
        if dynamic_counts:
            # full-capacity buffers in, traced counts; pad slots pinned to
            # (0-vector, +inf loss) regardless of what the caller sent.
            # Each budget's additions over the whole schedule are static,
            # so clamping the traced warm count to (capacity - additions)
            # keeps every later append inside the buffer — an oversized
            # caller count truncates its newest warm rows deterministically
            # instead of silently clobbering fresh observations through
            # dynamic_update_slice's start-index clamping.
            obs_v, obs_l, counts = {}, {}, {}
            for b, cap in caps.items():
                # a budget present in `capacities` but absent from the
                # warm inputs (exported-API callers may oversize the
                # capacity map for a later chunk) defaults to an empty
                # count-0 buffer instead of a trace-time KeyError
                # (ADVICE r4); a budget present in only SOME of the three
                # warm dicts is a caller bug — name it instead of letting
                # warm_v[b] raise bare or silently dropping the data
                have = warm_n is not None and b in warm_n
                have_v = warm_v is not None and b in warm_v
                have_l = warm_l is not None and b in warm_l
                if not (have == have_v == have_l):
                    raise ValueError(
                        f"inconsistent warm inputs for budget {b}: present "
                        f"in warm_n={have}, warm_v={have_v}, "
                        f"warm_l={have_l} — each budget must appear in all "
                        f"three dicts or none"
                    )
                n_b = jnp.minimum(
                    jnp.asarray(warm_n[b] if have else 0, jnp.int32),
                    cap - additions.get(b, 0),
                )
                live = jnp.arange(cap, dtype=jnp.int32) < n_b
                v = (jnp.asarray(warm_v[b], jnp.float32) if have
                     else jnp.zeros((cap, d), jnp.float32))
                l = (jnp.asarray(warm_l[b], jnp.float32) if have
                     else jnp.full((cap,), jnp.inf, jnp.float32))
                obs_v[b] = jnp.where(live[:, None], v, 0.0)
                obs_l[b] = jnp.where(
                    live & ~jnp.isnan(l), l, jnp.inf
                )
                counts[b] = n_b
            if pin_state_shards:
                obs_v = {b: shard_rows(v, mesh, axis)
                         for b, v in obs_v.items()}
                obs_l = {b: shard_rows(l, mesh, axis)
                         for b, l in obs_l.items()}
        else:
            obs_v = {
                b: jnp.zeros((cap, d), jnp.float32) for b, cap in caps.items()
            }
            obs_l = {b: jnp.zeros(cap, jnp.float32) for b, cap in caps.items()}
            counts = {b: 0 for b in caps}  # python ints: static
            for b, n in warm_counts.items():
                obs_v[b] = obs_v[b].at[:n].set(warm_v[b].astype(jnp.float32))
                obs_l[b] = obs_l[b].at[:n].set(
                    jnp.where(jnp.isnan(warm_l[b]), jnp.inf, warm_l[b]).astype(
                        jnp.float32
                    )
                )
                counts[b] = n
        return obs_v, obs_l, counts

    def init_incumbent():
        """(best_key, best_loss, best_vec, best_bracket, per_bracket) —
        the cross-bracket incumbent fold's carry. ``per_bracket`` is a
        fixed f32[len(plans)] written at the bracket's index (the array
        form both the unrolled loop and the resident scan can update)."""
        return (
            jnp.asarray(jnp.inf, jnp.float32),
            jnp.asarray(jnp.nan, jnp.float32),
            jnp.zeros((d,), jnp.float32),
            jnp.asarray(-1, jnp.int32),
            jnp.zeros((len(plans),), jnp.float32),
        )

    def run_bracket(b_i, plan, key, obs_v, obs_l, counts, inc, metrics):
        """One bracket: sample/propose -> forbidden resampling -> fused
        rung ladder -> observation append -> incumbent fold -> metrics
        accumulation.

        ``b_i`` may be a Python int (the unrolled trace) or a traced i32
        (the resident scan's round arithmetic): ``fold_in`` is
        value-deterministic, so both derive identical draws for the same
        bracket index — the resident/unrolled bit-parity contract.
        Functional: returns updated ``(obs_v, obs_l, counts, inc,
        metrics, out)`` without mutating the caller's dicts (the scan
        carry requires it); ``out`` is the bracket's
        :class:`SweepBracketOutput` or ``None`` under ``incumbent_only``;
        ``metrics`` is the :class:`DeviceMetrics` carry (``None`` when
        the metrics plane is off — nothing extra is traced then). All
        metrics writes index row ``b_i``, which works for both the
        unrolled (concrete) and scanned (traced) index.
        """
        obs_v, obs_l, counts = dict(obs_v), dict(obs_l), dict(counts)
        n0 = plan.num_configs[0]
        k_rand, k_prop, k_frac, k_fit = jax.random.split(
            jax.random.fold_in(key, b_i), 4
        )
        # per-shard derivation under shard_sampling: each shard's rows
        # come from its own folded key, so generation stays local to
        # the owning device (n_shards == 1 falls through to the
        # unfolded base key — the 1-device-mesh bit-parity contract)
        rand_vecs = random_unit_sharded(codec, k_rand, n0, n_shards)
        if n_shards > 1:
            rand_vecs = shard_rows(rand_vecs, mesh, axis)

        #: metrics-plane KDE gate flag for this bracket: traced under the
        #: dynamic tier (the gate is count-arithmetic), concrete 0/1 on
        #: the static tier — both are the same host-model gate
        fit_flag = jnp.zeros((), jnp.int32)
        if dynamic_counts:
            if not any_trainable:
                # no budget's gate can open even at full capacity
                # (FusedHyperBand/RandomSearch) — skip tracing the
                # model math entirely
                proposals = rand_vecs
                mb_mask = jnp.zeros(n0, bool)
            else:
                proposals, mb_mask, any_model = dynamic_proposals(
                    obs_v, obs_l, counts, rand_vecs, k_prop, k_frac,
                    k_fit, n0,
                )
                fit_flag = any_model.astype(jnp.int32)
        else:
            model_budget = None
            for b in sorted(caps, reverse=True):
                if trained_split(counts[b]) is not None:
                    model_budget = b
                    break

            if model_budget is None:
                proposals = rand_vecs
                mb_mask = jnp.zeros(n0, bool)
            else:
                fit_flag = jnp.ones((), jnp.int32)
                n = counts[model_budget]
                n_good, n_bad = trained_split(n)
                good, bad = _fit_kde_pair_device(
                    obs_v[model_budget][:n], obs_l[model_budget][:n],
                    n_good, n_bad, cards_dev, min_bandwidth,
                    impute_key=k_fit if active_mask_fn is not None else None,
                )
                model_vecs = _propose_model_vecs(good, bad, k_prop, n0)
                mb_mask = (
                    jax.random.uniform(k_frac, (n0,)) >= random_fraction
                )
                proposals = jnp.where(
                    mb_mask[:, None], model_vecs, rand_vecs
                )

        vectors = quantize_unit(codec, proposals)

        if forbidden_fn is not None:
            # in-trace rejection resampling (bounded, static shapes):
            # redraw forbidden rows uniformly; anything still forbidden
            # after the retry budget clamps to the known-valid fallback
            def batch_act(vecs):
                if active_mask_fn is not None:
                    return jax.vmap(active_mask_fn)(vecs)
                return jnp.ones(vecs.shape, bool)

            k_forb = jax.random.fold_in(k_rand, 0x7FB)
            resampled = jnp.zeros(n0, bool)
            for t in range(max_forbidden_retries):
                forbidden_rows = jax.vmap(forbidden_fn)(
                    vectors, batch_act(vectors)
                )
                resampled = resampled | forbidden_rows
                fresh = quantize_unit(
                    codec,
                    random_unit(codec, jax.random.fold_in(k_forb, t), n0),
                )
                vectors = jnp.where(
                    forbidden_rows[:, None], fresh, vectors
                )
            forbidden_rows = jax.vmap(forbidden_fn)(
                vectors, batch_act(vectors)
            )
            fb = quantize_unit(
                codec, jnp.asarray(fallback_vector, jnp.float32)
            )
            vectors = jnp.where(
                forbidden_rows[:, None], fb[None, :], vectors
            )
            # a redrawn/clamped row is uniform (or the fallback), not a
            # model pick — don't let it masquerade as model-based in
            # config_info / analysis
            mb_mask = mb_mask & ~resampled

        if active_mask_fn is not None:
            # conditional space: evaluation sees 0 in inactive dims
            # (host parity: to_vector -> NaN -> nan_to_num(0)), while
            # observations and outputs carry NaN so the host decoder
            # and the KDE imputation see the true activity pattern
            active = jax.vmap(active_mask_fn)(vectors)
            eval_vectors = jnp.where(active, vectors, 0.0)
            out_vectors = jnp.where(active, vectors, jnp.nan)
        else:
            eval_vectors = out_vectors = vectors
        # shard_rows, NOT a raw with_sharding_constraint: constraining a
        # batch that does not divide the config axis miscompiles under
        # XLA CPU SPMD on multi-axis meshes (stage indices come back
        # scaled by the other axis' size — the __graft_entry__ dryrun's
        # (config, model) mesh with a 9-row bracket), and shard_rows is
        # the one place that divisibility policy lives
        eval_vectors = shard_rows(eval_vectors, mesh, axis)

        stages = fused_sh_bracket(
            eval_fn, eval_vectors, plan.num_configs, plan.budgets,
            rank_fn=rank_fn,
            # per-stage sharding constraints: the rung ladder's
            # survivor batches stay distributed over the config axis
            # (promotion masks reduce across shards on-device)
            mesh=mesh if shard_sampling else None, axis=axis,
            # warm-continuation seam: the bracket's live training states
            # stay device-internal (bracket-local scratch, never carried)
            stateful=stateful_eval,
        )

        for (idx_s, losses_s), k_s, budget in zip(
            stages, plan.num_configs, plan.budgets
        ):
            b = float(budget)
            c = counts[b]
            upd_l = jnp.where(jnp.isnan(losses_s), jnp.inf, losses_s)
            if dynamic_counts:
                obs_v[b] = jax.lax.dynamic_update_slice_in_dim(
                    obs_v[b], out_vectors[idx_s], c, 0
                )
                obs_l[b] = jax.lax.dynamic_update_slice_in_dim(
                    obs_l[b], upd_l, c, 0
                )
            else:
                obs_v[b] = obs_v[b].at[c:c + k_s].set(out_vectors[idx_s])
                obs_l[b] = obs_l[b].at[c:c + k_s].set(upd_l)
            counts[b] = c + k_s

        if metrics is not None:
            # metrics plane: per-rung histograms / crash counts plus the
            # per-bracket refit flag and best final loss, all written at
            # row b_i (concrete OR traced — the resident/unrolled parity
            # contract extends to telemetry). O(n) binning per stage is
            # trivial next to the stage evaluation it accompanies; the
            # carried arrays are O(schedule), never O(configs).
            m_hist, m_ev, m_cr, m_pr, m_sq = (
                metrics.loss_hist, metrics.evals, metrics.crashes,
                metrics.promotions, metrics.rung_seq,
            )
            depth = len(plan.num_configs)
            for s, ((_idx_s, losses_s), k_s) in enumerate(
                zip(stages, plan.num_configs)
            ):
                h_s, c_s = stage_telemetry(losses_s, dm_edges)
                m_hist = m_hist.at[b_i, s].set(h_s)
                m_ev = m_ev.at[b_i, s].set(k_s)
                m_cr = m_cr.at[b_i, s].set(c_s)
                m_pr = m_pr.at[b_i, s].set(
                    plan.num_configs[s + 1] if s + 1 < depth else 0
                )
                # global execution-order stamp: static per-bracket base
                # (gathered at the concrete-or-traced b_i) + the stage
                # offset — monotonically increasing over the whole
                # schedule, resident rounds included
                m_sq = m_sq.at[b_i, s].set(dm_seq_base[b_i] + s)
            _, loss_fin = stages[-1]
            key_fin = jnp.where(jnp.isnan(loss_fin), _CRASH_RANK, loss_fin)
            metrics = DeviceMetrics(
                loss_hist=m_hist, evals=m_ev, crashes=m_cr,
                promotions=m_pr,
                model_fits=metrics.model_fits.at[b_i].set(fit_flag),
                best_final=metrics.best_final.at[b_i].set(
                    loss_fin[jnp.argmin(key_fin)]
                ),
                rung_seq=m_sq,
            )

        out = None
        if incumbent_only:
            # only the winner leaves the device loop: reduce the final
            # (largest-budget) stage to its best row and fold it into
            # the running cross-bracket incumbent — crashed (NaN) rows
            # rank behind every real loss via the shared crash rank
            best_key, best_loss, best_vec, best_bracket, per_bracket = inc
            idx_f, loss_f = stages[-1]
            key_f = jnp.where(jnp.isnan(loss_f), _CRASH_RANK, loss_f)
            a = jnp.argmin(key_f)
            cand_key = key_f[a]
            take = cand_key < best_key
            best_key = jnp.where(take, cand_key, best_key)
            best_loss = jnp.where(take, loss_f[a], best_loss)
            best_vec = jnp.where(take, out_vectors[idx_f[a]], best_vec)
            best_bracket = jnp.where(
                take, jnp.asarray(b_i, jnp.int32), best_bracket
            )
            per_bracket = per_bracket.at[b_i].set(loss_f[a])
            inc = (best_key, best_loss, best_vec, best_bracket, per_bracket)
        else:
            idx_packed, loss_packed = _pack_stages(stages)
            out = SweepBracketOutput(
                out_vectors[:n0], mb_mask, idx_packed, loss_packed
            )
        return obs_v, obs_l, counts, inc, metrics, out

    def sweep(
        seed: jax.Array, warm_v=None, warm_l=None, warm_n=None
    ) -> List[SweepBracketOutput]:
        key = jax.random.key(seed)
        obs_v, obs_l, counts = init_obs_state(warm_v, warm_l, warm_n)
        inc = init_incumbent() if incumbent_only else None
        # the metrics carry rides the same functional thread as the
        # incumbent (None = metrics plane off: a registered-empty pytree
        # node, legal in the scan carry exactly like the inc slot)
        metrics = (
            init_device_metrics(len(plans), dm_rungs, dm_bins)
            if device_metrics else None
        )
        outputs: List[SweepBracketOutput] = []
        if resident:
            # the resident outer loop: ONE traced round of the bracket
            # rotation, scanned over rounds — bracket rotation, KDE
            # refit, promotion and the incumbent update never surface to
            # host between brackets, and program size is O(rotation)
            # instead of O(brackets)
            def round_body(carry, r):
                obs_v, obs_l, counts, inc, metrics = carry
                outs = []
                for pos, plan in enumerate(round_plans):
                    obs_v, obs_l, counts, inc, metrics, out = run_bracket(
                        r * rotation + pos, plan, key,
                        obs_v, obs_l, counts, inc, metrics,
                    )
                    if not incumbent_only:
                        outs.append(out)
                if pin_state_shards:
                    # the scan carry is an AOT-stable boundary like the
                    # return_state one: in/out shardings must agree by
                    # construction, not by XLA's whim
                    obs_v = {b: shard_rows(v, mesh, axis)
                             for b, v in obs_v.items()}
                    obs_l = {b: shard_rows(l, mesh, axis)
                             for b, l in obs_l.items()}
                return (obs_v, obs_l, counts, inc, metrics), tuple(outs)

            (obs_v, obs_l, counts, inc, metrics), stacked = jax.lax.scan(
                round_body, (obs_v, obs_l, counts, inc, metrics),
                jnp.arange(n_rounds, dtype=jnp.int32),
            )
            tail_outs: List[SweepBracketOutput] = []
            for j, plan in enumerate(tail_plans):
                obs_v, obs_l, counts, inc, metrics, out = run_bracket(
                    n_rounds * rotation + j, plan, key,
                    obs_v, obs_l, counts, inc, metrics,
                )
                if not incumbent_only:
                    tail_outs.append(out)
            result = (
                SweepIncumbent(inc[2], inc[1], inc[3], inc[4])
                if incumbent_only
                else ResidentSweepOutputs(stacked, tuple(tail_outs))
            )
        else:
            for b_i, plan in enumerate(plans):
                obs_v, obs_l, counts, inc, metrics, out = run_bracket(
                    b_i, plan, key, obs_v, obs_l, counts, inc, metrics
                )
                if not incumbent_only:
                    outputs.append(out)
            result = (
                SweepIncumbent(inc[2], inc[1], inc[3], inc[4])
                if incumbent_only else outputs
            )
        if return_state:
            # the donated warm inputs alias these outputs buffer-for-buffer
            # (same pytree structure, shapes, dtypes) — the in-place state
            # thread chunked drivers hand back to the next call. Boundary
            # shardings re-pinned so the threaded state re-enters the AOT
            # executable with exactly the sharding it was lowered for.
            if pin_state_shards:
                obs_v = {b: shard_rows(v, mesh, axis)
                         for b, v in obs_v.items()}
                obs_l = {b: shard_rows(l, mesh, axis)
                         for b, l in obs_l.items()}
            if device_metrics:
                return result, metrics, (obs_v, obs_l, counts)
            return result, (obs_v, obs_l, counts)
        if device_metrics:
            return result, metrics
        return result

    from hpbandster_tpu.parallel.mesh import is_multiprocess_mesh

    # buffer-donation contract (docs/perf_notes.md): the warm observation
    # buffers are donated exactly when the call returns the updated state
    # they can alias (the dynamic chunked thread) AND the backend handles
    # aliasing safely. Elsewhere the outputs never match the input shapes,
    # so donation would be a no-op warning — declined explicitly.
    donate = (
        (1, 2, 3)
        if (dynamic_counts and return_state and _sweep_donation_safe())
        else ()
    )

    base_name = program_name or "fused_sweep"
    if is_multiprocess_mesh(mesh):
        # DCN tier (VERDICT r3 #6): the mesh spans several jax.distributed
        # processes. Every rank's SPMD driver replays the SAME sweep, so
        # inputs (seed + warm observations, identical on all ranks) and
        # outputs (the stage records every rank's bookkeeping consumes) pin
        # to fully-REPLICATED shardings — a rank could not device_get a
        # shard homed on another process. Evaluation still shards over the
        # 'config' axis via the with_sharding_constraint above; XLA inserts
        # the all-gathers (outputs are tiny: indices + losses + vectors).
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        return tracked_jit(
            sweep,
            name=base_name + ("_resident_spmd" if resident else "_spmd"),
            in_shardings=rep, out_shardings=rep, donate_argnums=donate,
        )
    return tracked_jit(
        sweep,
        name=base_name + ("_resident" if resident else ""),
        donate_argnums=donate,
    )
