"""Ring attention — sequence-parallel exact attention over a device mesh.

Long-context support (SURVEY.md §5 "long-context / seq parallel" row;
the task brief's first-class requirement): attention over a sequence too
long for one device's memory, computed EXACTLY by sharding the sequence
axis across the mesh and rotating K/V blocks around the ring with
``jax.lax.ppermute`` while queries stay resident. Each of the P steps
combines one (Q-block, K/V-block) tile with the numerically stable online
softmax (flash-attention-style running max / normalizer / accumulator),
so memory per device is O(T/P · d) while the result is the MATHEMATICALLY
EXACT softmax over the full sequence (no approximation; last-ulp rounding
differs from dense attention because the reduction is reordered), with no
quadratic-in-T buffer anywhere.

Causal runs skip the GEMMs of fully-masked tiles (``lax.cond`` on the
block order). On a synchronous ring this saves energy, not wall — at
step t the busiest device still computes one live tile. The wall fix is
the STRIPED layout (``striped=True``, after Brandon et al.'s Striped
Attention): device i holds the positions congruent to i mod P, so every
(Q-stripe, K-stripe) tile is ~half live and the causal work is balanced
across the ring — no device ever waits on a fully-dead step.
``make_ring_attention(striped=True)`` permutes global arrays to stripes
and back internally; the block form expects stripe-layout inputs.

The memory bound holds for TRAINING too: a ``custom_vjp`` saves only this
device's blocks plus the per-row logsumexp and re-ROTATES K/V around the
ring in the backward pass (flash-attention backward per tile, with the
dK/dV accumulators traveling alongside their blocks until they return
home) — without it, reverse-mode AD through the forward loop would stash
every rotated block as a scan residual and quietly materialize the full
sequence's K/V per device per layer, exactly what ring attention exists
to avoid.

TPU mapping: the tile products are bf16 GEMMs with f32 accumulation on
the MXU (``compute_dtype``); the P-1 forward (P backward) ppermutes ride
the ICI ring, and XLA overlaps each block's GEMM with the next block's
transfer — the compute/communication pipeline of Liu et al.'s ring
attention, expressed in pure ``shard_map`` + collectives rather than
hand-written RDMA.

Public surface:

* :func:`ring_attention_block` — the per-shard computation, for use
  INSIDE an existing ``shard_map`` (composes with other parallelism).
* :func:`make_ring_attention` — wraps it in ``shard_map`` over a named
  mesh axis: ``fn(q, k, v)`` on global ``[T, H, dh]`` arrays.
* ``shard_map`` — the version-resolved transform, re-exported so callers
  don't repeat the pre-0.8 fallback.

Parity with dense attention — values AND gradients — is pinned in
``tests/test_ring_attention.py`` on the virtual 8-device mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

__all__ = [
    "ring_attention_block", "make_ring_attention", "seq_mesh",
    "stripe_indices", "shard_map",
]

#: additive mask value: large-negative (not -inf) so fully-masked tiles
#: produce exp() underflow to exactly 0 instead of NaN arithmetic
_MASK = -1e30


def seq_mesh(devices=None) -> Mesh:
    """1-D mesh over all devices with a 'seq' axis (the long-context twin
    of — and delegate to — ``parallel.mesh.config_mesh``)."""
    from hpbandster_tpu.parallel.mesh import config_mesh

    return config_mesh(devices, axis_name="seq")


def _ring_perm(p_size):
    return [(s, (s + 1) % p_size) for s in range(p_size)]


def stripe_indices(t: int, p_size: int):
    """Index arrays converting a length-``t`` sequence between natural
    order and the striped layout (device i holds positions ≡ i mod P).

    ``to_striped``: ``x[to_striped]`` is stripe-ordered so a contiguous
    'seq' sharding gives device i slots ``s`` holding position
    ``s * P + i``. ``to_natural`` inverts it."""
    import numpy as np

    assert t % p_size == 0, f"T={t} must divide by the ring size {p_size}"
    b = t // p_size
    n = np.arange(t)
    to_striped = (n % b) * p_size + n // b
    to_natural = np.empty(t, np.int64)
    to_natural[to_striped] = n
    return to_striped, to_natural


def _tile_scores(q_c, k_blk, scale, compute_dtype, causal, striped,
                 i, j, t_q, t_k):
    """[H, Tq, Tk] tile scores: compute_dtype GEMM, f32 accumulation,
    global-position causal mask (contiguous or striped layout)."""
    s = jnp.einsum(
        "qhd,khd->hqk", q_c, k_blk.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        if striped:
            # striped layout: q position = slot*P + i, k position =
            # slot*P + j, so the tile's causal set is slot_q > slot_k,
            # plus the diagonal when i >= j — every tile is ~half live
            # (the load-balance property)
            sq = jnp.arange(t_q)[:, None]
            sk = jnp.arange(t_k)[None, :]
            live = (sq > sk) | ((sq == sk) & (i >= j))
        else:
            q_pos = i * t_q + jnp.arange(t_q)
            k_pos = j * t_k + jnp.arange(t_k)
            live = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(live[None], s, _MASK)
    return s


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _ring_attention(axis_name, causal, striped, scale, compute_dtype,
                    q, k, v):
    out, _ = _ring_attention_fwd(axis_name, causal, striped, scale,
                                 compute_dtype, q, k, v)
    return out


def _ring_attention_fwd(axis_name, causal, striped, scale, compute_dtype,
                        q, k, v):
    p_size = jax.lax.psum(1, axis_name)
    i = jax.lax.axis_index(axis_name)
    t_q, n_heads, dh = q.shape
    t_k = k.shape[0]
    q_c = q.astype(compute_dtype)
    perm = _ring_perm(p_size)

    def tile_update(j, k_blk, v_blk, m, l, acc):
        """Fold one (Q-block, K/V-block-from-device-j) tile into the
        running online-softmax state."""
        s = _tile_scores(q_c, k_blk, scale, compute_dtype, causal,
                         striped, i, j, t_q, t_k)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "hqk,khd->hqd", p.astype(compute_dtype),
            v_blk.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    # step 0 (this device's own block) is hoisted: the loop then
    # rotates-then-computes, so exactly P-1 ppermutes ride the ring and
    # no final rotation's result is thrown away. Hoisting also seeds the
    # running max from the never-fully-masked diagonal block, and the
    # q/k/v-derived state is naturally device-varying (what shard_map
    # requires of the carry).
    m0 = jnp.full((n_heads, t_q), _MASK, jnp.float32)
    l0 = jnp.zeros((n_heads, t_q), jnp.float32)
    acc0 = jnp.zeros((n_heads, t_q, dh), jnp.float32)
    m, l, acc = tile_update(i, k, v, m0, l0, acc0)

    def body(t, carry):
        k_blk, v_blk, m, l, acc = carry
        # rotate K/V one hop; XLA overlaps this ICI transfer with the
        # tile GEMMs (the ring-attention pipeline)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        j = (i - t) % p_size  # ring origin after t rotations
        if causal and not striped:
            # a tile whose every key position exceeds every query position
            # is fully masked: its probabilities are exactly 0, so skip
            # its GEMMs (under vmap cond lowers to select and computes
            # both — harmless, just no saving). Striped tiles are ~half
            # live by construction — nothing to skip.
            m, l, acc = jax.lax.cond(
                j * t_k > i * t_q + (t_q - 1),
                lambda: (m, l, acc),
                lambda: tile_update(j, k_blk, v_blk, m, l, acc),
            )
        else:
            m, l, acc = tile_update(j, k_blk, v_blk, m, l, acc)
        return k_blk, v_blk, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(
        1, p_size, body, (k, v, m, l, acc)
    )
    out_hqd = acc / l[..., None]
    out = out_hqd.transpose(1, 0, 2).astype(q.dtype)
    # residuals are O(T/P · d): own blocks + per-row logsumexp. The
    # rotated blocks are NOT saved — the backward re-rotates them.
    logsumexp = m + jnp.log(l)
    return out, (q, k, v, out, logsumexp)


def _ring_attention_bwd(axis_name, causal, striped, scale, compute_dtype,
                        res, dout):
    """Flash-attention backward per tile, K/V re-rotated around the ring.

    With the saved logsumexp L the softmax probabilities of any tile are
    recomputable exactly (``p = exp(s - L)``); the dK/dV accumulators
    travel WITH their blocks so after P-1 in-loop rotations plus one
    final hop every block's gradient lands back on its home device.
    """
    q, k, v, out, logsumexp = res
    p_size = jax.lax.psum(1, axis_name)
    i = jax.lax.axis_index(axis_name)
    t_q, n_heads, dh = q.shape
    t_k = k.shape[0]
    q_c = q.astype(compute_dtype)
    perm = _ring_perm(p_size)

    do_f = dout.astype(jnp.float32)
    # D = rowsum(dO ∘ O): the softmax-jacobian correction term, [H, Tq]
    d_corr = jnp.einsum("qhd,qhd->hq", do_f, out.astype(jnp.float32))
    do_c = dout.astype(compute_dtype)

    def tile_grads(j, k_blk, v_blk, dk_blk, dv_blk, dq):
        s = _tile_scores(q_c, k_blk, scale, compute_dtype, causal,
                         striped, i, j, t_q, t_k)
        # exact probabilities; masked entries underflow to exactly 0, so
        # no explicit backward mask is needed
        p = jnp.exp(s - logsumexp[..., None])
        p_c = p.astype(compute_dtype)
        dv_blk = dv_blk + jnp.einsum(
            "hqk,qhd->khd", p_c, do_c, preferred_element_type=jnp.float32
        )
        dp = jnp.einsum(
            "qhd,khd->hqk", do_c, v_blk.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - d_corr[..., None])).astype(compute_dtype)
        dq = dq + scale * jnp.einsum(
            "hqk,khd->qhd", ds, k_blk.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        dk_blk = dk_blk + scale * jnp.einsum(
            "hqk,qhd->khd", ds, q_c, preferred_element_type=jnp.float32
        )
        return dk_blk, dv_blk, dq

    dk0 = jnp.zeros((t_k, n_heads, dh), jnp.float32)
    dv0 = jnp.zeros((t_k, n_heads, dh), jnp.float32)
    dq0 = jnp.zeros((t_q, n_heads, dh), jnp.float32)
    dk_blk, dv_blk, dq = tile_grads(i, k, v, dk0, dv0, dq0)

    def body(t, carry):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        # the gradient accumulators rotate WITH their blocks
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
        j = (i - t) % p_size
        if causal and not striped:
            # fully-masked tile: p == 0 everywhere, all its gradient
            # contributions are exactly 0 — skip the four GEMMs
            dk_blk, dv_blk, dq = jax.lax.cond(
                j * t_k > i * t_q + (t_q - 1),
                lambda: (dk_blk, dv_blk, dq),
                lambda: tile_grads(j, k_blk, v_blk, dk_blk, dv_blk, dq),
            )
        else:
            dk_blk, dv_blk, dq = tile_grads(
                j, k_blk, v_blk, dk_blk, dv_blk, dq
            )
        return k_blk, v_blk, dk_blk, dv_blk, dq

    _, _, dk_blk, dv_blk, dq = jax.lax.fori_loop(
        1, p_size, body, (k, v, dk_blk, dv_blk, dq)
    )
    # after P-1 in-loop rotations the accumulators hold block (i+1)'s
    # gradients; one final hop returns every block home (identity at P=1)
    dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
    dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
    return dq.astype(q.dtype), dk_blk.astype(k.dtype), dv_blk.astype(v.dtype)


_ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)


def ring_attention_block(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    compute_dtype=jnp.bfloat16,
    striped: bool = False,
) -> jax.Array:
    """Exact attention for this device's query block; call inside shard_map.

    ``q``/``k``/``v``: this shard's blocks, ``[T_blk, H, dh]``. With
    ``striped=False`` the global sequence is the concatenation over the
    ``axis_name`` ring in axis order; with ``striped=True`` the caller
    has laid positions out in stripes (device i holds positions ≡ i mod
    P — see :func:`stripe_indices`), which balances causal-mask work
    across the ring. Causal masking uses GLOBAL positions either way, so
    the result equals dense causal attention over the full sequence —
    and so do its gradients (the custom VJP re-rotates K/V instead of
    saving residuals, keeping training memory at O(T/P · d) per device).
    """
    scale = float(q.shape[-1] ** -0.5 if scale is None else scale)
    return _ring_attention(axis_name, bool(causal), bool(striped), scale,
                           compute_dtype, q, k, v)


def make_ring_attention(
    mesh: Mesh,
    axis: str = "seq",
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    compute_dtype=jnp.bfloat16,
    striped: bool = False,
):
    """``fn(q, k, v)`` over GLOBAL ``[T, H, dh]`` arrays in natural
    order, sequence axis sharded over ``mesh[axis]``; jittable,
    differentiable, vmappable.

    ``striped=True`` relayouts the inputs to stripes before sharding and
    the output back to natural order — as reshape/transpose (free of
    materialized index constants; XLA lowers them as cheap copies, often
    fused into the sharding), so every device's causal tiles are ~half
    live: the load-balanced schedule for causal long-context work.
    Non-causal calls skip the relayout (nothing to balance; the result
    is identical either way).

    T must divide evenly by the axis size (shard_map's partitioning
    contract — pad the sequence to a multiple, the standard TPU practice
    for static shapes)."""
    spec = PartitionSpec(axis, None, None)
    p_size = int(mesh.shape[axis])
    # non-causal attention has no mask imbalance to balance: the stripe
    # permutations would be pure overhead for a bit-identical result
    striped = bool(striped) and bool(causal)

    def to_stripes(x):
        # natural -> striped is exactly a (b, P) -> (P, b) transpose of
        # the leading axis: new index i*b + s holds position s*P + i.
        # Same relayout as stripe_indices, without baking length-T index
        # constants into the jaxpr (XLA lowers this as a copy, not a
        # gather) — q and k/v may have different lengths; each uses its
        # own block size (the striped mask only needs a shared modulus P)
        t = x.shape[0]
        assert t % p_size == 0, f"T={t} must divide by the ring size"
        return (x.reshape(t // p_size, p_size, *x.shape[1:])
                .swapaxes(0, 1).reshape(x.shape))

    def to_natural(x):
        t = x.shape[0]
        return (x.reshape(p_size, t // p_size, *x.shape[1:])
                .swapaxes(0, 1).reshape(x.shape))

    def fn(q, k, v):
        if striped:
            q, k, v = to_stripes(q), to_stripes(k), to_stripes(v)
        out = shard_map(
            lambda qb, kb, vb: ring_attention_block(
                qb, kb, vb, axis, causal=causal, scale=scale,
                compute_dtype=compute_dtype, striped=striped,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)
        return to_natural(out) if striped else out

    return fn
