"""Ring attention — sequence-parallel exact attention over a device mesh.

Long-context support (SURVEY.md §5 "long-context / seq parallel" row;
the task brief's first-class requirement): attention over a sequence too
long for one device's memory, computed EXACTLY by sharding the sequence
axis across the mesh and rotating K/V blocks around the ring with
``jax.lax.ppermute`` while queries stay resident. Each of the P steps
combines one (Q-block, K/V-block) tile with the numerically stable online
softmax (flash-attention-style running max / normalizer / accumulator),
so memory per device is O(T/P · d) while the result is bit-for-bit the
softmax over the FULL sequence — no approximation, no quadratic-in-T
buffer anywhere.

TPU mapping: the tile products are bf16 GEMMs with f32 accumulation on
the MXU (``compute_dtype``); the P-1 ppermutes ride the ICI ring, and XLA
overlaps each block's GEMM with the next block's transfer — the classic
compute/communication pipeline of Liu et al.'s ring attention, expressed
in pure ``shard_map`` + collectives rather than hand-written RDMA.

Public surface:

* :func:`ring_attention_block` — the per-shard computation, for use
  INSIDE an existing ``shard_map`` (composes with other parallelism).
* :func:`make_ring_attention` — wraps it in ``shard_map`` over a named
  mesh axis: ``fn(q, k, v)`` on global ``[T, H, dh]`` arrays.

Parity with dense attention is pinned in ``tests/test_ring_attention.py``
on the virtual 8-device mesh (causal and full, f32 exact and bf16).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

__all__ = ["ring_attention_block", "make_ring_attention", "seq_mesh"]

#: additive mask value: large-negative (not -inf) so fully-masked tiles
#: produce exp() underflow to exactly 0 instead of NaN arithmetic
_MASK = -1e30


def seq_mesh(devices=None) -> Mesh:
    """1-D mesh over all devices with a 'seq' axis (the long-context twin
    of ``parallel.config_mesh``)."""
    import numpy as np

    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), axis_names=("seq",))


def ring_attention_block(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Exact attention for this device's query block; call inside shard_map.

    ``q``/``k``/``v``: this shard's blocks, ``[T_blk, H, dh]`` (the global
    sequence is the concatenation over the ``axis_name`` ring, in axis
    order). Causal masking uses GLOBAL positions, so the result equals
    dense causal attention over the full sequence.

    The loop runs P = mesh-axis-size steps; step t processes the K/V
    block that originated on device ``(i - t) mod P`` and then rotates
    K/V one hop around the ring. Scores/mixing are ``compute_dtype``
    GEMMs with f32 accumulation; the running (max, normalizer,
    accumulator) state is f32.
    """
    p_size = jax.lax.psum(1, axis_name)
    i = jax.lax.axis_index(axis_name)
    t_q, n_heads, dh = q.shape
    t_k = k.shape[0]
    scale = dh ** -0.5 if scale is None else scale

    q_c = q.astype(compute_dtype)
    q_pos = i * t_q + jnp.arange(t_q)
    perm = [(s, (s + 1) % p_size) for s in range(p_size)]

    def tile_update(j, k_blk, v_blk, m, l, acc):
        """Fold one (Q-block, K/V-block-from-device-j) tile into the
        running online-softmax state."""
        # [H, Tq, Tk] tile scores: compute_dtype GEMM, f32 accumulation
        s = jnp.einsum(
            "qhd,khd->hqk", q_c, k_blk.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            k_pos = j * t_k + jnp.arange(t_k)
            s = jnp.where(
                (q_pos[:, None] >= k_pos[None, :])[None], s, _MASK
            )
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "hqk,khd->hqd", p.astype(compute_dtype),
            v_blk.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    # step 0 (this device's own block) is hoisted: the loop then
    # rotates-then-computes, so exactly P-1 ppermutes ride the ring and
    # no final rotation's result is thrown away. Hoisting also seeds the
    # running max from the never-fully-masked diagonal block, and the
    # q/k/v-derived state is naturally device-varying (what shard_map
    # requires of the carry).
    m0 = jnp.full((n_heads, t_q), _MASK, jnp.float32)
    l0 = jnp.zeros((n_heads, t_q), jnp.float32)
    acc0 = jnp.zeros((n_heads, t_q, dh), jnp.float32)
    m, l, acc = tile_update(i, k, v, m0, l0, acc0)

    def body(t, carry):
        k_blk, v_blk, m, l, acc = carry
        # rotate K/V one hop; XLA overlaps this ICI transfer with the
        # tile GEMMs (the ring-attention pipeline)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        j = (i - t) % p_size  # ring origin after t rotations
        m, l, acc = tile_update(j, k_blk, v_blk, m, l, acc)
        return k_blk, v_blk, m, l, acc

    _, _, _, l, acc = jax.lax.fori_loop(
        1, p_size, body, (k, v, m, l, acc)
    )
    out = acc / l[..., None]
    return out.transpose(1, 0, 2).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    axis: str = "seq",
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    compute_dtype=jnp.bfloat16,
):
    """``fn(q, k, v)`` over GLOBAL ``[T, H, dh]`` arrays, sequence axis
    sharded over ``mesh[axis]``; jittable, differentiable, vmappable.

    T must divide evenly by the axis size (shard_map's partitioning
    contract — pad the sequence to a multiple, the standard TPU practice
    for static shapes)."""
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pre-0.8 jax
        from jax.experimental.shard_map import shard_map

    spec = PartitionSpec(axis, None, None)

    def fn(q, k, v):
        return shard_map(
            lambda qb, kb, vb: ring_attention_block(
                qb, kb, vb, axis, causal=causal, scale=scale,
                compute_dtype=compute_dtype,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)

    return fn
