"""Pure successive-halving / HyperBand bracket arithmetic.

The reference scatters this math across ``optimizers/hyperband.py`` /
``optimizers/bohb.py`` (ladder + bracket sizing) and
``optimizers/iterations/successivehalving.py`` (the promotion rule) — see
SURVEY.md §2 rows "HyperBand optimizer" and "SuccessiveHalving iteration".
Here it lives as standalone pure functions: host-side schedule construction
(static shapes, plain numpy) and a jittable / vmappable promotion kernel.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "max_sh_iterations",
    "budget_ladder",
    "BracketPlan",
    "hyperband_bracket",
    "hyperband_schedule",
    "mesh_aligned_plan",
    "sh_promotion_mask",
    "sh_promotion_mask_compiled",
    "sh_promotion_mask_np",
    "sh_resample_mask",
    "pareto_rank",
    "pareto_rank_np",
    "pareto_promotion_mask",
    "pareto_promotion_mask_np",
    "power_law_extrapolate",
]


def max_sh_iterations(min_budget: float, max_budget: float, eta: float) -> int:
    """Number of distinct successive-halving bracket shapes.

    Reference: ``max_SH_iter = floor(log(max/min)/log(eta)) + 1``
    (SURVEY.md §3.1, BOHB.__init__).
    """
    if not (max_budget > 0 and min_budget > 0 and max_budget >= min_budget):
        raise ValueError(f"need 0 < min_budget <= max_budget, got [{min_budget}, {max_budget}]")
    if eta <= 1:
        raise ValueError(f"need eta > 1, got {eta}")
    # epsilon-robust floor: log(243)/log(3) = 4.999999999999999 in f64, and a
    # bare floor would silently drop the lowest rung of an exact ladder
    ratio = np.log(max_budget / min_budget) / np.log(eta)
    return int(np.floor(ratio + 1e-9)) + 1


def budget_ladder(min_budget: float, max_budget: float, eta: float) -> np.ndarray:
    """Ascending geometric budget ladder ending exactly at ``max_budget``.

    Reference: ``budgets = max_budget * eta ** (-linspace(max_SH_iter-1, 0))``.
    """
    k = max_sh_iterations(min_budget, max_budget, eta)
    return max_budget * np.power(float(eta), -np.arange(k - 1, -1, -1, dtype=np.float64))


class BracketPlan(NamedTuple):
    """Static description of one successive-halving bracket."""

    #: configs alive at each stage, e.g. [9, 3, 1]
    num_configs: Tuple[int, ...]
    #: budget evaluated at each stage (same length)
    budgets: Tuple[float, ...]

    @property
    def n_stages(self) -> int:
        return len(self.num_configs)

    @property
    def total_evaluations(self) -> int:
        return int(sum(self.num_configs))


def hyperband_bracket(
    iteration_index: int, min_budget: float, max_budget: float, eta: float
) -> BracketPlan:
    """The bracket HyperBand runs at global iteration ``iteration_index``.

    Reference arithmetic (SURVEY.md §2 "HyperBand optimizer"):
    ``s = max_SH_iter - 1 - (i % max_SH_iter)``;
    ``n0 = ceil(max_SH_iter / (s+1) * eta**s)``;
    ``ns = [max(floor(n0 * eta**(-j)), 1) for j in 0..s]``;
    budgets are the last ``s+1`` rungs of the ladder.
    """
    k = max_sh_iterations(min_budget, max_budget, eta)
    ladder = budget_ladder(min_budget, max_budget, eta)
    s = k - 1 - (iteration_index % k)
    n0 = int(math.ceil((k / (s + 1)) * eta**s))
    ns = tuple(max(int(n0 * eta ** (-j)), 1) for j in range(s + 1))
    budgets = tuple(float(b) for b in ladder[-(s + 1):])
    return BracketPlan(num_configs=ns, budgets=budgets)


def hyperband_schedule(
    n_iterations: int, min_budget: float, max_budget: float, eta: float
) -> Tuple[BracketPlan, ...]:
    """Plans for ``n_iterations`` consecutive HyperBand iterations."""
    return tuple(
        hyperband_bracket(i, min_budget, max_budget, eta) for i in range(n_iterations)
    )


def mesh_aligned_plan(
    n_configs: int,
    min_budget: float,
    max_budget: float,
    eta: float,
    mesh_size: int = 1,
) -> BracketPlan:
    """One deep successive-halving bracket sized for a sharded mesh.

    The 100k-1M tier's schedule: stage 0 starts at ``n_configs`` and each
    rung keeps ``1/eta`` of the survivors, every stage count rounded UP to
    a multiple of ``mesh_size`` (floor ``mesh_size``) so the config axis
    shards evenly at every rung — the sharded sampler and the per-stage
    sharding constraints both need divisible widths. Budgets are the full
    ``min_budget..max_budget`` geometric ladder. The roundup waste per
    stage is at most ``mesh_size - 1`` rows — negligible against 100k+
    rows, and zero when ``n_configs`` and ``eta`` are powers of two on a
    pow2 mesh (the amortization the pow2 bucket geometry already relies
    on).
    """
    m = max(int(mesh_size), 1)
    ladder = budget_ladder(min_budget, max_budget, eta)
    depth = len(ladder)
    ns = []
    for j in range(depth):
        n = max(int(n_configs * float(eta) ** (-j)), 1)
        ns.append(max(((n + m - 1) // m) * m, m))
    # roundup of a decreasing profile can create equal neighbors but must
    # never create an INCREASING step
    for j in range(depth - 2, -1, -1):
        ns[j] = max(ns[j], ns[j + 1])
    return BracketPlan(
        num_configs=tuple(ns), budgets=tuple(float(b) for b in ladder)
    )


def sh_promotion_mask(losses: jax.Array, k) -> jax.Array:
    """The successive-halving promotion rule as a pure jittable kernel.

    ``losses`` is ``f32[n]`` for one finished stage (NaN = crashed config);
    returns ``bool[n]`` marking the ``k`` best (lowest-loss) configs.

    Reference rule (SURVEY.md §3.3): ``ranks = argsort(argsort(losses));
    advance = ranks < k`` — NaNs (crashed runs) rank last because they are
    replaced by ``+inf`` before ranking, matching the reference's
    crashed-config-never-promoted behavior. ``vmap`` over a leading bracket
    axis batches many brackets' promotions into one dispatch.
    """
    losses = jnp.asarray(losses)
    clean = jnp.where(jnp.isnan(losses), jnp.inf, losses)
    ranks = jnp.argsort(jnp.argsort(clean))
    return ranks < k


#: process-wide compiled promotion kernel, built on first use. A plain
#: module-level jit would be fine for dispatch, but routing it through
#: ``obs.runtime.tracked_jit`` journals its (single, scalar-k) compile in
#: the same ledger as the fused brackets — the whole on-device promotion
#: tier accounted under one vocabulary.
_PROMOTION_JIT = None


def sh_promotion_mask_compiled():
    """The tracked-jit compilation of :func:`sh_promotion_mask` (lazy,
    one per process). ``k`` stays a traced scalar so every bracket width
    shares one executable — callers pass it as an ``i32`` array."""
    global _PROMOTION_JIT
    if _PROMOTION_JIT is None:
        from hpbandster_tpu.obs.runtime import tracked_jit

        # donation declined explicitly (docs/perf_notes.md): the bool[n]
        # mask output cannot alias the f32[n] losses input (dtype differs)
        _PROMOTION_JIT = tracked_jit(
            sh_promotion_mask, name="sh_promotion_mask", donate_argnums=()
        )
    return _PROMOTION_JIT


def sh_promotion_mask_np(losses: np.ndarray, k) -> np.ndarray:
    """Host (numpy) twin of :func:`sh_promotion_mask` — bit-identical
    semantics (NaN -> +inf, stable double-argsort ranking, rank < k).

    The Master's per-stage bookkeeping runs over a few dozen host floats; a
    device dispatch there costs a full accelerator round-trip (tens of ms
    over a tunneled link) to rank an 81-element array. The jittable version
    stays the on-device rule inside fused brackets and vmapped sweeps.
    """
    # rank in float32, same as the device twin — float64 here would break
    # tie-handling parity with the fused on-device bracket on near-equal
    # losses (distinct in f64, tied after f32 rounding)
    losses = np.asarray(losses, dtype=np.float32)
    clean = np.where(np.isnan(losses), np.float32(np.inf), losses)
    ranks = np.argsort(np.argsort(clean, kind="stable"), kind="stable")
    return ranks < k


def pareto_rank(objectives: jax.Array) -> jax.Array:
    """Domination-count Pareto ranking, jittable: ``objectives f32[n, m]``
    (all minimized) -> ``i32[n]`` where rank 0 is the Pareto front.

    ``rank[j]`` counts the rows that dominate row ``j`` (all objectives
    <= and at least one <). A NaN in column 0 (the loss: a CRASHED
    config) invalidates its whole row — every entry becomes +inf, so a
    crashed config that happened to fail cheaply cannot ride its low
    measured cost onto the front and displace a healthy config from a
    promotion slot. A NaN in a later column alone (an unmeasured cost)
    only infs that entry: the row stays rankable by its finite loss.
    O(n^2 m) pairwise compare: the rung widths this ranks are
    bracket-sized (dozens to low thousands), far under the sort-based
    kernels' scale.
    """
    obj = jnp.asarray(objectives, jnp.float32)
    crashed = jnp.isnan(obj[:, 0])
    clean = jnp.where(
        jnp.isnan(obj) | crashed[:, None], jnp.inf, obj
    )
    # dominates[i, j]: row i dominates row j
    le = (clean[:, None, :] <= clean[None, :, :]).all(axis=-1)
    lt = (clean[:, None, :] < clean[None, :, :]).any(axis=-1)
    return (le & lt).sum(axis=0).astype(jnp.int32)


def pareto_promotion_mask(objectives: jax.Array, k) -> jax.Array:
    """Pareto-front top-``k`` promotion as a pure jittable kernel.

    ``objectives`` is ``f32[n, m]`` with column 0 the rung loss (NaN =
    crashed) and the remaining columns measured costs (NaN = unmeasured,
    treated as +inf). Selection order is (domination count, loss rank,
    row index) — Pareto fronts peel first, ties inside a front resolve
    by the loss column under the same f32 double-argsort ranking as
    :func:`sh_promotion_mask`, so the single-objective case degrades to
    exactly the successive-halving rule. Crashed rows (NaN loss) are
    NEVER promoted, whatever ``k`` — the same crash-safety contract as
    ``sh_promotion_mask``'s NaN -> +inf — and, because
    :func:`pareto_rank` infs a crashed row WHOLESALE, a config that
    crashed cheaply cannot occupy a front slot and displace a healthy
    config out of the top-k either.
    """
    obj = jnp.asarray(objectives, jnp.float32)
    loss = obj[:, 0]
    ranks = pareto_rank(obj)
    clean_loss = jnp.where(jnp.isnan(loss), jnp.inf, loss)
    loss_order = jnp.argsort(jnp.argsort(clean_loss))
    # lexicographic (pareto rank, loss rank) via two stable sorts
    # (secondary first, then primary over the permuted rows) — a
    # composite integer key `ranks * n + order` would overflow i32 near
    # n = 46341, and i64 is unavailable with x64 disabled
    by_loss = jnp.argsort(loss_order)
    final_perm = by_loss[jnp.argsort(ranks[by_loss])]
    positions = jnp.argsort(final_perm)
    return (positions < k) & ~jnp.isnan(loss)


def pareto_rank_np(objectives: np.ndarray) -> np.ndarray:
    """Host (numpy) twin of :func:`pareto_rank` — identical f32
    semantics, for the Master's host-side bracket bookkeeping."""
    obj = np.asarray(objectives, dtype=np.float32)
    crashed = np.isnan(obj[:, 0])
    clean = np.where(
        np.isnan(obj) | crashed[:, None], np.float32(np.inf), obj
    )
    le = (clean[:, None, :] <= clean[None, :, :]).all(axis=-1)
    lt = (clean[:, None, :] < clean[None, :, :]).any(axis=-1)
    return (le & lt).sum(axis=0).astype(np.int32)


def pareto_promotion_mask_np(objectives: np.ndarray, k) -> np.ndarray:
    """Host twin of :func:`pareto_promotion_mask` (stable argsorts, f32
    comparisons) — bit-identical masks to the device kernel."""
    obj = np.asarray(objectives, dtype=np.float32)
    loss = obj[:, 0]
    ranks = pareto_rank_np(obj)
    clean_loss = np.where(np.isnan(loss), np.float32(np.inf), loss)
    loss_order = np.argsort(
        np.argsort(clean_loss, kind="stable"), kind="stable"
    )
    # same two-stable-sort lexicographic selection as the device kernel
    # (overflow-free at any n, identical tie resolution)
    by_loss = np.argsort(loss_order, kind="stable")
    final_perm = by_loss[np.argsort(ranks[by_loss], kind="stable")]
    positions = np.argsort(final_perm, kind="stable")
    return (positions < k) & ~np.isnan(loss)


def power_law_extrapolate(
    budgets: jax.Array, losses: jax.Array, target_budget: float,
    floor: float = 1e-6,
) -> jax.Array:
    """Jittable twin of ``models.learning_curves.PowerLawModel.predict``,
    vectorized over configs: ``budgets f32[s]`` (ascending), ``losses
    f32[n, s]`` -> extrapolated loss at ``target_budget``, ``f32[n]``.

    Fallback semantics mirror the host model exactly: fewer than 3 points,
    non-positive residuals, all-increasing curves, or a positive slope fall
    back to the last observed value. The on-device H2BO promotion
    (``FusedH2BO``) ranks by these scores.
    """
    budgets = jnp.asarray(budgets, jnp.float32)
    losses = jnp.asarray(losses, jnp.float32)
    n, s = losses.shape
    last = losses[:, -1]
    if s < 3:
        return last

    y0, y1, y2 = losses[:, -3], losses[:, -2], losses[:, -1]
    denom = y0 + y2 - 2.0 * y1
    c_est = jnp.where(
        jnp.abs(denom) > 1e-12, (y0 * y2 - y1 * y1) / denom, -jnp.inf
    )
    ymin = losses.min(axis=1)
    # scale-aware floor (twin of PowerLawModel.predict): a fixed 1e-12 is
    # not representable next to f32 values of order 1
    floor_eff = jnp.maximum(floor, jnp.abs(ymin) * 1e-5)
    c = jnp.where(
        jnp.isfinite(c_est),
        jnp.minimum(c_est, ymin - floor_eff),
        ymin - floor_eff,
    )
    resid = losses - c[:, None]
    bad = (resid <= 0).any(axis=1) | (jnp.diff(losses, axis=1) > 0).all(axis=1)

    log_b = jnp.log(budgets)[None, :]
    log_r = jnp.log(jnp.maximum(resid, 1e-30))
    mb = log_b.mean(axis=1)
    mr = log_r.mean(axis=1)
    cov = ((log_b - mb[:, None]) * (log_r - mr[:, None])).mean(axis=1)
    var = jnp.maximum(((log_b - mb[:, None]) ** 2).mean(axis=1), 1e-30)
    slope = cov / var
    intercept = mr - slope * mb
    bad = bad | (slope > 0)
    pred = c + jnp.exp(intercept + slope * jnp.log(jnp.float32(target_budget)))
    return jnp.where(bad, last, pred)


def sh_resample_mask(
    losses: jax.Array, k, resampling_rate: float, key: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """SuccessiveResampling variant (SURVEY.md §2): promote only
    ``ceil(k * (1 - resampling_rate))`` survivors; the caller fills the rest of
    the next stage with fresh samples.

    Returns ``(promote_mask, n_resampled)``.
    """
    del key  # selection is deterministic; the resample draw happens upstream
    losses = jnp.asarray(losses)
    n_promote = jnp.maximum(
        jnp.ceil(k * (1.0 - resampling_rate)).astype(jnp.int32), 1
    )
    mask = sh_promotion_mask(losses, n_promote)
    return mask, jnp.asarray(k, jnp.int32) - n_promote
