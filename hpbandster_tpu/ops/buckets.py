"""Shape-bucketed fused brackets: a handful of programs for a whole sweep.

The compile ledger (``obs/runtime.py``) proved what the fused per-bracket
tier pays: ``make_fused_bracket_fn`` burns every bracket shape into its
trace, so a multi-bracket HyperBand sweep compiles one XLA program per
distinct ``(num_configs, budgets)`` — seven programs for the 36-bracket
1..729 rotation, each tens of seconds on a cold cache. This module spends
those ledger numbers: bracket shapes are padded up to a small GEOMETRIC
bucket set, and per-stage survivor counts become *traced* inputs, so every
bracket in a bucket shares ONE compiled program.

Bucket geometry (:func:`build_bucket_set`):

* **depths pair up**: adjacent present depths ``(d, d-1)`` share a bucket
  aligned at the ladder TAIL (their budgets are suffixes of each other in
  a HyperBand schedule). The shallower member enters at stage 1 and wastes
  only the bucket's cheapest leading rung — a bounded ~1/depth overhead —
  while halving the program count. Deeper merges are geometrically worse
  (HyperBand rungs cost roughly equal device time), so pairing is the
  default and the knob stops there.
* **widths round up to powers of two** (floor 8) of the widest member at
  each aligned rung, so one width profile covers the pair and future
  schedules reusing the shapes hit the same executables.

The bucketed kernel (:func:`fused_sh_bracket_bucketed`) reproduces
``fused_sh_bracket``'s promotion semantics exactly — NaN (crashed) rows
rank behind every clean loss and ahead of padding, ties break
index-stably, survivors keep their original order — but the top-k widths
are traced counts: promotion is a rank mask (the same double-argsort as
``sh_promotion_mask``) followed by a static-width gather, not a static
``top_k``. Rows beyond a stage's traced count are padding: evaluated
(bounded waste, see above) but never promoted and never reported.

Programs are AOT-compiled through ``tracked_jit``'s ``lower().compile()``
proxy (:func:`precompile_buckets`), optionally on a background thread so
the compile overlaps stage-0 sampling, and every compile lands in the
process-wide ledger — the budget tests in ``tests/test_buckets.py`` and
the bench budget gate read it back.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from hpbandster_tpu.ops.bracket import BracketPlan
from hpbandster_tpu.utils.lru import LRUCache

__all__ = [
    "BucketPlan",
    "BucketSet",
    "build_bucket_set",
    "bucketed_stage_telemetry",
    "fused_sh_bracket_bucketed",
    "fused_sh_bracket_bucketed_packed",
    "make_bucketed_bracket_fn",
    "precompile_buckets",
    "slice_member_stages",
]

#: crashed (NaN) losses rank here: behind any real loss, ahead of the +inf
#: padding rows — the same constant (and therefore the same ordering) as
#: ops.fused._CRASH_RANK / the host sh_promotion_mask twin
_CRASH_RANK = np.float32(3.0e38)


class BucketPlan(NamedTuple):
    """One compiled bucket: static per-stage WIDTHS + static budgets."""

    #: padded row capacity at each stage (non-increasing, pow2, floor 8)
    widths: Tuple[int, ...]
    #: concrete budget per stage (a ladder suffix; eval fns may use it as
    #: a static trip count, exactly like the unbucketed fused bracket)
    budgets: Tuple[float, ...]

    @property
    def depth(self) -> int:
        return len(self.widths)


class BucketSet(NamedTuple):
    """The bucket programs for a schedule + each shape's placement."""

    buckets: Tuple[BucketPlan, ...]
    #: (num_configs, budgets) -> (bucket_index, entry_stage)
    assignment: Dict[Tuple, Tuple[int, int]]

    def lookup(self, num_configs, budgets) -> Optional[Tuple[int, int]]:
        key = (
            tuple(int(n) for n in num_configs),
            tuple(float(b) for b in budgets),
        )
        return self.assignment.get(key)


def _pow2(n: int, floor: int = 8) -> int:
    size = floor
    while size < n:
        size *= 2
    return size


def build_bucket_set(
    plans: Sequence[BracketPlan],
    *,
    min_width: int = 8,
    mesh_size: int = 1,
) -> BucketSet:
    """Group a schedule's bracket shapes into a small geometric bucket set.

    Shapes group by depth, adjacent present depths pairing up (deepest
    first); within a bucket, shapes align at the ladder TAIL (final budgets
    coincide), the bucket's budgets are the deepest member's, and each
    rung's width is the widest aligned member count rounded up to a power
    of two (stage 0 additionally to a multiple of ``mesh_size``). A shape
    whose budgets are not a suffix of its group's deepest member — plans
    from a different ladder — falls back to its own singleton bucket
    rather than mis-aligning.

    Single-stage plans are excluded (nothing to fuse, nothing to compile).
    """
    shapes = sorted(
        {
            (
                tuple(int(n) for n in p.num_configs),
                tuple(float(b) for b in p.budgets),
            )
            for p in plans
            if len(p.num_configs) >= 2
        },
        key=lambda s: (-len(s[1]), s[1], s[0]),
    )
    by_depth: Dict[int, List[Tuple]] = {}
    for shape in shapes:
        by_depth.setdefault(len(shape[1]), []).append(shape)

    buckets: List[BucketPlan] = []
    assignment: Dict[Tuple, Tuple[int, int]] = {}
    depths = sorted(by_depth, reverse=True)
    used: set = set()
    for d in depths:
        if d in used:
            continue
        group_depths = [d]
        if (d - 1) in by_depth and (d - 1) not in used:
            group_depths.append(d - 1)
        used.update(group_depths)

        # the bucket's budgets come from the deepest member; members whose
        # budgets are not a suffix of them get singleton buckets instead
        bucket_budgets = by_depth[d][0][1]
        members: List[Tuple[Tuple, int]] = []  # (shape, entry)
        for gd in group_depths:
            for shape in by_depth[gd]:
                entry = len(bucket_budgets) - len(shape[1])
                if shape[1] == bucket_budgets[entry:]:
                    members.append((shape, entry))
                else:
                    singleton = BucketPlan(
                        widths=tuple(
                            _pow2(int(n), min_width) for n in shape[0]
                        ),
                        budgets=shape[1],
                    )
                    singleton = _mesh_pad(singleton, mesh_size)
                    assignment[shape] = (len(buckets), 0)
                    buckets.append(singleton)

        if not members:
            continue
        widths = [0] * len(bucket_budgets)
        for shape, entry in members:
            for s, n in enumerate(shape[0]):
                widths[entry + s] = max(widths[entry + s], int(n))
        # pow2 roundup of an (aligned-max) non-increasing profile stays
        # non-increasing; the running max from the right guards the
        # invariant against degenerate inputs anyway
        widths = [_pow2(w, min_width) for w in widths]
        for j in range(len(widths) - 2, -1, -1):
            widths[j] = max(widths[j], widths[j + 1])
        bucket = _mesh_pad(
            BucketPlan(widths=tuple(widths), budgets=bucket_budgets),
            mesh_size,
        )
        idx = len(buckets)
        buckets.append(bucket)
        for shape, entry in members:
            assignment[shape] = (idx, entry)
    return BucketSet(buckets=tuple(buckets), assignment=assignment)


def _mesh_pad(bucket: BucketPlan, mesh_size: int) -> BucketPlan:
    """EVERY stage width padded to a mesh multiple, so each rung of the
    ladder stays evenly shardable over the config axis (the per-stage
    :func:`~hpbandster_tpu.ops.fused.shard_rows` constraints only apply to
    divisible widths).

    The waste is amortized by the pow2 bucket geometry: widths are already
    powers of two (floor 8), so on a pow2 mesh any width >= mesh_size is a
    multiple for free and only tail rungs narrower than the mesh pad up to
    one row per shard. Per-stage relative waste is bounded by
    ``(ceil(w/m)*m - w)/w <= (m-1)/w`` — exactly zero on pow2 meshes with
    ``w >= m`` (docs/perf_notes.md "Mesh sharding")."""
    m = max(int(mesh_size), 1)
    if m == 1 or all(w % m == 0 for w in bucket.widths):
        return bucket
    widths = [((w + m - 1) // m) * m for w in bucket.widths]
    # mesh roundup of a non-increasing profile stays non-increasing, but
    # guard the invariant like build_bucket_set does
    for j in range(len(widths) - 2, -1, -1):
        widths[j] = max(widths[j], widths[j + 1])
    return BucketPlan(widths=tuple(widths), budgets=bucket.budgets)


def fused_sh_bracket_bucketed(
    eval_fn: Callable,
    vectors,
    counts,
    bucket: BucketPlan,
    mesh=None,
    axis: str = "config",
):
    """One bucketed bracket, traceable under ``jit``.

    ``vectors`` is ``f32[widths[0], d]`` (member rows first, zero-padded);
    ``counts`` is ``i32[depth]`` — the member's TRUE per-stage config
    counts, 0 for stages before its entry. Returns per-stage
    ``(indices, losses)`` at bucket widths; rows past ``counts[t]`` are
    padding (see :func:`slice_member_stages`).

    Promotion reproduces ``fused_sh_bracket`` / ``sh_promotion_mask``
    exactly (crash rank, index-stable ties, original-order survivors) with
    the top-k width a traced count: rank < k masks survivors, a stable
    index-keyed argsort packs them first, a static slice narrows to the
    next stage's width. While a stage's count is 0 (pre-entry) the carry
    is the identity head slice, so entering rows survive untouched.

    ``mesh``/``axis`` keep each stage's rows sharded over the config axis
    (``ops.fused.shard_rows``) — the rank mask then reduces across shards
    on-device (ICI collectives) and no stage is ever gathered to one
    device. Values are bit-identical with or without the mesh.
    """
    import jax
    import jax.numpy as jnp

    from hpbandster_tpu.ops.fused import shard_rows

    widths = bucket.widths
    budgets = bucket.budgets
    depth = len(widths)
    counts = jnp.asarray(counts, jnp.int32)

    def eval_stage(vecs, budget: float):
        return jax.vmap(lambda v: eval_fn(v, budget))(vecs).astype(jnp.float32)

    cur_vecs = shard_rows(vectors, mesh, axis)
    cur_idx = jnp.arange(widths[0], dtype=jnp.int32)
    out = []
    for t in range(depth):
        losses_t = eval_stage(cur_vecs, float(budgets[t]))
        out.append((cur_idx, losses_t))
        if t + 1 == depth:
            break
        w, w_next = widths[t], widths[t + 1]
        rows = jnp.arange(w, dtype=jnp.int32)
        valid = rows < counts[t]
        key = jnp.where(jnp.isnan(losses_t), _CRASH_RANK, losses_t)
        key = jnp.where(valid, key, jnp.inf)
        # double argsort = value rank with index-stable ties, the same
        # selection top_k makes (and sh_promotion_mask_np replays host-side)
        ranks = jnp.argsort(jnp.argsort(key, stable=True), stable=True)
        promote = (ranks < counts[t + 1]) & valid
        # survivors first, original order among them — then the rest, so a
        # static head slice is the gather (matches fused's sorted top_k)
        order = jnp.argsort(jnp.where(promote, rows, w + rows), stable=True)
        sel_ranked = order[:w_next]
        sel_identity = jnp.arange(w_next, dtype=jnp.int32)
        sel = jnp.where(counts[t] > 0, sel_ranked, sel_identity)
        cur_vecs = shard_rows(cur_vecs[sel], mesh, axis)
        cur_idx = cur_idx[sel]
    return out


def fused_sh_bracket_bucketed_packed(
    eval_fn: Callable,
    vectors,
    counts,
    bucket: BucketPlan,
):
    """A LANE-PACKED stack of bucketed brackets, traceable under ``jit``.

    ``vectors`` is ``f32[P, widths[0], d]`` and ``counts`` ``i32[P, depth]``
    — ``P`` independent member brackets of the SAME bucket, one per lane
    (the serving tier's cross-tenant megabatch, ``serve/megabatch.py``).
    Each lane runs :func:`fused_sh_bracket_bucketed` under ``vmap``;
    brackets are independent SH ladders, so lanes never interact and each
    lane's promotions are BIT-IDENTICAL to dispatching that bracket alone
    (pinned by ``tests/test_serve.py``). Returns the packed per-lane
    ``(i32[P, sum(widths)], f32[P, sum(widths)])`` pair — the same
    flat-concatenated layout the solo ``_BucketRunner`` ships, with a
    leading lane axis.

    A lane whose counts are all zero is pure padding: every stage carries
    the identity slice and its rows are evaluated (bounded waste, exactly
    the bucket-padding trade) but never reported to anyone.
    """
    import jax
    import jax.numpy as jnp

    def one_lane(vecs, cnts):
        stages = fused_sh_bracket_bucketed(eval_fn, vecs, cnts, bucket)
        return (
            jnp.concatenate([s[0] for s in stages]),
            jnp.concatenate([s[1] for s in stages]),
        )

    return jax.vmap(one_lane)(vectors, jnp.asarray(counts, jnp.int32))


def bucketed_stage_telemetry(stages, counts, edges):
    """Jittable device-metrics accumulation over one BUCKETED bracket's
    stages: per-stage ``(histogram i32[n_bins], crash_count i32[])`` in
    exactly the schema the fused-sweep accumulator emits
    (``ops.fused.stage_telemetry`` over ``obs/device_metrics.py`` bin
    edges) — the seam through which the bucketed/megabatch executor tier
    joins the device metrics plane.

    A bucketed stage's rows past its traced ``counts[t]`` are padding:
    evaluated but never reported, so they are masked out of BOTH the
    histogram and the crash count here (a padding row's garbage loss —
    or NaN — must not read as telemetry). Output shapes are fixed by the
    bucket depth and bin count alone.
    """
    import jax.numpy as jnp

    from hpbandster_tpu.ops.fused import stage_telemetry

    counts = jnp.asarray(counts, jnp.int32)
    out = []
    for t, (_idx_t, losses_t) in enumerate(stages):
        live = jnp.arange(losses_t.shape[0], dtype=jnp.int32) < counts[t]
        # padding rows become NaN for the histogram mask, then their
        # (artificial) crash contribution is subtracted back out
        masked = jnp.where(live, losses_t, jnp.nan)
        hist, crashes = stage_telemetry(masked, edges)
        crashes = crashes - jnp.sum(~live).astype(jnp.int32)
        out.append((hist, crashes))
    return out


def slice_member_stages(
    stages: List[Tuple], plan: BracketPlan, entry: int
) -> List[Tuple]:
    """Cut a bucket dispatch's stage list down to one member bracket's
    results: bucket stage ``entry + s`` holds member stage ``s`` in its
    first ``plan.num_configs[s]`` rows."""
    out = []
    for s, k in enumerate(plan.num_configs):
        idx, losses = stages[entry + s]
        out.append((idx[: int(k)], losses[: int(k)]))
    return out


#: process-wide compiled-bucket cache — same policy as ops.fused's
#: _FUSED_FN_CACHE: a (objective, bucket, mesh) combination compiles once
#: per process, bounded so throwaway closures cannot pin executables
_BUCKET_FN_CACHE: LRUCache = LRUCache(maxsize=64)


class _BucketRunner:
    """One bucket's compiled program + dispatch/unpack plumbing.

    The executable is built exactly once (lazily on first dispatch, or
    ahead of time via :meth:`ensure_compiled` / :func:`precompile_buckets`)
    through the tracked ``lower().compile()`` proxy, so the compile ledger
    sees exactly one compile per bucket — the number the budget tests and
    the bench gate assert on. Dispatches always run the AOT executable;
    the jit wrapper itself is never called (that would compile a second,
    untracked-by-AOT cache entry).
    """

    def __init__(self, eval_fn, bucket: BucketPlan, mesh=None, axis="config"):
        from hpbandster_tpu.obs.runtime import tracked_jit

        self.bucket = bucket
        self.mesh = mesh
        self.axis = axis
        self._lock = threading.Lock()
        self._compiled = None
        self._dim: Optional[int] = None

        def bracket(vectors, counts):
            stages = fused_sh_bracket_bucketed(
                eval_fn, vectors, counts, bucket, mesh=mesh, axis=axis
            )
            import jax.numpy as jnp

            return (
                jnp.concatenate([s[0] for s in stages]),
                jnp.concatenate([s[1] for s in stages]),
            )

        jit_kwargs: Dict = {
            # donation declined explicitly (docs/perf_notes.md): the
            # packed (idx, loss) outputs cannot alias the [W0, d] vectors
            # input, so donating it would only emit a per-compile warning
            "donate_argnums": (),
        }
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            shard = NamedSharding(mesh, PartitionSpec(axis))
            rep = NamedSharding(mesh, PartitionSpec())
            jit_kwargs["in_shardings"] = (shard, rep)
        self._wrapper = tracked_jit(
            bracket, name="fused_bucket", **jit_kwargs
        )

    # ------------------------------------------------------------- compile
    def ensure_compiled(self, d: int):
        """AOT-compile the bucket program for ``d``-dim vectors (idempotent,
        thread-safe — the background precompiler and a dispatching executor
        may race here)."""
        with self._lock:
            if self._compiled is not None:
                if self._dim != int(d):
                    raise ValueError(
                        f"bucket program compiled for d={self._dim}, "
                        f"asked for d={d}"
                    )
                return self._compiled
            import jax
            import jax.numpy as jnp

            specs = (
                jax.ShapeDtypeStruct((self.bucket.widths[0], int(d)), jnp.float32),
                jax.ShapeDtypeStruct((self.bucket.depth,), jnp.int32),
            )
            self._compiled = self._wrapper.lower(*specs).compile()
            self._dim = int(d)
            return self._compiled

    # ------------------------------------------------------------ dispatch
    def dispatch(self, vectors: np.ndarray, counts: Sequence[int]):
        """Launch one member bracket; returns packed DEVICE arrays without
        blocking (callers overlap several brackets before fetching).

        ``vectors`` is ``f32[n0, d]`` member rows (padded up here);
        ``counts`` the member's true per-stage counts, entry-aligned
        (length = bucket depth, leading zeros for pre-entry stages).
        """
        from hpbandster_tpu.obs.runtime import note_transfer

        vectors = np.asarray(vectors, np.float32)
        w0 = self.bucket.widths[0]
        if vectors.shape[0] > w0:
            raise ValueError(
                f"{vectors.shape[0]} rows do not fit bucket width {w0}"
            )
        if vectors.shape[0] < w0:
            vectors = np.concatenate(
                [vectors, np.zeros((w0 - vectors.shape[0], vectors.shape[1]),
                                   np.float32)]
            )
        counts = np.asarray(counts, np.int32)
        if counts.shape != (self.bucket.depth,):
            raise ValueError(
                f"counts must be i32[{self.bucket.depth}], got {counts.shape}"
            )
        compiled = self.ensure_compiled(vectors.shape[1])
        note_transfer("h2d", vectors.nbytes + counts.nbytes, buffers=2)
        if self.mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            shard = NamedSharding(self.mesh, PartitionSpec(self.axis))
            rep = NamedSharding(self.mesh, PartitionSpec())
            vecs_host = vectors
            counts_host = counts
            vectors = jax.make_array_from_callback(
                vecs_host.shape, shard, lambda idx: vecs_host[idx]
            )
            counts = jax.make_array_from_callback(
                counts_host.shape, rep, lambda idx: counts_host[idx]
            )
        return compiled(vectors, counts)

    def unpack(self, packed) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Blocking fetch of a dispatch's packed pair, cut back into
        per-stage (idx, losses) at bucket widths."""
        import jax

        from hpbandster_tpu.obs.runtime import note_transfer

        idx_flat, loss_flat = jax.device_get(tuple(packed))
        note_transfer("d2h", idx_flat.nbytes + loss_flat.nbytes, buffers=2)
        out, off = [], 0
        for w in self.bucket.widths:
            out.append((idx_flat[off:off + w], loss_flat[off:off + w]))
            off += w
        return out

    def run_member(self, vectors: np.ndarray, plan: BracketPlan, entry: int):
        """Dispatch + fetch one member bracket, returning its TRUE-shape
        per-stage ``(indices, losses)`` — the drop-in equivalent of a
        ``make_fused_bracket_fn`` runner call."""
        counts = np.zeros(self.bucket.depth, np.int32)
        for s, k in enumerate(plan.num_configs):
            counts[entry + s] = int(k)
        packed = self.dispatch(np.asarray(vectors, np.float32), counts)
        return slice_member_stages(self.unpack(packed), plan, entry)


def make_bucketed_bracket_fn(
    eval_fn: Callable,
    bucket: BucketPlan,
    mesh=None,
    axis: str = "config",
) -> _BucketRunner:
    """The (process-cached) runner for one bucket program."""
    key = (eval_fn, bucket, mesh, axis)
    runner = _BUCKET_FN_CACHE.get(key)
    if runner is None:
        runner = _BucketRunner(eval_fn, bucket, mesh=mesh, axis=axis)
        _BUCKET_FN_CACHE[key] = runner
    return runner


class _Precompile:
    """Handle over a (possibly background) bucket-set compilation."""

    def __init__(self, runners: List[_BucketRunner], d: int):
        self._runners = runners
        self._d = int(d)
        self._done = threading.Event()
        self.errors: List[Exception] = []

    def _work(self) -> None:
        try:
            for r in self._runners:
                try:
                    r.ensure_compiled(self._d)
                except Exception as e:  # noqa: BLE001 — reported via wait()
                    self.errors.append(e)
        finally:
            self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every bucket is compiled; True when finished."""
        return self._done.wait(timeout)


def precompile_buckets(
    eval_fn: Callable,
    bucket_set: BucketSet,
    d: int,
    mesh=None,
    axis: str = "config",
    background: bool = True,
) -> _Precompile:
    """AOT-compile every bucket program through the tracked
    ``lower().compile()`` proxy — in a daemon thread by default, so the
    compile overlaps the optimizer's stage-0 sampling instead of
    serializing in front of the first dispatch. Returns a handle whose
    ``wait()`` blocks until the set is ready (dispatching earlier is safe:
    the runner's own lock serializes on the in-flight compile)."""
    runners = [
        make_bucketed_bracket_fn(eval_fn, b, mesh=mesh, axis=axis)
        for b in bucket_set.buckets
    ]
    handle = _Precompile(runners, d)
    if background:
        threading.Thread(
            target=handle._work, daemon=True, name="bucket-precompile"
        ).start()
    else:
        handle._work()
    return handle
