"""Shape-bucketed fused brackets: a handful of programs for a whole sweep.

The compile ledger (``obs/runtime.py``) proved what the fused per-bracket
tier pays: ``make_fused_bracket_fn`` burns every bracket shape into its
trace, so a multi-bracket HyperBand sweep compiles one XLA program per
distinct ``(num_configs, budgets)`` — seven programs for the 36-bracket
1..729 rotation, each tens of seconds on a cold cache. This module spends
those ledger numbers: bracket shapes are padded up to a small GEOMETRIC
bucket set, and per-stage survivor counts become *traced* inputs, so every
bracket in a bucket shares ONE compiled program.

Bucket geometry (:func:`build_bucket_set`):

* **depths pair up**: adjacent present depths ``(d, d-1)`` share a bucket
  aligned at the ladder TAIL (their budgets are suffixes of each other in
  a HyperBand schedule). The shallower member enters at stage 1 and wastes
  only the bucket's cheapest leading rung — a bounded ~1/depth overhead —
  while halving the program count. Deeper merges are geometrically worse
  (HyperBand rungs cost roughly equal device time), so pairing is the
  default and the knob stops there.
* **widths round up to powers of two** (floor 8) of the widest member at
  each aligned rung, so one width profile covers the pair and future
  schedules reusing the shapes hit the same executables.

The bucketed kernel (:func:`fused_sh_bracket_bucketed`) reproduces
``fused_sh_bracket``'s promotion semantics exactly — NaN (crashed) rows
rank behind every clean loss and ahead of padding, ties break
index-stably, survivors keep their original order — but the top-k widths
are traced counts: promotion is a rank mask (the same double-argsort as
``sh_promotion_mask``) followed by a static-width gather, not a static
``top_k``. Rows beyond a stage's traced count are padding: evaluated
(bounded waste, see above) but never promoted and never reported.

Programs are AOT-compiled through ``tracked_jit``'s ``lower().compile()``
proxy (:func:`precompile_buckets`), optionally on a background thread so
the compile overlaps stage-0 sampling, and every compile lands in the
process-wide ledger — the budget tests in ``tests/test_buckets.py`` and
the bench budget gate read it back.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from hpbandster_tpu.ops.bracket import BracketPlan
from hpbandster_tpu.utils.lru import LRUCache

__all__ = [
    "BucketPlan",
    "BucketSet",
    "build_bucket_set",
    "bucketed_stage_telemetry",
    "fused_sh_bracket_bucketed",
    "fused_sh_bracket_bucketed_packed",
    "fused_sh_bracket_bucketed_packed_carry",
    "make_bucketed_bracket_fn",
    "member_counts_for",
    "member_telemetry_record",
    "precompile_buckets",
    "slice_member_stages",
]

#: crashed (NaN) losses rank here: behind any real loss, ahead of the +inf
#: padding rows — the same constant (and therefore the same ordering) as
#: ops.fused._CRASH_RANK / the host sh_promotion_mask twin
_CRASH_RANK = np.float32(3.0e38)


class BucketPlan(NamedTuple):
    """One compiled bucket: static per-stage WIDTHS + static budgets."""

    #: padded row capacity at each stage (non-increasing, pow2, floor 8)
    widths: Tuple[int, ...]
    #: concrete budget per stage (a ladder suffix; eval fns may use it as
    #: a static trip count, exactly like the unbucketed fused bracket)
    budgets: Tuple[float, ...]

    @property
    def depth(self) -> int:
        return len(self.widths)


class BucketSet(NamedTuple):
    """The bucket programs for a schedule + each shape's placement."""

    buckets: Tuple[BucketPlan, ...]
    #: (num_configs, budgets) -> (bucket_index, entry_stage)
    assignment: Dict[Tuple, Tuple[int, int]]

    def lookup(self, num_configs, budgets) -> Optional[Tuple[int, int]]:
        key = (
            tuple(int(n) for n in num_configs),
            tuple(float(b) for b in budgets),
        )
        return self.assignment.get(key)


def _pow2(n: int, floor: int = 8) -> int:
    size = floor
    while size < n:
        size *= 2
    return size


def build_bucket_set(
    plans: Sequence[BracketPlan],
    *,
    min_width: int = 8,
    mesh_size: int = 1,
) -> BucketSet:
    """Group a schedule's bracket shapes into a small geometric bucket set.

    Shapes group by depth, adjacent present depths pairing up (deepest
    first); within a bucket, shapes align at the ladder TAIL (final budgets
    coincide), the bucket's budgets are the deepest member's, and each
    rung's width is the widest aligned member count rounded up to a power
    of two (stage 0 additionally to a multiple of ``mesh_size``). A shape
    whose budgets are not a suffix of its group's deepest member — plans
    from a different ladder — falls back to its own singleton bucket
    rather than mis-aligning.

    Single-stage plans are excluded (nothing to fuse, nothing to compile).
    """
    shapes = sorted(
        {
            (
                tuple(int(n) for n in p.num_configs),
                tuple(float(b) for b in p.budgets),
            )
            for p in plans
            if len(p.num_configs) >= 2
        },
        key=lambda s: (-len(s[1]), s[1], s[0]),
    )
    by_depth: Dict[int, List[Tuple]] = {}
    for shape in shapes:
        by_depth.setdefault(len(shape[1]), []).append(shape)

    buckets: List[BucketPlan] = []
    assignment: Dict[Tuple, Tuple[int, int]] = {}
    depths = sorted(by_depth, reverse=True)
    used: set = set()
    for d in depths:
        if d in used:
            continue
        group_depths = [d]
        if (d - 1) in by_depth and (d - 1) not in used:
            group_depths.append(d - 1)
        used.update(group_depths)

        # the bucket's budgets come from the deepest member; members whose
        # budgets are not a suffix of them get singleton buckets instead
        bucket_budgets = by_depth[d][0][1]
        members: List[Tuple[Tuple, int]] = []  # (shape, entry)
        for gd in group_depths:
            for shape in by_depth[gd]:
                entry = len(bucket_budgets) - len(shape[1])
                if shape[1] == bucket_budgets[entry:]:
                    members.append((shape, entry))
                else:
                    singleton = BucketPlan(
                        widths=tuple(
                            _pow2(int(n), min_width) for n in shape[0]
                        ),
                        budgets=shape[1],
                    )
                    singleton = _mesh_pad(singleton, mesh_size)
                    assignment[shape] = (len(buckets), 0)
                    buckets.append(singleton)

        if not members:
            continue
        widths = [0] * len(bucket_budgets)
        for shape, entry in members:
            for s, n in enumerate(shape[0]):
                widths[entry + s] = max(widths[entry + s], int(n))
        # pow2 roundup of an (aligned-max) non-increasing profile stays
        # non-increasing; the running max from the right guards the
        # invariant against degenerate inputs anyway
        widths = [_pow2(w, min_width) for w in widths]
        for j in range(len(widths) - 2, -1, -1):
            widths[j] = max(widths[j], widths[j + 1])
        bucket = _mesh_pad(
            BucketPlan(widths=tuple(widths), budgets=bucket_budgets),
            mesh_size,
        )
        idx = len(buckets)
        buckets.append(bucket)
        for shape, entry in members:
            assignment[shape] = (idx, entry)
    return BucketSet(buckets=tuple(buckets), assignment=assignment)


def _mesh_pad(bucket: BucketPlan, mesh_size: int) -> BucketPlan:
    """EVERY stage width padded to a mesh multiple, so each rung of the
    ladder stays evenly shardable over the config axis (the per-stage
    :func:`~hpbandster_tpu.ops.fused.shard_rows` constraints only apply to
    divisible widths).

    The waste is amortized by the pow2 bucket geometry: widths are already
    powers of two (floor 8), so on a pow2 mesh any width >= mesh_size is a
    multiple for free and only tail rungs narrower than the mesh pad up to
    one row per shard. Per-stage relative waste is bounded by
    ``(ceil(w/m)*m - w)/w <= (m-1)/w`` — exactly zero on pow2 meshes with
    ``w >= m`` (docs/perf_notes.md "Mesh sharding")."""
    m = max(int(mesh_size), 1)
    if m == 1 or all(w % m == 0 for w in bucket.widths):
        return bucket
    widths = [((w + m - 1) // m) * m for w in bucket.widths]
    # mesh roundup of a non-increasing profile stays non-increasing, but
    # guard the invariant like build_bucket_set does
    for j in range(len(widths) - 2, -1, -1):
        widths[j] = max(widths[j], widths[j + 1])
    return BucketPlan(widths=tuple(widths), budgets=bucket.budgets)


def fused_sh_bracket_bucketed(
    eval_fn: Callable,
    vectors,
    counts,
    bucket: BucketPlan,
    mesh=None,
    axis: str = "config",
):
    """One bucketed bracket, traceable under ``jit``.

    ``vectors`` is ``f32[widths[0], d]`` (member rows first, zero-padded);
    ``counts`` is ``i32[depth]`` — the member's TRUE per-stage config
    counts, 0 for stages before its entry. Returns per-stage
    ``(indices, losses)`` at bucket widths; rows past ``counts[t]`` are
    padding (see :func:`slice_member_stages`).

    Promotion reproduces ``fused_sh_bracket`` / ``sh_promotion_mask``
    exactly (crash rank, index-stable ties, original-order survivors) with
    the top-k width a traced count: rank < k masks survivors, a stable
    index-keyed argsort packs them first, a static slice narrows to the
    next stage's width. While a stage's count is 0 (pre-entry) the carry
    is the identity head slice, so entering rows survive untouched.

    ``mesh``/``axis`` keep each stage's rows sharded over the config axis
    (``ops.fused.shard_rows``) — the rank mask then reduces across shards
    on-device (ICI collectives) and no stage is ever gathered to one
    device. Values are bit-identical with or without the mesh.
    """
    import jax
    import jax.numpy as jnp

    from hpbandster_tpu.ops.fused import shard_rows

    widths = bucket.widths
    budgets = bucket.budgets
    depth = len(widths)
    counts = jnp.asarray(counts, jnp.int32)

    def eval_stage(vecs, budget: float):
        return jax.vmap(lambda v: eval_fn(v, budget))(vecs).astype(jnp.float32)

    cur_vecs = shard_rows(vectors, mesh, axis)
    cur_idx = jnp.arange(widths[0], dtype=jnp.int32)
    out = []
    for t in range(depth):
        losses_t = eval_stage(cur_vecs, float(budgets[t]))
        out.append((cur_idx, losses_t))
        if t + 1 == depth:
            break
        w, w_next = widths[t], widths[t + 1]
        rows = jnp.arange(w, dtype=jnp.int32)
        valid = rows < counts[t]
        key = jnp.where(jnp.isnan(losses_t), _CRASH_RANK, losses_t)
        key = jnp.where(valid, key, jnp.inf)
        # double argsort = value rank with index-stable ties, the same
        # selection top_k makes (and sh_promotion_mask_np replays host-side)
        ranks = jnp.argsort(jnp.argsort(key, stable=True), stable=True)
        promote = (ranks < counts[t + 1]) & valid
        # survivors first, original order among them — then the rest, so a
        # static head slice is the gather (matches fused's sorted top_k)
        order = jnp.argsort(jnp.where(promote, rows, w + rows), stable=True)
        sel_ranked = order[:w_next]
        sel_identity = jnp.arange(w_next, dtype=jnp.int32)
        sel = jnp.where(counts[t] > 0, sel_ranked, sel_identity)
        cur_vecs = shard_rows(cur_vecs[sel], mesh, axis)
        cur_idx = cur_idx[sel]
    return out


def _lane_stages(eval_fn: Callable, bucket: BucketPlan):
    """ONE definition of a packed program's lane body: run the bucketed
    bracket and flat-concatenate its stages — shared by the uncarried
    and carried packed kernels (and their telemetry variants), so a
    future change to the lane semantics cannot diverge between the
    compiled programs."""
    import jax.numpy as jnp

    def run(vecs, cnts):
        stages = fused_sh_bracket_bucketed(eval_fn, vecs, cnts, bucket)
        return (
            stages,
            jnp.concatenate([s[0] for s in stages]),
            jnp.concatenate([s[1] for s in stages]),
        )

    return run


def _lane_telemetry(stages, cnts, edges):
    """Per-lane telemetry stack: ``(i32[depth, n_bins], i32[depth])``
    from :func:`bucketed_stage_telemetry` (padding-masked)."""
    import jax.numpy as jnp

    tel = bucketed_stage_telemetry(stages, cnts, edges)
    return (
        jnp.stack([h for h, _ in tel]),
        jnp.stack([c for _, c in tel]),
    )


def fused_sh_bracket_bucketed_packed(
    eval_fn: Callable,
    vectors,
    counts,
    bucket: BucketPlan,
    telemetry_edges=None,
):
    """A LANE-PACKED stack of bucketed brackets, traceable under ``jit``.

    ``vectors`` is ``f32[P, widths[0], d]`` and ``counts`` ``i32[P, depth]``
    — ``P`` independent member brackets of the SAME bucket, one per lane
    (the serving tier's cross-tenant megabatch, ``serve/megabatch.py``).
    Each lane runs :func:`fused_sh_bracket_bucketed` under ``vmap``;
    brackets are independent SH ladders, so lanes never interact and each
    lane's promotions are BIT-IDENTICAL to dispatching that bracket alone
    (pinned by ``tests/test_serve.py``). Returns the packed per-lane
    ``(i32[P, sum(widths)], f32[P, sum(widths)])`` pair — the same
    flat-concatenated layout the solo ``_BucketRunner`` ships, with a
    leading lane axis. With ``telemetry_edges`` (the device-metrics bin
    schema) the return gains per-lane ``(hist i32[P, depth, n_bins],
    crashes i32[P, depth])`` from :func:`bucketed_stage_telemetry`.

    A lane whose counts are all zero is pure padding: every stage carries
    the identity slice and its rows are evaluated (bounded waste, exactly
    the bucket-padding trade) but never reported to anyone.
    """
    import jax
    import jax.numpy as jnp

    body = _lane_stages(eval_fn, bucket)

    def one_lane(vecs, cnts):
        stages, idx, loss = body(vecs, cnts)
        if telemetry_edges is None:
            return idx, loss
        hist, crashes = _lane_telemetry(stages, cnts, telemetry_edges)
        return idx, loss, hist, crashes

    return jax.vmap(one_lane)(vectors, jnp.asarray(counts, jnp.int32))


def fused_sh_bracket_bucketed_packed_carry(
    eval_fn: Callable,
    vectors,
    counts,
    carry,
    reset,
    bucket: BucketPlan,
    telemetry_edges=None,
):
    """The CARRIED lane-packed kernel — the continuous-batching tier's
    device program (``serve/continuous.py``).

    Identical lane semantics to :func:`fused_sh_bracket_bucketed_packed`
    (each lane's promotions are bit-identical to a solo dispatch,
    pinned), plus a per-lane incumbent state threaded device-to-device
    across chunk dispatches the way the resident sweep threads its obs
    state (``ops/sweep.py``):

    * ``carry`` is ``f32[P]`` in RANK space
      (:func:`~hpbandster_tpu.ops.sweep.init_lane_state`): a real loss
      is itself, crashed-only is the shared crash-rank constant, and
      ``+inf`` means the lane has observed nothing;
    * ``reset`` is ``bool[P]``: True re-initializes the lane's carry
      BEFORE this chunk folds in (a lane whose owner changed at the
      chunk boundary must not leak the previous tenant's incumbent);
    * each lane folds ``min(carry, best final-stage loss)`` where NaN
      rows rank at the crash constant and rows past the lane's traced
      final count are ``+inf`` — a zero-count (masked-empty) lane folds
      ``+inf`` and its carry passes through untouched.

    Returns ``((i32[P, sum(widths)], f32[P, sum(widths)]), f32[P])`` —
    the packed per-lane stage pair and the updated carry, which the
    caller keeps ON DEVICE between chunks (the whole point: tenant churn
    never re-uploads or re-compiles, and the incumbent trail needs no
    per-chunk d2h). With ``telemetry_edges`` the return gains a third
    element: per-lane ``(hist i32[P, depth, n_bins],
    crashes i32[P, depth])`` — the device metrics plane riding the same
    dispatch (padding lanes mask to zero).
    """
    import jax
    import jax.numpy as jnp

    counts = jnp.asarray(counts, jnp.int32)
    carry = jnp.asarray(carry, jnp.float32)
    reset = jnp.asarray(reset, jnp.bool_)
    body = _lane_stages(eval_fn, bucket)

    def one_lane(vecs, cnts, c_in, rst):
        stages, idx, loss = body(vecs, cnts)
        _f_idx, f_loss = stages[-1]
        w_last = bucket.widths[-1]
        valid = jnp.arange(w_last, dtype=jnp.int32) < cnts[-1]
        rank = jnp.where(jnp.isnan(f_loss), jnp.float32(_CRASH_RANK), f_loss)
        rank = jnp.where(valid, rank, jnp.inf)
        base = jnp.where(rst, jnp.inf, c_in)
        new_c = jnp.minimum(base, jnp.min(rank))
        if telemetry_edges is None:
            return idx, loss, new_c
        hist, crashes = _lane_telemetry(stages, cnts, telemetry_edges)
        return idx, loss, new_c, hist, crashes

    out = jax.vmap(one_lane)(vectors, counts, carry, reset)
    if telemetry_edges is None:
        idx, loss, new_carry = out
        return (idx, loss), new_carry
    idx, loss, new_carry, hist, crashes = out
    return (idx, loss), new_carry, (hist, crashes)


def bucketed_stage_telemetry(stages, counts, edges):
    """Jittable device-metrics accumulation over one BUCKETED bracket's
    stages: per-stage ``(histogram i32[n_bins], crash_count i32[])`` in
    exactly the schema the fused-sweep accumulator emits
    (``ops.fused.stage_telemetry`` over ``obs/device_metrics.py`` bin
    edges) — the seam through which the bucketed/megabatch executor tier
    joins the device metrics plane.

    A bucketed stage's rows past its traced ``counts[t]`` are padding:
    evaluated but never reported, so they are masked out of BOTH the
    histogram and the crash count here (a padding row's garbage loss —
    or NaN — must not read as telemetry). Output shapes are fixed by the
    bucket depth and bin count alone.
    """
    import jax.numpy as jnp

    from hpbandster_tpu.ops.fused import stage_telemetry

    counts = jnp.asarray(counts, jnp.int32)
    out = []
    for t, (_idx_t, losses_t) in enumerate(stages):
        live = jnp.arange(losses_t.shape[0], dtype=jnp.int32) < counts[t]
        # padding rows become NaN for the histogram mask, then their
        # (artificial) crash contribution is subtracted back out
        masked = jnp.where(live, losses_t, jnp.nan)
        hist, crashes = stage_telemetry(masked, edges)
        crashes = crashes - jnp.sum(~live).astype(jnp.int32)
        out.append((hist, crashes))
    return out


def slice_member_stages(
    stages: List[Tuple], plan: BracketPlan, entry: int
) -> List[Tuple]:
    """Cut a bucket dispatch's stage list down to one member bracket's
    results: bucket stage ``entry + s`` holds member stage ``s`` in its
    first ``plan.num_configs[s]`` rows."""
    out = []
    for s, k in enumerate(plan.num_configs):
        idx, losses = stages[entry + s]
        out.append((idx[: int(k)], losses[: int(k)]))
    return out


def member_counts_for(
    bucket: BucketPlan, plan: BracketPlan, entry: int
) -> np.ndarray:
    """One member bracket's entry-aligned traced-count vector
    (``i32[bucket.depth]``, zeros for pre-entry stages) — the ONE
    definition of the counts layout every dispatcher builds."""
    counts = np.zeros(bucket.depth, np.int32)
    for s, k in enumerate(plan.num_configs):
        counts[entry + s] = int(k)
    return counts


def member_telemetry_record(hist, crashes, counts, budgets, stages):
    """One member bracket's fetched in-trace telemetry -> the decoded
    ``device_telemetry`` record (``obs/device_metrics.py`` schema).

    ``hist``/``crashes`` are the :func:`bucketed_stage_telemetry` outputs
    for this member's dispatch (or its lane of a packed dispatch),
    bucket-depth rows; ``counts`` the member's entry-aligned traced
    counts; ``budgets`` the bucket's budgets; ``stages`` the member's
    TRUE-shape per-stage ``(idx, losses)`` (for the best-final fold —
    already fetched, no extra device work). Returns None for an all-zero
    (padding) lane. The record shape matches what the fused drivers
    journal, so ``summarize``/``report``/anomaly readers see one schema
    whichever executor produced it.
    """
    from types import SimpleNamespace

    from hpbandster_tpu.obs.device_metrics import decode_device_metrics

    counts = np.asarray(counts, np.int64)
    nonzero = np.nonzero(counts)[0]
    if nonzero.size == 0:
        return None
    entry = int(nonzero[0])
    member_counts = tuple(int(c) for c in counts[entry:])
    member_budgets = tuple(float(b) for b in budgets[entry:])
    n_stages = len(member_counts)
    hist_m = np.asarray(hist)[entry:entry + n_stages]
    crash_m = np.asarray(crashes)[entry:entry + n_stages]
    # SH promotions are exactly the next stage's traced count (the rank
    # mask always fills it: counts are non-increasing and crashed rows
    # still rank); the final rung promotes nobody
    promos = np.array(list(member_counts[1:]) + [0], np.int64)
    final_losses = np.asarray(stages[-1][1], np.float32)[: member_counts[-1]]
    finite = final_losses[~np.isnan(final_losses)]
    best = float(finite.min()) if finite.size else float("nan")
    metrics = SimpleNamespace(
        loss_hist=hist_m[None, :, :],
        evals=np.array([member_counts], np.int64),
        crashes=crash_m[None, :],
        promotions=promos[None, :],
        model_fits=np.zeros((1,), np.int64),
        best_final=np.array([best], np.float32),
    )
    return decode_device_metrics(
        metrics, plans=[(member_counts, member_budgets)]
    )


class _TelemetryPacked(NamedTuple):
    """A telemetry-carrying dispatch handle: the compiled output tuple
    plus the host counts the decode needs (callers treat dispatch
    results as opaque, so the handle rides through their fetch
    plumbing untouched)."""

    out: Tuple
    counts: np.ndarray


def _publish_member_telemetry(hist, crashes, counts, budgets, stages) -> None:
    """Decode one member's fetched telemetry and hand it to the obs
    pipeline (gauges + ``device_telemetry`` journal record) — the shared
    tail of the solo and packed unpack paths."""
    from hpbandster_tpu.obs.device_metrics import (
        emit_device_telemetry,
        publish_device_metrics,
    )

    rec = member_telemetry_record(hist, crashes, counts, budgets, stages)
    if rec is not None:
        publish_device_metrics(rec)
        emit_device_telemetry(rec)


#: process-wide compiled-bucket cache — same policy as ops.fused's
#: _FUSED_FN_CACHE: a (objective, bucket, mesh, telemetry-flag)
#: combination compiles once per process, bounded so throwaway closures
#: cannot pin executables
_BUCKET_FN_CACHE: LRUCache = LRUCache(maxsize=64)


class _BucketRunner:
    """One bucket's compiled program + dispatch/unpack plumbing.

    The executable is built exactly once (lazily on first dispatch, or
    ahead of time via :meth:`ensure_compiled` / :func:`precompile_buckets`)
    through the tracked ``lower().compile()`` proxy, so the compile ledger
    sees exactly one compile per bucket — the number the budget tests and
    the bench gate assert on. Dispatches always run the AOT executable;
    the jit wrapper itself is never called (that would compile a second,
    untracked-by-AOT cache entry).
    """

    def __init__(self, eval_fn, bucket: BucketPlan, mesh=None, axis="config",
                 device_metrics: Optional[bool] = None):
        from hpbandster_tpu.obs.device_metrics import device_metrics_default
        from hpbandster_tpu.obs.runtime import tracked_jit

        self.bucket = bucket
        self.mesh = mesh
        self.axis = axis
        #: in-trace telemetry (obs/device_metrics.py): the compiled
        #: program additionally returns per-stage histograms + crash
        #: counts (bucketed_stage_telemetry) and every unpack emits the
        #: decoded device_telemetry record — the bucketed/megabatch
        #: executors' join onto the device metrics plane. Resolved HERE
        #: (not at dispatch) because the flag changes the program.
        self.device_metrics = (
            device_metrics_default() if device_metrics is None
            else bool(device_metrics)
        )
        self._lock = threading.Lock()
        self._compiled = None
        self._dim: Optional[int] = None
        # the bin schema is a host constant burned into the trace —
        # resolved OUTSIDE the traced closure (obs-emit-in-jit contract)
        dm_edges = None
        if self.device_metrics:
            from hpbandster_tpu.obs.device_metrics import bin_edges

            dm_edges = bin_edges().astype(np.float32)

        def bracket(vectors, counts):
            stages = fused_sh_bracket_bucketed(
                eval_fn, vectors, counts, bucket, mesh=mesh, axis=axis
            )
            import jax.numpy as jnp

            out = (
                jnp.concatenate([s[0] for s in stages]),
                jnp.concatenate([s[1] for s in stages]),
            )
            if dm_edges is None:
                return out
            tel = bucketed_stage_telemetry(stages, counts, dm_edges)
            return out + (
                jnp.stack([h for h, _ in tel]),
                jnp.stack([c for _, c in tel]),
            )

        jit_kwargs: Dict = {
            # donation declined explicitly (docs/perf_notes.md): the
            # packed (idx, loss) outputs cannot alias the [W0, d] vectors
            # input, so donating it would only emit a per-compile warning
            "donate_argnums": (),
        }
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            shard = NamedSharding(mesh, PartitionSpec(axis))
            rep = NamedSharding(mesh, PartitionSpec())
            jit_kwargs["in_shardings"] = (shard, rep)
        self._wrapper = tracked_jit(
            bracket, name="fused_bucket", **jit_kwargs
        )

    # ------------------------------------------------------------- compile
    def ensure_compiled(self, d: int):
        """AOT-compile the bucket program for ``d``-dim vectors (idempotent,
        thread-safe — the background precompiler and a dispatching executor
        may race here)."""
        with self._lock:
            if self._compiled is not None:
                if self._dim != int(d):
                    raise ValueError(
                        f"bucket program compiled for d={self._dim}, "
                        f"asked for d={d}"
                    )
                return self._compiled
            import jax
            import jax.numpy as jnp

            specs = (
                jax.ShapeDtypeStruct((self.bucket.widths[0], int(d)), jnp.float32),
                jax.ShapeDtypeStruct((self.bucket.depth,), jnp.int32),
            )
            self._compiled = self._wrapper.lower(*specs).compile()
            self._dim = int(d)
            return self._compiled

    # ------------------------------------------------------------ dispatch
    def dispatch(self, vectors: np.ndarray, counts: Sequence[int]):
        """Launch one member bracket; returns packed DEVICE arrays without
        blocking (callers overlap several brackets before fetching).

        ``vectors`` is ``f32[n0, d]`` member rows (padded up here);
        ``counts`` the member's true per-stage counts, entry-aligned
        (length = bucket depth, leading zeros for pre-entry stages).
        """
        from hpbandster_tpu.obs.runtime import note_transfer

        vectors = np.asarray(vectors, np.float32)
        w0 = self.bucket.widths[0]
        if vectors.shape[0] > w0:
            raise ValueError(
                f"{vectors.shape[0]} rows do not fit bucket width {w0}"
            )
        if vectors.shape[0] < w0:
            vectors = np.concatenate(
                [vectors, np.zeros((w0 - vectors.shape[0], vectors.shape[1]),
                                   np.float32)]
            )
        counts = np.asarray(counts, np.int32)
        if counts.shape != (self.bucket.depth,):
            raise ValueError(
                f"counts must be i32[{self.bucket.depth}], got {counts.shape}"
            )
        compiled = self.ensure_compiled(vectors.shape[1])
        note_transfer("h2d", vectors.nbytes + counts.nbytes, buffers=2)
        counts_host = np.asarray(counts)
        if self.mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            shard = NamedSharding(self.mesh, PartitionSpec(self.axis))
            rep = NamedSharding(self.mesh, PartitionSpec())
            vecs_host = vectors
            vectors = jax.make_array_from_callback(
                vecs_host.shape, shard, lambda idx: vecs_host[idx]
            )
            counts = jax.make_array_from_callback(
                counts_host.shape, rep, lambda idx: counts_host[idx]
            )
        out = compiled(vectors, counts)
        if self.device_metrics:
            # the counts ride the handle so unpack can decode the
            # telemetry against the member's true rung layout
            return _TelemetryPacked(out, counts_host)
        return out

    def unpack(self, packed) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Blocking fetch of a dispatch's packed pair, cut back into
        per-stage (idx, losses) at bucket widths. A telemetry-carrying
        dispatch (``device_metrics=True``) additionally decodes the
        in-trace histograms/crash counts into a ``device_telemetry``
        record, publishes the gauges, and journals the event — the
        bucketed executor tier's join onto the device metrics plane."""
        import jax

        from hpbandster_tpu.obs.runtime import note_transfer

        counts_host = None
        if isinstance(packed, _TelemetryPacked):
            packed, counts_host = packed
        fetched = jax.device_get(tuple(packed))
        note_transfer(
            "d2h", sum(int(a.nbytes) for a in fetched), buffers=len(fetched)
        )
        idx_flat, loss_flat = fetched[0], fetched[1]
        out, off = [], 0
        for w in self.bucket.widths:
            out.append((idx_flat[off:off + w], loss_flat[off:off + w]))
            off += w
        if counts_host is not None and len(fetched) == 4:
            _publish_member_telemetry(
                fetched[2], fetched[3], counts_host, self.bucket.budgets, out
            )
        return out

    def run_member(self, vectors: np.ndarray, plan: BracketPlan, entry: int):
        """Dispatch + fetch one member bracket, returning its TRUE-shape
        per-stage ``(indices, losses)`` — the drop-in equivalent of a
        ``make_fused_bracket_fn`` runner call."""
        counts = member_counts_for(self.bucket, plan, entry)
        packed = self.dispatch(np.asarray(vectors, np.float32), counts)
        return slice_member_stages(self.unpack(packed), plan, entry)


def make_bucketed_bracket_fn(
    eval_fn: Callable,
    bucket: BucketPlan,
    mesh=None,
    axis: str = "config",
    device_metrics: Optional[bool] = None,
) -> _BucketRunner:
    """The (process-cached) runner for one bucket program. The telemetry
    flag resolves BEFORE the cache key (like the fused drivers'
    ``_sweep_key``): a mid-process ``HPB_DEVICE_METRICS`` flip misses the
    cache instead of silently serving the other program."""
    from hpbandster_tpu.obs.device_metrics import device_metrics_default

    if device_metrics is None:
        device_metrics = device_metrics_default()
    key = (eval_fn, bucket, mesh, axis, bool(device_metrics))
    runner = _BUCKET_FN_CACHE.get(key)
    if runner is None:
        runner = _BucketRunner(
            eval_fn, bucket, mesh=mesh, axis=axis,
            device_metrics=device_metrics,
        )
        _BUCKET_FN_CACHE[key] = runner
    return runner


class _Precompile:
    """Handle over a (possibly background) bucket-set compilation."""

    def __init__(self, runners: List[_BucketRunner], d: int):
        self._runners = runners
        self._d = int(d)
        self._done = threading.Event()
        self.errors: List[Exception] = []

    def _work(self) -> None:
        try:
            for r in self._runners:
                try:
                    r.ensure_compiled(self._d)
                except Exception as e:  # noqa: BLE001 — reported via wait()
                    self.errors.append(e)
        finally:
            self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every bucket is compiled; True when finished."""
        return self._done.wait(timeout)


def precompile_buckets(
    eval_fn: Callable,
    bucket_set: BucketSet,
    d: int,
    mesh=None,
    axis: str = "config",
    background: bool = True,
) -> _Precompile:
    """AOT-compile every bucket program through the tracked
    ``lower().compile()`` proxy — in a daemon thread by default, so the
    compile overlaps the optimizer's stage-0 sampling instead of
    serializing in front of the first dispatch. Returns a handle whose
    ``wait()`` blocks until the set is ready (dispatching earlier is safe:
    the runner's own lock serializes on the in-flight compile)."""
    runners = [
        make_bucketed_bracket_fn(eval_fn, b, mesh=mesh, axis=axis)
        for b in bucket_set.buckets
    ]
    handle = _Precompile(runners, d)
    if background:
        threading.Thread(
            target=handle._work, daemon=True, name="bucket-precompile"
        ).start()
    else:
        handle._work()
    return handle
