"""Mixed-type kernel-density estimation + TPE-style proposal, in pure JAX.

Re-implements the model math of the reference's BOHB config generator
(SURVEY.md §2 "BOHB config generator (KDE)" and §3.4) — which there is a
Python loop over ``statsmodels.KDEMultivariate`` pdf calls — as jittable,
vmappable array kernels:

* product kernels per statsmodels convention: Gaussian for continuous dims,
  Aitchison–Aitken for unordered categoricals, Wang–van Ryzin for ordinals;
* normal-reference ("Scott/Silverman") bandwidths;
* truncated-normal / keep-or-resample candidate sampling around good points;
* the ``l(x)/g(x)`` acquisition maximized over ``num_samples`` candidates.

Shapes are static: observation sets are padded to a fixed capacity with a
0/1 mask, so a growing observation history causes at most ``log2`` many
recompilations. A whole stage of proposals is one ``vmap`` over keys — this
is the batched path the rebuild's north star asks for (SURVEY.md §0).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.scipy.special import logsumexp, ndtr, ndtri

from hpbandster_tpu.obs.runtime import tracked_jit

__all__ = [
    "KDE",
    "LOG_PDF_FLOOR",
    "normal_reference_bandwidths",
    "kde_logpdf",
    "sample_around",
    "propose",
    "propose_batch",
    "propose_batch_seeded_scored",
    "impute_conditional_masked",
    "fit_kde_pair_masked",
    "refit_propose_batch_seeded",
]

#: reference clips pdf values at 1e-32 before the ratio (SURVEY.md §3.4)
LOG_PDF_FLOOR = math.log(1e-32)

_LOG_SQRT_2PI = 0.5 * math.log(2.0 * math.pi)


class KDE(NamedTuple):
    """A fitted mixed-type KDE over unit-hypercube observation vectors.

    ``data`` is ``f32[capacity, d]`` (imputed — no NaNs), ``mask`` is
    ``f32[capacity]`` with 1 for real observations, ``bw`` is ``f32[d]``.
    """

    data: jax.Array
    mask: jax.Array
    bw: jax.Array


def _discrete_bw_cap(cards: jax.Array) -> jax.Array:
    """Aitchison–Aitken lambda must stay below (k-1)/k; continuous dims uncapped."""
    cards_f = jnp.maximum(cards.astype(jnp.float32), 2.0)
    cap = (cards_f - 1.0) / cards_f
    return jnp.where(cards > 0, cap, jnp.inf)


def normal_reference_bandwidths(
    data: jax.Array,
    mask: jax.Array,
    cards: jax.Array,
    min_bandwidth: float = 1e-3,
) -> jax.Array:
    """Per-dim normal-reference rule: ``1.06 * sigma_j * n^(-1/(d+4))``.

    Matches statsmodels' ``bw='normal_reference'`` default that the reference
    relies on, with the reference's ``min_bandwidth`` floor applied to every
    dim and the Aitchison–Aitken cap applied to discrete dims.

    Constant derivation (VERDICT r1 "missing #2"): the asymptotically
    optimal Gaussian-reference constant is ``(4/3)^(1/5) ≈ 1.05922`` for
    d=1; statsmodels' ``_normal_reference`` hardcodes the ROUNDED value
    ``C = 1.06`` and applies it for every d with ``np.std`` (ddof=0) and
    ``n^(-1/(d+4))``. We match statsmodels bit-for-bit, not the theory:
    **1.06**, population sigma, same exponent.
    """
    data = jnp.asarray(data, jnp.float32)
    mask = jnp.asarray(mask, jnp.float32)
    d = data.shape[-1]
    n = jnp.maximum(mask.sum(), 1.0)
    mean = (data * mask[:, None]).sum(0) / n
    var = (jnp.square(data - mean) * mask[:, None]).sum(0) / n
    sigma = jnp.sqrt(jnp.maximum(var, 0.0))
    bw = 1.06 * sigma * n ** (-1.0 / (4.0 + d))
    bw = jnp.clip(bw, min_bandwidth, _discrete_bw_cap(cards))
    return bw


def _per_dim_log_kernels(
    x: jax.Array,
    data: jax.Array,
    bw: jax.Array,
    vartypes: jax.Array,
    cards: jax.Array,
) -> jax.Array:
    """log kernel value for each (datum, dim) pair; shape ``[capacity, d]``.

    vartypes codes: 0 continuous (Gaussian), 1 unordered (Aitchison–Aitken),
    2 ordered (Wang–van Ryzin) — see space.VARTYPE_CODES.
    """
    diff = x[None, :] - data  # [cap, d]
    bw = jnp.clip(bw, 1e-10, None)

    # Gaussian, normalized
    log_c = -0.5 * jnp.square(diff / bw) - jnp.log(bw) - _LOG_SQRT_2PI

    same = jnp.abs(diff) < 0.5  # discrete dims hold integer codes
    lam = jnp.clip(bw, 1e-10, 1.0 - 1e-7)
    km1 = jnp.maximum(cards.astype(jnp.float32) - 1.0, 1.0)

    # Aitchison–Aitken: 1-lam if match else lam/(k-1)
    log_u = jnp.where(same, jnp.log1p(-lam), jnp.log(lam) - jnp.log(km1))

    # Wang–van Ryzin: 1-lam if match else 0.5*(1-lam)*lam^|x-xi|
    log_o = jnp.where(
        same,
        jnp.log1p(-lam),
        math.log(0.5) + jnp.log1p(-lam) + jnp.abs(diff) * jnp.log(lam),
    )

    vt = vartypes[None, :]
    return jnp.where(vt == 0, log_c, jnp.where(vt == 1, log_u, log_o))


def kde_logpdf(
    x: jax.Array,
    kde: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
) -> jax.Array:
    """Mixture log-density of one point under the product-kernel KDE."""
    log_k = _per_dim_log_kernels(x, kde.data, kde.bw, vartypes, cards)  # [cap, d]
    per_datum = log_k.sum(-1)  # [cap]
    log_w = jnp.where(kde.mask > 0, 0.0, -jnp.inf)
    n = jnp.maximum(kde.mask.sum(), 1.0)
    return logsumexp(per_datum + log_w) - jnp.log(n)


def _truncnorm_unit(key: jax.Array, mean: jax.Array, sd: jax.Array) -> jax.Array:
    """Truncated-normal sample on [0, 1] via inverse-CDF (vectorized over dims)."""
    sd = jnp.clip(sd, 1e-6, None)
    a = ndtr((0.0 - mean) / sd)
    b = ndtr((1.0 - mean) / sd)
    u = jax.random.uniform(key, mean.shape, minval=a, maxval=b)
    u = jnp.clip(u, 1e-7, 1.0 - 1e-7)
    return jnp.clip(mean + sd * ndtri(u), 0.0, 1.0)


def sample_around(
    key: jax.Array,
    datum: jax.Array,
    bw: jax.Array,
    vartypes: jax.Array,
    cards: jax.Array,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
) -> jax.Array:
    """One BOHB candidate: perturb a good observation per-dim.

    Continuous dims: truncnorm(mean=datum, sd=bw*bandwidth_factor) on [0,1];
    discrete dims: keep the datum's value w.p. (1-bw), else uniform over the
    other choices — the reference's sampling scheme (SURVEY.md §3.4).
    """
    k_cont, k_keep, k_cat = jax.random.split(key, 3)
    sd = jnp.clip(bw * bandwidth_factor, min_bandwidth, None)
    cont = _truncnorm_unit(k_cont, datum, sd)

    lam = jnp.clip(bw, 0.0, 1.0 - 1e-7)
    keep = jax.random.uniform(k_keep, datum.shape) >= lam
    cards_safe = jnp.maximum(cards, 1)
    rand_choice = jax.random.uniform(k_cat, datum.shape) * cards_safe.astype(jnp.float32)
    rand_choice = jnp.clip(jnp.floor(rand_choice), 0, cards_safe - 1).astype(jnp.float32)
    disc = jnp.where(keep, datum, rand_choice)

    return jnp.where(vartypes == 0, cont, disc)


@partial(tracked_jit, static_argnames=("num_samples",))
def propose(
    key: jax.Array,
    good: KDE,
    bad: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One BOHB proposal: the best of ``num_samples`` candidates by l(x)/g(x).

    Returns ``(best_vector, candidates, scores)``; scores are
    ``log l(x) - log g(x)`` with both log-densities floored at
    ``LOG_PDF_FLOOR`` exactly like the reference's ``max(1e-32, pdf)`` clamp.
    """
    k_idx, k_samp = jax.random.split(key)
    logits = jnp.where(good.mask > 0, 0.0, -jnp.inf)
    idx = jax.random.categorical(k_idx, logits, shape=(num_samples,))
    data = good.data[idx]  # [S, d]

    keys = jax.random.split(k_samp, num_samples)
    cands = jax.vmap(
        lambda k, x: sample_around(
            k, x, good.bw, vartypes, cards, bandwidth_factor, min_bandwidth
        )
    )(keys, data)

    lg = jax.vmap(lambda c: kde_logpdf(c, good, vartypes, cards))(cands)
    lb = jax.vmap(lambda c: kde_logpdf(c, bad, vartypes, cards))(cands)
    scores = jnp.maximum(lg, LOG_PDF_FLOOR) - jnp.maximum(lb, LOG_PDF_FLOOR)

    best = cands[jnp.argmax(scores)]
    return best, cands, scores


def generate_candidates(
    key: jax.Array,
    good: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    total: int,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
) -> jax.Array:
    """``total`` perturbed-good-point candidates, ``f32[total, d]`` — the
    generation half of the BOHB proposal, shared by the seeded host entry
    point and the fused-sweep tracer so the sampling scheme has one home."""
    k_idx, k_samp = jax.random.split(key)
    logits = jnp.where(good.mask > 0, 0.0, -jnp.inf)
    idx = jax.random.categorical(k_idx, logits, shape=(total,))
    keys = jax.random.split(k_samp, total)
    return jax.vmap(
        lambda k, x: sample_around(
            k, x, good.bw, vartypes, cards, bandwidth_factor, min_bandwidth
        )
    )(keys, good.data[idx])


@partial(tracked_jit, static_argnames=("n", "num_samples"))
def generate_candidates_seeded(
    seed: jax.Array,
    good: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    n: int,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
) -> jax.Array:
    """All ``n * num_samples`` candidates for a stage of proposals,
    flattened to ``f32[n*num_samples, d]`` — :func:`generate_candidates`
    keyed from one scalar seed (one scalar transfer on high-latency links),
    so an external scorer (e.g. ``ops.pallas_kde``) can do the scoring half."""
    return generate_candidates(
        jax.random.key(seed), good, vartypes, cards, n * num_samples,
        bandwidth_factor, min_bandwidth,
    )


@partial(tracked_jit, static_argnames=("n", "num_samples"))
def propose_batch_seeded_scored(
    seed: jax.Array,
    good: KDE,
    bad: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    n: int,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
) -> Tuple[jax.Array, jax.Array]:
    """Like :func:`propose_batch` but derives the key batch on-device from
    a single uint32 seed — one scalar transfer instead of an [n, 2] key
    upload (matters when the host link is a high-latency tunnel) — and
    also returns each proposal's winning acquisition score:
    ``(f32[n, d], f32[n])`` where the score is the selected candidate's
    ``log l(x) - log g(x)`` (the max over the same score vector the
    argmax already computed), so the audit trail (``obs/audit.py``)
    costs one extra [n] fetch, not a different draw."""
    keys = jax.random.split(jax.random.key(seed), n)

    def one(k):
        best, _, scores = propose(
            k, good, bad, vartypes, cards, num_samples, bandwidth_factor,
            min_bandwidth,
        )
        return best, jnp.max(scores)

    return jax.vmap(one)(keys)


def propose_batch_seeded(
    seed: jax.Array,
    good: KDE,
    bad: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    n: int,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
) -> jax.Array:
    """:func:`propose_batch_seeded_scored` without the scores — one
    proposal body to maintain (the discarded per-proposal max is trivial
    next to the candidate scoring it reuses)."""
    return propose_batch_seeded_scored(
        seed, good, bad, vartypes, cards, n, num_samples, bandwidth_factor,
        min_bandwidth,
    )[0]


def impute_conditional_masked(
    key: jax.Array, data: jax.Array, cards: jax.Array
) -> jax.Array:
    """Device twin of ``BOHBKDE.impute_conditional_data``: every NaN
    (inactive-dim) entry borrows the value of a uniformly random *active*
    row of the same column; columns with no active rows fall back to a
    random category (discrete) or uniform draw (continuous).

    O(n·d): donors are drawn by inverse-CDF over each column's running
    active count (no n x n materialization). Lived in ``ops/sweep.py``
    until the in-trace refit op below needed it too — the sweep imports it
    from here now, so the imputation scheme has exactly one definition."""
    n, d = data.shape
    isnan = jnp.isnan(data)
    active = (~isnan).astype(jnp.int32)
    cnt = jnp.cumsum(active, axis=0)  # [n, d] running donor count
    total = cnt[-1, :]  # [d]
    k_pick, k_fb = jax.random.split(key)
    u = jax.random.uniform(k_pick, (n, d))
    # r-th donor (1-indexed) per entry; searchsorted over the column's
    # non-decreasing count finds its row
    r = jnp.floor(u * jnp.maximum(total, 1)[None, :]).astype(jnp.int32) + 1
    rows = jax.vmap(
        lambda c, rr: jnp.searchsorted(c, rr, side="left"), in_axes=(1, 1),
        out_axes=1,
    )(cnt, r)
    donated = jnp.take_along_axis(data, jnp.clip(rows, 0, n - 1), axis=0)

    u_fb = jax.random.uniform(k_fb, (n, d))
    cards_f = jnp.maximum(cards.astype(jnp.float32), 1.0)
    disc = jnp.clip(jnp.floor(u_fb * cards_f), 0, cards_f - 1)
    fallback = jnp.where(cards[None, :] > 0, disc, u_fb)

    fill = jnp.where((total > 0)[None, :], donated, fallback)
    return jnp.where(isnan, fill, data)


def _pallas_fit_requested() -> Optional[bool]:
    """Tri-state ``HPB_PALLAS_KDE_FIT`` flag: ``"1"`` forces the Pallas
    bandwidth-fit kernel (interpreted off-TPU), ``"0"`` forces the XLA
    path, unset defers to the caller's ``use_pallas_fit`` argument
    (default: XLA — the Pallas fit is opt-in until a TPU window
    re-baselines it; see docs/perf_notes.md "Resident outer loop")."""
    import os

    env = os.environ.get("HPB_PALLAS_KDE_FIT", "")
    if env in ("0", "1"):
        return env == "1"
    return None


def fit_kde_pair_masked(
    vecs: jax.Array,
    losses: jax.Array,
    count: jax.Array,
    n_good: jax.Array,
    n_bad: jax.Array,
    cards: jax.Array,
    min_bandwidth: float,
    impute_key=None,
    use_pallas_fit: Optional[bool] = None,
) -> Tuple[KDE, KDE]:
    """Traced-count good/bad KDE fit over a full-capacity buffer.

    ``vecs``/``losses`` are FULL capacity buffers (``f32[C, d]`` /
    ``f32[C]``, empty slots carrying ``+inf`` loss); ``count`` / ``n_good``
    / ``n_bad`` are traced i32 scalars. Split membership is a rank mask
    over the loss-sorted buffer instead of a static slice — every KDE
    primitive downstream (bandwidths, log-pdf, candidate sampling, the
    Pallas scorer) is mask-weighted, so the fitted model is the same; only
    observation COUNTS stay out of the compiled program. This is the one
    definition behind both the dynamic-count fused sweep
    (``ops/sweep.py``) and the in-trace refit+propose op below.

    ``use_pallas_fit=True`` (or ``HPB_PALLAS_KDE_FIT=1``, which
    overrides) computes the bandwidth reduction through
    ``ops.pallas_kde.pallas_normal_reference_bandwidths`` — one
    VMEM-streaming moment pass instead of two [C, d] HBM intermediates,
    the lever if the fit is the wall at 1M observations (measured by the
    bench ``resident_100k`` tier's ``kde_fit`` probe). A distinct
    numeric consumer (one-pass variance), so it is opt-in behind the
    flag; the split/sort half is unchanged either way.
    """
    cap = vecs.shape[0]
    order = jnp.argsort(losses, stable=True)  # +inf pads sort last
    sorted_v = vecs[order]
    rank = jnp.arange(cap, dtype=jnp.int32)
    good_mask = rank < n_good
    bad_mask = (rank >= count - n_bad) & (rank < count)
    if impute_key is not None:
        # conditional spaces: donor-impute each split side exactly like the
        # static path, with non-members NaN'd out so they neither donate
        # nor constrain (their filled values are then masked from the fit)
        kg, kb = jax.random.split(impute_key)
        good_data = impute_conditional_masked(
            kg, jnp.where(good_mask[:, None], sorted_v, jnp.nan), cards
        )
        bad_data = impute_conditional_masked(
            kb, jnp.where(bad_mask[:, None], sorted_v, jnp.nan), cards
        )
    else:
        good_data = bad_data = sorted_v

    env = _pallas_fit_requested()
    pallas_fit = bool(use_pallas_fit) if env is None else env

    def mk(data: jax.Array, mask: jax.Array) -> KDE:
        mask = mask.astype(jnp.float32)
        if pallas_fit:
            from hpbandster_tpu.ops.pallas_kde import (
                pallas_available,
                pallas_normal_reference_bandwidths,
            )

            bw = pallas_normal_reference_bandwidths(
                data, mask, cards, min_bandwidth,
                interpret=not pallas_available(),
            )
        else:
            bw = normal_reference_bandwidths(
                data, mask, cards, min_bandwidth
            )
        return KDE(data, mask, bw)

    return mk(good_data, good_mask), mk(bad_data, bad_mask)


# the observation buffers are rebuilt host-side per refit and never reread
# by the caller, but they cannot alias the [n, d] proposal outputs, so
# donation buys nothing here — declined explicitly (jit-donation contract,
# docs/perf_notes.md)
@partial(
    tracked_jit, static_argnames=("n", "num_samples"), donate_argnums=()
)
def refit_propose_batch_seeded(
    seed: jax.Array,
    obs_v: jax.Array,
    obs_l: jax.Array,
    count: jax.Array,
    n_good: jax.Array,
    n_bad: jax.Array,
    vartypes: jax.Array,
    cards: jax.Array,
    n: int,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
    impute_seed: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """KDE refit + a whole stage of proposals in ONE device dispatch.

    The host path (``models/bohb_kde.py`` default) fits the KDE pair in
    numpy, uploads the fitted arrays, then runs the proposal kernel — the
    refit state round-trips through the host every rung. This op keeps it
    in-trace: raw observation buffers go up (``f32[C, d]`` vectors,
    ``f32[C]`` losses, ``+inf`` in empty slots), the good/bad split,
    bandwidths, candidate generation, scoring and the per-proposal argmax
    all happen inside one compiled program, and only the selected
    ``(f32[n, d], f32[n])`` proposals + scores come back.

    ``count``/``n_good``/``n_bad`` are traced i32 (the caller runs the
    reference's split arithmetic), so observation growth recompiles only
    when the buffer capacity doubles. Pass ``impute_seed`` on conditional
    spaces to donor-impute NaN dims in-trace (a distinct RNG consumer from
    the host path's ``rng.choice`` — documented, like the dynamic sweep
    tier).
    """
    impute_key = (
        None if impute_seed is None else jax.random.key(impute_seed)
    )
    good, bad = fit_kde_pair_masked(
        obs_v, obs_l, count, n_good, n_bad, cards, min_bandwidth,
        impute_key=impute_key,
    )
    keys = jax.random.split(jax.random.key(seed), n)

    def one(k):
        best, _, scores = propose(
            k, good, bad, vartypes, cards, num_samples, bandwidth_factor,
            min_bandwidth,
        )
        return best, jnp.max(scores)

    return jax.vmap(one)(keys)


@partial(tracked_jit, static_argnames=("num_samples",))
def propose_batch(
    keys: jax.Array,
    good: KDE,
    bad: KDE,
    vartypes: jax.Array,
    cards: jax.Array,
    num_samples: int = 64,
    bandwidth_factor: float = 3.0,
    min_bandwidth: float = 1e-3,
) -> jax.Array:
    """A whole stage of proposals in one dispatch: vmap of :func:`propose`.

    ``keys`` is ``[n, 2]`` (uint32 key batch); returns ``f32[n, d]``. This is
    the vmapped replacement for the reference's one-proposal-per-RPC loop.
    """
    return jax.vmap(
        lambda k: propose(
            k, good, bad, vartypes, cards, num_samples, bandwidth_factor, min_bandwidth
        )[0]
    )(keys)
