"""Worker — the user-code evaluation plugin, host tier.

API-compatible with the reference's ``core/worker.py`` (SURVEY.md §2):
subclass, implement ``compute(config_id, config, budget,
working_directory) -> {'loss': float, 'info': ...}``, then ``run()`` either
in-process (``background=True``, the test/examples fixture) or as a
standalone (possibly remote) process that discovers the master through the
nameserver or a shared-directory credentials file.

Transport is the stdlib TCP RPC layer instead of Pyro4; semantics kept:
one job at a time, exceptions captured as traceback strings, results pushed
back to the dispatcher's callback URI, optional idle-timeout self-shutdown.
"""

from __future__ import annotations

import logging
import os
import pickle
import socket
import threading
import time
import traceback
from typing import Any, Dict, Optional

from hpbandster_tpu.parallel.rpc import RPCProxy, RPCServer, format_uri

__all__ = ["Worker"]


class Worker:
    def __init__(
        self,
        run_id: str,
        nameserver: Optional[str] = None,
        nameserver_port: Optional[int] = None,
        logger: Optional[logging.Logger] = None,
        host: Optional[str] = None,
        id: Optional[Any] = None,
        timeout: Optional[float] = None,
    ):
        self.run_id = run_id
        self.nameserver = nameserver
        self.nameserver_port = nameserver_port
        self.host = host or "127.0.0.1"
        self.worker_id = (
            f"hpbandster.run_{run_id}.worker.{socket.gethostname()}.{os.getpid()}"
            f".{threading.get_native_id()}"
        )
        if id is not None:
            self.worker_id += f".{id}"
        self.logger = logger or logging.getLogger(
            f"hpbandster_tpu.worker.{os.getpid()}"
        )
        self.timeout = timeout

        self._server: Optional[RPCServer] = None
        self._busy_lock = threading.Lock()
        self._shutdown_event = threading.Event()
        self._last_active = time.time()
        self._timeout_thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- bootstrap
    def load_nameserver_credentials(
        self, working_directory: str, num_tries: int = 60, interval: float = 1.0
    ) -> None:
        """Poll the shared directory for the nameserver credentials file
        (cluster bootstrap path, reference §2 NameServer row)."""
        fn = os.path.join(working_directory, f"HPB_run_{self.run_id}_pyro.pkl")
        for attempt in range(num_tries):
            try:
                with open(fn, "rb") as fh:
                    self.nameserver, self.nameserver_port = pickle.load(fh)
                return
            except FileNotFoundError:
                self.logger.warning(
                    "config file %s not found (trying %d/%d)", fn, attempt + 1, num_tries
                )
                time.sleep(interval)
        raise RuntimeError(f"could not find nameserver credentials in {working_directory}")

    # -------------------------------------------------------------- lifecycle
    def run(self, background: bool = False) -> None:
        """Serve jobs. ``background=True`` returns immediately (daemon
        threads), the in-process mode the test suite uses; otherwise blocks
        until shutdown."""
        if self.nameserver is None:
            raise RuntimeError("no nameserver specified (or credentials loaded)")
        self._server = RPCServer(self.host, 0)
        self._server.register("start_computation", self._rpc_start_computation)
        self._server.register("is_busy", self._rpc_is_busy)
        self._server.register("shutdown", self._rpc_shutdown)
        self._server.register("ping", lambda: "pong")
        self._extra_rpc(self._server)
        self._server.start()

        ns = RPCProxy(format_uri(self.nameserver, self.nameserver_port))
        ns.call("register", name=self.worker_id, uri=self._server.uri)
        self.logger.info(
            "worker %s serving at %s", self.worker_id, self._server.uri
        )

        if self.timeout is not None:
            self._timeout_thread = threading.Thread(
                target=self._timeout_watchdog, daemon=True
            )
            self._timeout_thread.start()

        if not background:
            self._shutdown_event.wait()
            self._teardown()

    def _timeout_watchdog(self) -> None:
        while not self._shutdown_event.wait(min(self.timeout, 1.0)):
            idle = time.time() - self._last_active
            if not self._busy_lock.locked() and idle > self.timeout:
                self.logger.info("worker idle for %.1fs; self-shutdown", idle)
                self.shutdown()
                return

    def _teardown(self) -> None:
        try:
            ns = RPCProxy(format_uri(self.nameserver, self.nameserver_port), timeout=2)
            ns.call("unregister", name=self.worker_id)
        except Exception as e:
            # best-effort: the nameserver may already be gone at teardown
            self.logger.debug("unregister from nameserver failed: %r", e)
        if self._server is not None:
            self._server.shutdown()
            self._server = None

    def shutdown(self) -> None:
        self._shutdown_event.set()
        # when running in background mode nobody waits on the event; tear
        # down from here (idempotent)
        if self._server is not None:
            threading.Thread(target=self._teardown, daemon=True).start()

    # ------------------------------------------------------------ rpc surface
    def _extra_rpc(self, server: RPCServer) -> None:
        """Hook for subclasses to register additional RPC methods before the
        server starts (e.g. TPUBatchedWorker's ``evaluate_batch``)."""

    def _rpc_is_busy(self) -> bool:
        return self._busy_lock.locked()

    def _rpc_shutdown(self) -> bool:
        self.logger.debug("shutdown requested via RPC")
        self.shutdown()
        return True

    def _rpc_start_computation(
        self, callback_uri: str, id: Any, **job_kwargs: Any
    ) -> bool:
        if not self._busy_lock.acquire(blocking=False):
            raise RuntimeError("worker is busy")
        self._last_active = time.time()
        thread = threading.Thread(
            target=self._run_job,
            args=(callback_uri, tuple(id), job_kwargs),
            daemon=True,
            name=f"compute-{id}",
        )
        thread.start()
        return True

    def _run_job(self, callback_uri: str, config_id: Any, job_kwargs: Dict[str, Any]) -> None:
        result: Optional[Dict[str, Any]] = None
        exception: Optional[str] = None
        try:
            result = self.compute(config_id=config_id, **job_kwargs)
            if not isinstance(result, dict) or "loss" not in result:
                raise TypeError(
                    "compute() must return a dict with a 'loss' key, got "
                    f"{type(result).__name__}"
                )
        except Exception:
            result = None
            exception = traceback.format_exc()
            self.logger.warning("compute crashed:\n%s", exception)
        finally:
            self._last_active = time.time()
            self._busy_lock.release()
        try:
            RPCProxy(callback_uri, timeout=30).call(
                "register_result",
                id=list(config_id),
                result={"result": result, "exception": exception},
            )
        except Exception:
            self.logger.error(
                "could not deliver result for %s:\n%s",
                config_id, traceback.format_exc(),
            )

    # --------------------------------------------------------------- user API
    def compute(
        self,
        config_id: Any,
        config: Dict[str, Any],
        budget: float,
        working_directory: str,
    ) -> Dict[str, Any]:
        """Evaluate ``config`` at ``budget``; MUST return
        ``{'loss': float, 'info': <json-serializable>}``."""
        raise NotImplementedError(
            "subclass hpbandster_tpu.Worker and implement compute()"
        )
