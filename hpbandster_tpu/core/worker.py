"""Worker — the user-code evaluation plugin, host tier.

API-compatible with the reference's ``core/worker.py`` (SURVEY.md §2):
subclass, implement ``compute(config_id, config, budget,
working_directory) -> {'loss': float, 'info': ...}``, then ``run()`` either
in-process (``background=True``, the test/examples fixture) or as a
standalone (possibly remote) process that discovers the master through the
nameserver or a shared-directory credentials file.

Transport is the stdlib TCP RPC layer instead of Pyro4; semantics kept:
one job at a time, exceptions captured as traceback strings, results pushed
back to the dispatcher's callback URI, optional idle-timeout self-shutdown.

Worker-side observability (docs/observability.md "Trace propagation"):
the dispatcher's ``start_computation`` call carries the job's trace in the
``_obs`` envelope; the RPC handler enters it, :meth:`_rpc_start_computation`
captures it (threads do NOT inherit contextvars) and the compute thread
re-enters it — so every worker event carries the same ``trace_id`` the
master minted. Pass ``journal_path`` to give the worker its OWN journal,
stamped with ``{host, pid, worker_id}``: merged with the master's via
``python -m hpbandster_tpu.obs summarize a.jsonl b.jsonl`` it yields the
cross-host per-job timeline. Result delivery retries with capped
exponential backoff before a computed result is ever abandoned.
"""

from __future__ import annotations

import logging
import os
import pickle
import socket
import threading
import time
import traceback
from typing import Any, Dict, Optional

from hpbandster_tpu import obs
from hpbandster_tpu.obs.events import make_event
from hpbandster_tpu.obs.journal import event_to_record
from hpbandster_tpu.parallel.rpc import (
    CommunicationError,
    RPCError,
    RPCProxy,
    RPCServer,
    format_uri,
)

__all__ = ["Worker"]


class Worker:
    def __init__(
        self,
        run_id: str,
        nameserver: Optional[str] = None,
        nameserver_port: Optional[int] = None,
        logger: Optional[logging.Logger] = None,
        host: Optional[str] = None,
        id: Optional[Any] = None,
        timeout: Optional[float] = None,
        journal_path: Optional[str] = None,
    ):
        self.run_id = run_id
        self.nameserver = nameserver
        self.nameserver_port = nameserver_port
        self.host = host or "127.0.0.1"
        self.worker_id = (
            f"hpbandster.run_{run_id}.worker.{socket.gethostname()}.{os.getpid()}"
            f".{threading.get_native_id()}"
        )
        if id is not None:
            self.worker_id += f".{id}"
        self.logger = logger or logging.getLogger(
            f"hpbandster_tpu.worker.{os.getpid()}"
        )
        self.timeout = timeout

        self._server: Optional[RPCServer] = None
        self._busy_lock = threading.Lock()
        self._shutdown_event = threading.Event()
        # monotonic: the idle watchdog computes durations from this, and
        # a host clock step must not self-shutdown a healthy worker
        self._last_active = time.monotonic()
        self._timeout_thread: Optional[threading.Thread] = None

        # ---- observability: worker-local journal / ring / health -------
        #: result-delivery retry policy (capped exponential backoff) — a
        #: computed result is only abandoned after every attempt fails
        self.result_delivery_attempts = 4
        self.result_delivery_backoff = 0.5
        self.result_delivery_backoff_cap = 8.0
        self.journal_path = journal_path
        self._journal: Optional[obs.JsonlJournal] = None
        self._ring = obs.RingBuffer(capacity=64)
        self._current_job: Optional[Any] = None  # config_id while computing

    # -------------------------------------------------------------- bootstrap
    def load_nameserver_credentials(
        self, working_directory: str, num_tries: int = 60, interval: float = 1.0
    ) -> None:
        """Poll the shared directory for the nameserver credentials file
        (cluster bootstrap path, reference §2 NameServer row)."""
        fn = os.path.join(working_directory, f"HPB_run_{self.run_id}_pyro.pkl")
        for attempt in range(num_tries):
            try:
                with open(fn, "rb") as fh:
                    self.nameserver, self.nameserver_port = pickle.load(fh)
                return
            except FileNotFoundError:
                self.logger.warning(
                    "config file %s not found (trying %d/%d)", fn, attempt + 1, num_tries
                )
                time.sleep(interval)
        raise RuntimeError(f"could not find nameserver credentials in {working_directory}")

    # -------------------------------------------------------------- lifecycle
    def run(self, background: bool = False) -> None:
        """Serve jobs. ``background=True`` returns immediately (daemon
        threads), the in-process mode the test suite uses; otherwise blocks
        until shutdown."""
        if self.nameserver is None:
            raise RuntimeError("no nameserver specified (or credentials loaded)")
        # compute() may build jitted device programs: point jax's
        # persistent compile cache at the shared directory BEFORE any
        # compile, so a restarted worker pays no recompile tax
        # (HPB_XLA_CACHE=0 opts out — docs/perf_notes.md)
        from hpbandster_tpu.utils.compile_cache import (
            enable_persistent_compile_cache,
        )

        enable_persistent_compile_cache()
        if self.journal_path is not None and self._journal is None:
            # the worker's own half of the distributed story: every record
            # stamped with this process's identity (merge-ready)
            self._journal = obs.JsonlJournal(
                self.journal_path, static_fields=self.identity()
            )
        self._server = RPCServer(self.host, 0)
        self._server.register("start_computation", self._rpc_start_computation)
        self._server.register("is_busy", self._rpc_is_busy)
        self._server.register("shutdown", self._rpc_shutdown)
        self._server.register("ping", lambda: "pong")
        obs.HealthEndpoint(
            component="worker",
            identity=self.identity(),
            ring=self._ring,
            in_flight=self._health_in_flight,
        ).register(self._server)
        self._extra_rpc(self._server)
        self._server.start()

        ns = RPCProxy(format_uri(self.nameserver, self.nameserver_port))
        ns.call("register", name=self.worker_id, uri=self._server.uri)
        self.logger.info(
            "worker %s serving at %s", self.worker_id, self._server.uri
        )

        if self.timeout is not None:
            self._timeout_thread = threading.Thread(
                target=self._timeout_watchdog, daemon=True
            )
            self._timeout_thread.start()

        if not background:
            self._shutdown_event.wait()
            self._teardown()

    def _timeout_watchdog(self) -> None:
        while not self._shutdown_event.wait(min(self.timeout, 1.0)):
            idle = time.monotonic() - self._last_active
            if not self._busy_lock.locked() and idle > self.timeout:
                self.logger.info("worker idle for %.1fs; self-shutdown", idle)
                self.shutdown()
                return

    def _teardown(self) -> None:
        try:
            ns = RPCProxy(format_uri(self.nameserver, self.nameserver_port), timeout=2)
            ns.call("unregister", name=self.worker_id)
        except Exception as e:
            # best-effort: the nameserver may already be gone at teardown
            self.logger.debug("unregister from nameserver failed: %r", e)
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self._journal is not None:
            self._journal.close()

    def shutdown(self) -> None:
        self._shutdown_event.set()
        # when running in background mode nobody waits on the event; tear
        # down from here (idempotent)
        if self._server is not None:
            threading.Thread(target=self._teardown, daemon=True).start()

    # ------------------------------------------------------------ rpc surface
    def _extra_rpc(self, server: RPCServer) -> None:
        """Hook for subclasses to register additional RPC methods before the
        server starts (e.g. TPUBatchedWorker's ``evaluate_batch``)."""

    def _rpc_is_busy(self) -> bool:
        return self._busy_lock.locked()

    def _rpc_shutdown(self) -> bool:
        self.logger.debug("shutdown requested via RPC")
        self.shutdown()
        return True

    # ------------------------------------------------------- observability
    def identity(self) -> Dict[str, Any]:
        """This worker process's static identity stamp (journal records,
        health snapshots): ``{host, pid, worker_id}``."""
        return obs.process_identity(worker_id=self.worker_id)

    def _health_in_flight(self) -> Optional[list]:
        cj = self._current_job  # one read: the compute thread may clear it
        return list(cj) if cj is not None else None

    def _emit(self, name: str, **fields: Any) -> None:
        """Worker-side event emission: into the worker's own journal when
        one is configured (its half of a merged cross-host timeline, with
        the current trace_id stamped by ``make_event``), otherwise onto
        the process bus; the health ring always keeps the newest few.

        Never raises: a full disk or closed journal must not wedge the
        busy lock or strand a computed result — the same shielding the
        EventBus gives its sinks."""
        if not obs.enabled():
            return
        try:
            ev = make_event(name, fields)
            self._ring.append(event_to_record(ev))
            if self._journal is not None:
                self._journal(ev)
            else:
                obs.get_bus().publish(ev)
        except Exception:
            self.logger.exception("worker obs emit %s failed", name)

    # ------------------------------------------------------------- compute
    def _rpc_start_computation(
        self, callback_uri: str, id: Any, **job_kwargs: Any
    ) -> bool:
        if not self._busy_lock.acquire(blocking=False):
            raise RuntimeError("worker is busy")
        self._last_active = time.monotonic()
        self._current_job = tuple(id)
        # threads do not inherit contextvars: capture the trace AND the
        # tenant the RPC handler extracted from the _obs envelope and
        # hand them to the compute thread explicitly
        thread = threading.Thread(
            target=self._run_job,
            args=(callback_uri, tuple(id), job_kwargs, obs.current_trace(),
                  obs.current_tenant()),
            daemon=True,
            name=f"compute-{id}",
        )
        thread.start()
        return True

    def _run_job(
        self,
        callback_uri: str,
        config_id: Any,
        job_kwargs: Dict[str, Any],
        trace_ctx: Optional[obs.TraceContext] = None,
        tenant: Optional[str] = None,
    ) -> None:
        # under both identities: worker-side journal twins carry the
        # master's trace_id AND (serving tier) its tenant_id, and the
        # register_result RPC ships them back in its own envelope
        with obs.use_tenant(tenant), obs.use_trace(trace_ctx):
            self._emit(
                obs.JOB_STARTED,
                config_id=list(config_id), budget=job_kwargs.get("budget"),
            )
            result: Optional[Dict[str, Any]] = None
            exception: Optional[str] = None
            t0 = time.monotonic()
            try:
                result = self.compute(config_id=config_id, **job_kwargs)
                if not isinstance(result, dict) or "loss" not in result:
                    raise TypeError(
                        "compute() must return a dict with a 'loss' key, got "
                        f"{type(result).__name__}"
                    )
            except Exception:
                result = None
                exception = traceback.format_exc()
                self.logger.warning("compute crashed:\n%s", exception)
            finally:
                compute_s = time.monotonic() - t0
                self._last_active = time.monotonic()
                # guarded: once the busy lock is released a NEW job may
                # already own the marker while this thread is still in
                # delivery backoff — never clobber it
                if self._current_job == tuple(config_id):
                    self._current_job = None
                self._busy_lock.release()
            self._emit(
                obs.JOB_FAILED if exception is not None else obs.JOB_FINISHED,
                config_id=list(config_id), budget=job_kwargs.get("budget"),
                compute_s=round(compute_s, 6),
            )
            # feeds this worker's obs_snapshot `latency` section — what
            # `watch --snapshot <worker>` renders with no journal on disk
            obs.get_metrics().histogram("worker.compute_s").observe(compute_s)
            self._deliver_result(
                callback_uri, config_id,
                {"result": result, "exception": exception},
                budget=job_kwargs.get("budget"),
            )

    def _deliver_result(
        self,
        callback_uri: str,
        config_id: Any,
        payload: Dict[str, Any],
        budget: Any = None,
    ) -> bool:
        """Push the result to the dispatcher, retrying transient failures
        with capped exponential backoff — a single failed RPC must not
        strand a result the worker already paid to compute.

        Every attempt carries the job's idempotency key
        (``core/recovery.py``): a retry racing a slow ack of the first
        attempt used to deliver TWICE (the second copy dead-lettered or,
        worse, double-registered after a requeue) — the dispatcher's
        exactly-once gate now recognizes the key and acks the duplicate
        without re-ingesting it.
        """
        from hpbandster_tpu.core.recovery import idempotency_key

        key = (
            idempotency_key(config_id, budget)
            if isinstance(budget, (int, float)) else None
        )
        t0 = time.monotonic()
        delay = self.result_delivery_backoff
        attempts = max(int(self.result_delivery_attempts), 1)
        for attempt in range(1, attempts + 1):
            try:
                RPCProxy(callback_uri, timeout=30).call(
                    "register_result", id=list(config_id), result=payload,
                    key=key,
                )
            # broad on purpose (matches the pre-retry behavior): a
            # serialization TypeError must be logged and counted like any
            # transport failure, not kill the compute thread silently —
            # the attempt cap bounds pointless retries either way
            except Exception as e:
                if attempt >= attempts:
                    obs.get_metrics().counter(
                        "worker.result_delivery_failures"
                    ).inc()
                    self.logger.error(
                        "could not deliver result for %s after %d attempts:\n%s",
                        config_id, attempt, traceback.format_exc(),
                    )
                    return False
                obs.get_metrics().counter("worker.result_delivery_retries").inc()
                self._emit(
                    obs.RPC_RETRY,
                    config_id=list(config_id), attempt=attempt,
                    max_attempts=attempts, error=type(e).__name__,
                )
                self.logger.warning(
                    "register_result %d/%d for %s failed (%r); retrying in %.2fs",
                    attempt, attempts, config_id, e, delay,
                )
                time.sleep(delay)
                delay = min(delay * 2.0, self.result_delivery_backoff_cap)
            else:
                self._emit(
                    obs.RESULT_DELIVERED,
                    config_id=list(config_id),
                    delivery_s=round(time.monotonic() - t0, 6),
                    attempts=attempt,
                )
                return True
        return False

    # --------------------------------------------------------------- user API
    def compute(
        self,
        config_id: Any,
        config: Dict[str, Any],
        budget: float,
        working_directory: str,
    ) -> Dict[str, Any]:
        """Evaluate ``config`` at ``budget``; MUST return
        ``{'loss': float, 'info': <json-serializable>}``."""
        raise NotImplementedError(
            "subclass hpbandster_tpu.Worker and implement compute()"
        )
