"""Run/Result containers, streaming JSONL logging, and offline reload.

Format-compatible with the reference's ``core/result.py`` (SURVEY.md §2
"Result / logging" row and §3.5 call stack):

* ``configs.json`` — one JSON array per line: ``[config_id, config, config_info]``
* ``results.json`` — one JSON array per line:
  ``[config_id, budget, time_stamps, result, exception]``

so existing HpBandSter analysis scripts can consume this framework's logs
unchanged, and vice versa (``logged_results_to_HBS_result``).
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from hpbandster_tpu.core.iteration import Datum, Status
from hpbandster_tpu.core.job import ConfigId, Job

__all__ = [
    "Run",
    "Result",
    "json_result_logger",
    "logged_results_to_HBS_result",
    "extract_HBS_learning_curves",
]


class Run:
    """One (config_id, budget) evaluation, as surfaced by analysis code.

    Field names match the reference's ``Run`` (SURVEY.md §3.5): config_id,
    budget, loss, info, time_stamps, error_logs.
    """

    def __init__(
        self,
        config_id: ConfigId,
        budget: float,
        loss: Optional[float],
        info: Any,
        time_stamps: Dict[str, float],
        error_logs: Optional[str],
    ):
        self.config_id = tuple(config_id)
        self.budget = budget
        self.loss = loss
        self.info = info
        self.time_stamps = time_stamps
        self.error_logs = error_logs

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Run(id={self.config_id}, budget={self.budget}, loss={self.loss})"
        )

    def __getitem__(self, k: str) -> Any:
        """Dict-style access kept for reference-script compatibility."""
        return getattr(self, k)


def extract_HBS_learning_curves(runs: List[Run]) -> List[List[Tuple[float, float]]]:
    """Learning-curve extractor matching the reference helper: one curve —
    the (budget, loss) sequence sorted by budget — per config."""
    sr = sorted(runs, key=lambda r: r.budget)
    lc = [(r.budget, r.loss) for r in sr if r.loss is not None]
    return [lc] if lc else []


class Result:
    """All data from one optimizer run, plus the analysis helpers.

    Constructed from the list of finished iteration objects and the
    HB_config dict (eta/budgets/time_ref...), exactly like the reference.
    """

    def __init__(self, HB_iteration_data: List[Any], HB_config: Dict[str, Any]):
        # merge every iteration's {config_id: Datum} into one mapping
        self.data: Dict[ConfigId, Datum] = {}
        for it in HB_iteration_data:
            source = it.data if hasattr(it, "data") else it
            for cid, datum in source.items():
                self.data[tuple(cid)] = datum
        self.HB_config = dict(HB_config)

    # ------------------------------------------------------------- mappings
    def get_id2config_mapping(self) -> Dict[ConfigId, Dict[str, Any]]:
        return {
            cid: {"config": copy.deepcopy(d.config),
                  "config_info": copy.deepcopy(d.config_info)}
            for cid, d in self.data.items()
        }

    def get_runs_by_id(self, config_id: ConfigId) -> List[Run]:
        d = self.data[tuple(config_id)]
        runs = []
        for budget in sorted(d.results.keys()):
            err = d.exceptions.get(budget)
            res = d.results[budget]
            info = getattr(d, "infos", {}).get(budget)
            runs.append(
                Run(
                    config_id=tuple(config_id),
                    budget=budget,
                    loss=res,
                    info=info,
                    time_stamps=d.time_stamps.get(budget, {}),
                    error_logs=err,
                )
            )
        return runs

    def get_all_runs(self, only_largest_budget: bool = False) -> List[Run]:
        """Every recorded run; with ``only_largest_budget`` keep only each
        config's largest-budget run (reference semantics, §3.5)."""
        all_runs: List[Run] = []
        for cid in self.data.keys():
            runs = self.get_runs_by_id(cid)
            if not runs:
                continue
            if only_largest_budget:
                all_runs.append(runs[-1])
            else:
                all_runs.extend(runs)
        return all_runs

    # ------------------------------------------------------------ incumbents
    def get_incumbent_id(self) -> Optional[ConfigId]:
        """Config with the lowest loss among runs on the largest budget."""
        max_budget = self.HB_config.get("max_budget")
        if max_budget is None:
            budgets = [b for d in self.data.values() for b in d.results.keys()]
            if not budgets:
                return None
            max_budget = max(budgets)
        best, best_id = np.inf, None
        for cid, d in self.data.items():
            loss = d.results.get(max_budget)
            if loss is not None and loss < best:
                best, best_id = loss, cid
        return best_id

    def get_incumbent_trajectory(
        self,
        all_budgets: bool = True,
        bigger_is_better: bool = True,
        non_decreasing_budget: bool = True,
    ) -> Dict[str, List[Any]]:
        """Anytime best-loss curve over wall-clock, reference-compatible.

        * ``all_budgets``: consider runs at every budget, not just the largest.
        * ``bigger_is_better``: a run at a strictly larger budget replaces the
          incumbent even if its loss is worse (trust high-fidelity more).
        * ``non_decreasing_budget``: never let the incumbent budget shrink.
        """
        all_runs = self.get_all_runs(only_largest_budget=not all_budgets)
        if not all_budgets:
            all_runs = [
                r for r in all_runs if r.budget == self.HB_config.get("max_budget", r.budget)
            ]
        all_runs.sort(key=lambda r: r.time_stamps.get("finished", 0.0))

        return_dict: Dict[str, List[Any]] = {
            "config_ids": [], "times_finished": [], "budgets": [], "losses": [],
        }
        current_incumbent = float("inf")
        incumbent_budget = -float("inf")
        for r in all_runs:
            if r.loss is None:
                continue
            new_incumbent = False
            if bigger_is_better and r.budget > incumbent_budget:
                new_incumbent = True
            if r.loss < current_incumbent:
                new_incumbent = True
            if non_decreasing_budget and r.budget < incumbent_budget:
                new_incumbent = False
            if new_incumbent:
                current_incumbent = r.loss
                incumbent_budget = r.budget
                return_dict["config_ids"].append(r.config_id)
                return_dict["times_finished"].append(
                    r.time_stamps.get("finished", 0.0)
                )
                return_dict["budgets"].append(r.budget)
                return_dict["losses"].append(r.loss)
        return return_dict

    # --------------------------------------------------------------- exports
    def get_pandas_dataframe(
        self, budgets: Optional[List[float]] = None, loss_fn=lambda r: r.loss
    ):
        """One row per run: config values + budget + loss (+ info scalars)."""
        import pandas as pd

        all_runs = self.get_all_runs(only_largest_budget=False)
        if budgets is not None:
            all_runs = [r for r in all_runs if r.budget in budgets]
        id2conf = self.get_id2config_mapping()
        rows, losses = [], []
        for r in all_runs:
            row = dict(id2conf[r.config_id]["config"])
            row["budget"] = r.budget
            rows.append(row)
            losses.append(loss_fn(r))
        df_x = pd.DataFrame(rows)
        df_y = pd.DataFrame({"loss": losses})
        return df_x, df_y

    def get_fANOVA_data(
        self,
        config_space,
        budgets: Optional[List[float]] = None,
        loss_fn=lambda r: r.loss,
        failed_loss: Optional[float] = None,
    ) -> Tuple[np.ndarray, np.ndarray, Any]:
        """(X, y, config_space) arrays for fANOVA-style importance analysis.

        X uses the unit-hypercube codec, NaN-imputed with each dim's default
        value so conditional spaces stay rectangular.
        """
        all_runs = self.get_all_runs(only_largest_budget=False)
        if budgets is None:
            budgets = sorted({r.budget for r in all_runs})
        all_runs = [r for r in all_runs if r.budget in budgets]
        id2conf = self.get_id2config_mapping()

        hps = config_space.get_hyperparameters()
        defaults = np.array(
            [hp.to_unit(hp.default_value) for hp in hps], dtype=np.float64
        )
        X, y = [], []
        for r in all_runs:
            if r.loss is None and failed_loss is None:
                continue
            vec = config_space.to_vector(id2conf[r.config_id]["config"])
            vec = np.where(np.isnan(vec), defaults, vec)
            X.append(vec)
            y.append(failed_loss if r.loss is None else loss_fn(r))
        return np.asarray(X), np.asarray(y), config_space

    def get_learning_curves(
        self, lc_extractor=extract_HBS_learning_curves, config_ids=None
    ) -> Dict[ConfigId, List[List[Tuple[float, float]]]]:
        config_ids = config_ids or list(self.data.keys())
        return {
            tuple(cid): lc_extractor(self.get_runs_by_id(cid)) for cid in config_ids
        }

    def num_iterations(self) -> int:
        return len({cid[0] for cid in self.data.keys()}) if self.data else 0

    # ------------------------------------------------------------------ misc
    def __getstate__(self):
        return {"data": self.data, "HB_config": self.HB_config}

    def __setstate__(self, state):
        self.data = state["data"]
        self.HB_config = state["HB_config"]


class json_result_logger:
    """Streaming JSONL logger, byte-format-compatible with the reference.

    Writes ``configs.json`` (one line per new configuration) and
    ``results.json`` (one line per finished run) into ``directory``;
    refuses to clobber prior logs unless ``overwrite=True`` — both behaviors
    from the reference (SURVEY.md §5 "Checkpoint / resume").
    """

    def __init__(self, directory: str, overwrite: bool = False):
        os.makedirs(directory, exist_ok=True)
        self.config_fn = os.path.join(directory, "configs.json")
        self.results_fn = os.path.join(directory, "results.json")
        for fn in (self.config_fn, self.results_fn):
            if os.path.exists(fn):
                if overwrite:
                    os.remove(fn)
                else:
                    raise FileExistsError(
                        f"{fn} exists; pass overwrite=True to replace it"
                    )
            with open(fn, "a"):
                pass
        self.config_ids: set = set()

    def new_config(
        self, config_id: ConfigId, config: Dict[str, Any], config_info: Dict[str, Any]
    ) -> None:
        if tuple(config_id) in self.config_ids:
            return
        self.config_ids.add(tuple(config_id))
        with open(self.config_fn, "a") as fh:
            fh.write(json.dumps([list(config_id), config, config_info]))
            fh.write("\n")

    def __call__(self, job: Job) -> None:
        if tuple(job.id) not in self.config_ids:
            # happens for jobs injected via previous_result warm-starts
            self.new_config(job.id, job.kwargs.get("config", {}), {})
        with open(self.results_fn, "a") as fh:
            fh.write(
                json.dumps(
                    [
                        list(job.id),
                        job.kwargs.get("budget"),
                        job.timestamps,
                        job.result,
                        job.exception,
                    ]
                )
            )
            fh.write("\n")


def logged_results_to_HBS_result(directory: str) -> Result:
    """Rebuild a :class:`Result` from ``configs.json`` + ``results.json``.

    Accepts logs written by this framework or by the reference (same format).
    """
    data: Dict[ConfigId, Datum] = {}
    budget_set: set = set()
    time_ref = float("inf")

    with open(os.path.join(directory, "configs.json")) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            entry = json.loads(line)
            if len(entry) == 3:
                config_id, config, config_info = entry
            else:  # very old two-element format
                config_id, config = entry
                config_info = "N/A"
            data[tuple(config_id)] = Datum(
                config=config,
                config_info=config_info if isinstance(config_info, dict) else {},
            )

    with open(os.path.join(directory, "results.json")) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            config_id, budget, time_stamps, result, exception = json.loads(line)
            cid = tuple(config_id)
            if cid not in data:
                data[cid] = Datum(config={}, config_info={})
            d = data[cid]
            d.time_stamps[budget] = time_stamps
            d.results[budget] = None if result is None else result.get("loss")
            if result is not None and "info" in result:
                d.infos[budget] = result["info"]
            d.exceptions[budget] = exception
            d.budget = budget
            d.status = Status.REVIEW
            budget_set.add(budget)
            if time_stamps:
                time_ref = min(time_ref, time_stamps.get("submitted", time_ref))

    budgets = sorted(budget_set)
    HB_config = {
        "time_ref": 0.0 if time_ref == float("inf") else time_ref,
        "budgets": budgets,
        "max_budget": budgets[-1] if budgets else None,
        "min_budget": budgets[0] if budgets else None,
    }
    return Result([data], HB_config)
