"""Bracket bookkeeping: Datum + BaseIteration.

Reference semantics (SURVEY.md §2 "BaseIteration" row, §3.1/§3.3 call
stacks): one iteration object tracks one successive-halving bracket; each
config is a ``Datum`` with per-budget results/timestamps/exceptions and a
status in {QUEUED, RUNNING, REVIEW, TERMINATED, COMPLETED, CRASHED}. The
promotion decision itself (``_advance_to_next_stage``) is abstract and, in
this rebuild, implemented by jittable kernels from ``hpbandster_tpu.ops``.

A struct-of-arrays view (:meth:`BaseIteration.loss_matrix`) exposes the
bracket's state as NaN-masked arrays for the batched TPU path.
"""

from __future__ import annotations

import logging
from enum import IntEnum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from hpbandster_tpu import obs
from hpbandster_tpu.core.job import ConfigId, Job

__all__ = ["Status", "Datum", "BaseIteration"]


class Status(IntEnum):
    """Config lifecycle states, int8-codeable for array form."""

    QUEUED = 0
    RUNNING = 1
    REVIEW = 2
    TERMINATED = 3
    COMPLETED = 4
    CRASHED = 5


class Datum:
    """Per-config bookkeeping inside one bracket."""

    def __init__(
        self,
        config: Dict[str, Any],
        config_info: Dict[str, Any],
        results: Optional[Dict[float, Optional[float]]] = None,
        time_stamps: Optional[Dict[float, Dict[str, float]]] = None,
        exceptions: Optional[Dict[float, Optional[str]]] = None,
        status: Status = Status.QUEUED,
        budget: float = 0.0,
    ):
        self.config = config
        self.config_info = config_info
        self.results: Dict[float, Optional[float]] = results or {}
        self.time_stamps: Dict[float, Dict[str, float]] = time_stamps or {}
        self.exceptions: Dict[float, Optional[str]] = exceptions or {}
        #: per-budget user 'info' payloads from compute()/eval backends
        self.infos: Dict[float, Any] = {}
        self.status = status
        self.budget = budget

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Datum(status={self.status.name}, budget={self.budget}, "
            f"results={self.results})"
        )


class BaseIteration:
    """One successive-halving bracket.

    Parameters mirror the reference constructor
    (``BaseIteration.__init__(HPB_iter, num_configs, budgets, config_sampler)``,
    SURVEY.md §2): ``num_configs[i]`` configs evaluated at ``budgets[i]`` in
    stage ``i``; ``config_sampler(budget) -> (config, info)`` proposes fresh
    configs (the config-generator seam that makes BOHB = HyperBand + KDE).
    """

    #: audit label for promotion_decision records (obs/audit.py);
    #: subclasses with a different promotion rule override it
    promotion_rule: str = "successive_halving"

    def __init__(
        self,
        HPB_iter: int,
        num_configs: Sequence[int],
        budgets: Sequence[float],
        config_sampler: Callable[[float], Tuple[Dict[str, Any], Dict[str, Any]]],
        logger: Optional[logging.Logger] = None,
        result_logger: Optional[Any] = None,
        config_sampler_batch: Optional[
            Callable[[float, int], List[Tuple[Dict[str, Any], Dict[str, Any]]]]
        ] = None,
    ):
        if len(num_configs) != len(budgets):
            raise ValueError("num_configs and budgets must have equal length")
        self.HPB_iter = int(HPB_iter)
        self.num_configs = [int(n) for n in num_configs]
        self.budgets = [float(b) for b in budgets]
        self.config_sampler = config_sampler
        #: optional whole-stage sampler (batched executors): one vmapped
        #: proposal kernel instead of n sequential get_config calls
        self.config_sampler_batch = config_sampler_batch
        self.logger = logger or logging.getLogger("hpbandster_tpu")
        self.result_logger = result_logger

        self.stage = 0
        self.data: Dict[ConfigId, Datum] = {}
        #: configs actually added per stage (promotions + fresh samples)
        self.actual_num_configs = [0] * len(num_configs)
        self.is_finished = False
        self.num_running = 0
        #: a promotion rule that ranks by something other than the raw
        #: losses (H2BO extrapolation) stashes its per-candidate scores
        #: here from _advance_to_next_stage; they ride the audit record
        self.last_promotion_scores: Optional[List[Optional[float]]] = None
        #: multi-objective rules (promote/pareto.py) additionally stash
        #: the per-candidate Pareto domination counts here — the audit
        #: record then shows the front structure the decision ranked by
        self.last_pareto_ranks: Optional[List[Optional[int]]] = None

    # ------------------------------------------------------------- properties
    @property
    def n_stages(self) -> int:
        return len(self.num_configs)

    def add_configuration(
        self,
        config: Optional[Dict[str, Any]] = None,
        config_info: Optional[Dict[str, Any]] = None,
    ) -> ConfigId:
        """Register a fresh config for the current stage.

        Config ids are ``(HPB_iter, stage_sampled, index)`` triples — the same
        three-int shape the reference uses, so the JSONL log format and all
        ``Result`` tooling are interchangeable.
        """
        if config is None:
            config, config_info = self.config_sampler(self.budgets[self.stage])
        config_info = config_info or {}
        if self.is_finished:
            raise RuntimeError("iteration is finished, cannot add configurations")
        if self.actual_num_configs[self.stage] >= self.num_configs[self.stage]:
            raise RuntimeError(
                f"stage {self.stage} of iteration {self.HPB_iter} is already full"
            )
        config_id: ConfigId = (
            self.HPB_iter,
            self.stage,
            self.actual_num_configs[self.stage],
        )
        self.data[config_id] = Datum(
            config=config,
            config_info=config_info,
            budget=self.budgets[self.stage],
        )
        self.actual_num_configs[self.stage] += 1
        if self.result_logger is not None:
            self.result_logger.new_config(config_id, config, config_info)
        # the audit trail's birth record: the one place a config receives
        # its id, so the generator's decision details (model vs random,
        # KDE budget, l/g score — riding config_info) get linked to it
        obs.emit_config_sampled(
            config_id, self.budgets[self.stage], config_info
        )
        return config_id

    def get_next_run(self) -> Optional[Tuple[ConfigId, Dict[str, Any], float]]:
        """Hand out one (config_id, config, budget) ready to evaluate, or None.

        Reference logic (SURVEY.md §3.1): first any QUEUED datum at the
        current stage; otherwise sample a fresh config if the stage still has
        quota; otherwise nothing until results arrive.
        """
        if self.is_finished:
            return None
        for config_id, datum in self.data.items():
            if datum.status == Status.QUEUED:
                assert datum.budget == self.budgets[self.stage], (
                    f"queued budget {datum.budget} != stage budget "
                    f"{self.budgets[self.stage]}"
                )
                datum.status = Status.RUNNING
                self.num_running += 1
                return (config_id, datum.config, datum.budget)
        if self.actual_num_configs[self.stage] < self.num_configs[self.stage]:
            if self.config_sampler_batch is not None:
                # fill the whole remaining stage quota in one batched call
                k = self.num_configs[self.stage] - self.actual_num_configs[self.stage]
                for cfg, info in self.config_sampler_batch(
                    self.budgets[self.stage], k
                ):
                    self.add_configuration(cfg, info)
            else:
                self.add_configuration()
            return self.get_next_run()
        return None

    def register_result(self, job: Job, skip_sanity_checks: bool = False) -> None:
        """Record a finished job into its datum (RUNNING -> REVIEW/CRASHED)."""
        if self.is_finished:
            raise RuntimeError("iteration is finished, cannot register results")
        config_id = job.id
        budget = job.kwargs["budget"]
        datum = self.data[config_id]
        if not skip_sanity_checks:
            if datum.status != Status.RUNNING:
                raise RuntimeError(
                    f"result for {config_id} in status {datum.status.name}"
                )
            if datum.budget != budget:
                raise RuntimeError(
                    f"result budget {budget} != datum budget {datum.budget}"
                )
        loss = job.loss
        datum.results[budget] = None if np.isnan(loss) else loss
        datum.exceptions[budget] = job.exception
        datum.time_stamps[budget] = dict(job.timestamps)
        if isinstance(job.result, dict) and "info" in job.result:
            datum.infos[budget] = job.result["info"]
        # crashed evaluations stay in the bracket as REVIEW with a None loss —
        # they are simply never promoted (reference: crashed-as-worst, §5)
        datum.status = Status.REVIEW
        self.num_running -= 1

    def process_results(self) -> bool:
        """If the current stage is complete, advance the bracket one stage.

        Returns True when the bracket advanced (or finished). Reference flow
        (SURVEY.md §3.3): gather REVIEW losses, ask the promotion rule for a
        mask, promoted configs re-queue at the next budget, the rest
        TERMINATE; after the last stage survivors COMPLETE.
        """
        if self.is_finished:
            return False
        stage_full = (
            self.actual_num_configs[self.stage] == self.num_configs[self.stage]
        )
        all_reviewed = all(
            d.status == Status.REVIEW
            for d in self.data.values()
            if d.budget == self.budgets[self.stage]
        ) and any(d.budget == self.budgets[self.stage] for d in self.data.values())
        if not (stage_full and all_reviewed and self.num_running == 0):
            return False

        budget = self.budgets[self.stage]
        config_ids = [
            cid for cid, d in self.data.items() if d.budget == budget
        ]
        losses = np.array(
            [
                np.nan if self.data[cid].results.get(budget) is None
                else self.data[cid].results[budget]
                for cid in config_ids
            ],
            dtype=np.float64,
        )

        if self.stage == self.n_stages - 1:
            for cid in config_ids:
                d = self.data[cid]
                d.status = (
                    Status.CRASHED
                    if d.results.get(budget) is None
                    else Status.COMPLETED
                )
            self.is_finished = True
            self.logger.debug(
                "iteration %d finished (%d configs at final budget %g)",
                self.HPB_iter, len(config_ids), budget,
            )
            return True

        self.last_promotion_scores = None
        self.last_pareto_ranks = None
        advance = self._advance_to_next_stage(config_ids, losses)
        rung = self.stage
        self.stage += 1
        next_budget = self.budgets[self.stage]
        for cid, promote in zip(config_ids, advance):
            d = self.data[cid]
            if promote:
                d.status = Status.QUEUED
                d.budget = next_budget
                self.actual_num_configs[self.stage] += 1
            else:
                d.status = (
                    Status.CRASHED if d.results.get(budget) is None
                    else Status.TERMINATED
                )
        obs.emit_bracket_promotion(
            self.HPB_iter, rung, self.promotion_rule,
            promoted=int(np.sum(advance)), candidates=len(config_ids),
            budget=budget, next_budget=next_budget,
        )
        # the audit twin: full per-candidate detail (losses, mask, cut
        # threshold, rule scores, measured costs) — what report's regret
        # table and the promote/replay.py harness re-score
        obs.emit_promotion_decision(
            self.HPB_iter, rung, budget, next_budget,
            config_ids=config_ids,
            losses=[None if np.isnan(l) else float(l) for l in losses],
            promoted=[bool(a) for a in advance],
            rule=self.promotion_rule,
            scores=self.last_promotion_scores,
            pareto_rank=self.last_pareto_ranks,
            # bus-gated: the emitter discards everything when no sink is
            # attached, so the O(n) cost measurement must not be paid
            # eagerly on the no-sink fast path
            costs=(
                [self.promotion_cost(cid, budget) for cid in config_ids]
                if obs.get_bus().active else None
            ),
        )
        self.last_promotion_scores = None
        self.last_pareto_ranks = None
        self.logger.debug(
            "iteration %d advanced to stage %d (%d promoted)",
            self.HPB_iter, self.stage, int(np.sum(advance)),
        )
        return True

    def _advance_to_next_stage(
        self, config_ids: List[ConfigId], losses: np.ndarray
    ) -> np.ndarray:
        """bool[n] promotion mask — implemented by subclasses."""
        raise NotImplementedError

    def reported_cost(
        self, config_id: ConfigId, budget: float
    ) -> Optional[float]:
        """The explicit ``cost`` an evaluation reported in its info
        payload (a worker measuring device seconds, not wall) — the only
        genuinely PER-CANDIDATE cost measurement; None when the
        evaluation reported none. Split out of :meth:`measured_cost` so
        cost-aware promotion (promote/pareto.py) can prefer a reported
        measurement, then an obs-histogram aggregate, and fall back to
        the wall span only when neither exists."""
        d = self.data.get(config_id)
        if d is None:
            return None
        info = d.infos.get(budget)
        if isinstance(info, dict):
            cost = info.get("cost")
            if isinstance(cost, (int, float)) and np.isfinite(cost):
                return float(cost)
        return None

    def wall_span_cost(
        self, config_id: ConfigId, budget: float
    ) -> Optional[float]:
        """The started->finished wall span the job's timestamp schema
        records — the noisiest cost estimate (queue/dispatch jitter
        included), kept as the last-resort fallback."""
        d = self.data.get(config_id)
        if d is None:
            return None
        ts = d.time_stamps.get(budget) or {}
        try:
            span = float(ts["finished"]) - float(ts["started"])
        except (KeyError, TypeError, ValueError):
            return None
        return span if np.isfinite(span) and span >= 0 else None

    def measured_cost(
        self, config_id: ConfigId, budget: float
    ) -> Optional[float]:
        """Measured evaluation cost (seconds) of one config at one rung,
        or None when unmeasured.

        Priority: an explicit ``cost`` the evaluation reported in its
        info payload (:meth:`reported_cost`), then the started->finished
        wall span (:meth:`wall_span_cost`). This is the cost column
        multi-objective promotion ranks (promote/pareto.py) and what
        rides ``promotion_decision.costs`` so a recorded journal stays
        Pareto-replayable.
        """
        cost = self.reported_cost(config_id, budget)
        if cost is not None:
            return cost
        return self.wall_span_cost(config_id, budget)

    def promotion_cost(
        self, config_id: ConfigId, budget: float
    ) -> Optional[float]:
        """The cost column the audit record journals. Default: the
        measured cost. A rule ranking by a custom cost (ParetoIteration's
        ``cost_fn``) overrides this so ``promotion_decision.costs``
        carries the numbers the decision ACTUALLY used — the replay
        harness's Pareto re-scoring depends on that fidelity."""
        return self.measured_cost(config_id, budget)

    # ------------------------------------------------------- array interface
    def loss_matrix(self) -> Tuple[List[ConfigId], np.ndarray]:
        """Struct-of-arrays view: ``(ids, f64[n_configs, n_stages])`` NaN-masked."""
        ids = list(self.data.keys())
        mat = np.full((len(ids), self.n_stages), np.nan)
        for i, cid in enumerate(ids):
            for j, b in enumerate(self.budgets):
                v = self.data[cid].results.get(b)
                if v is not None:
                    mat[i, j] = v
        return ids, mat

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"{type(self).__name__}(iter={self.HPB_iter}, stage={self.stage}/"
            f"{self.n_stages}, configs={self.actual_num_configs}, "
            f"finished={self.is_finished})"
        )
