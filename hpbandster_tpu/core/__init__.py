"""Core runtime: jobs, iterations, master loop, results."""

from hpbandster_tpu.core.job import Job  # noqa: F401
from hpbandster_tpu.core.iteration import BaseIteration, Datum, Status  # noqa: F401
from hpbandster_tpu.core.successive_halving import (  # noqa: F401
    JaxSuccessiveHalving,
    SuccessiveHalving,
    SuccessiveResampling,
)
from hpbandster_tpu.core.master import Master  # noqa: F401
from hpbandster_tpu.core.result import (  # noqa: F401
    Result,
    Run,
    extract_HBS_learning_curves,
    json_result_logger,
    logged_results_to_HBS_result,
)
