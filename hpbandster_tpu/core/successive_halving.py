"""Concrete iteration types: SuccessiveHalving and SuccessiveResampling.

The promotion rules live as jittable kernels in ``ops/bracket.py``; these
classes only adapt them to the Datum bookkeeping. Reference counterparts:
``optimizers/iterations/successivehalving.py`` and
``successiveresampling.py`` (SURVEY.md §2).
"""

from __future__ import annotations

from typing import List

import numpy as np

from hpbandster_tpu.core.iteration import BaseIteration
from hpbandster_tpu.core.job import ConfigId
from hpbandster_tpu.ops.bracket import sh_promotion_mask_np

__all__ = ["SuccessiveHalving", "SuccessiveResampling", "JaxSuccessiveHalving"]


class SuccessiveHalving(BaseIteration):
    """Promote the best ``num_configs[next_stage]`` configs by loss rank."""

    promotion_rule = "successive_halving"

    def _advance_to_next_stage(
        self, config_ids: List[ConfigId], losses: np.ndarray
    ) -> np.ndarray:
        k = self.num_configs[self.stage + 1]
        return sh_promotion_mask_np(losses, k)


class SuccessiveResampling(BaseIteration):
    """Promote fewer survivors and refill the gap with fresh samples.

    ``resampling_rate`` is the fraction of the next stage drawn fresh from the
    config generator instead of promoted (reference variant, SURVEY.md §2
    "SuccessiveResampling iteration").
    """

    promotion_rule = "successive_resampling"

    def __init__(self, *args, resampling_rate: float = 0.5, min_samples_advance: int = 1, **kwargs):
        super().__init__(*args, **kwargs)
        self.resampling_rate = float(resampling_rate)
        self.min_samples_advance = int(min_samples_advance)

    def _advance_to_next_stage(
        self, config_ids: List[ConfigId], losses: np.ndarray
    ) -> np.ndarray:
        k = self.num_configs[self.stage + 1]
        n_promote = max(
            int(np.ceil(k * (1.0 - self.resampling_rate))), self.min_samples_advance
        )
        # the unfilled remainder of the next stage is topped up by
        # get_next_run() sampling fresh configs (actual_num_configs < quota)
        return sh_promotion_mask_np(losses, min(n_promote, k))


class JaxSuccessiveHalving(SuccessiveHalving):
    """SuccessiveHalving whose promotion mask is decided on-device.

    The per-bracket allocation (the top-k ranking) runs as the jitted
    ``ops.bracket.sh_promotion_mask`` kernel instead of host numpy — the
    "per-bracket allocation decided on-device" half of the north star. The
    kernel is bit-identical to the host rule (same NaN -> +inf, f32
    double-argsort ranking), so fused-bracket caches and host bookkeeping
    always agree; use this iteration type when the Master itself runs
    colocated with the accelerator (e.g. ``BOHB(..., iteration_class=
    JaxSuccessiveHalving)``) and the loss vector is already device-resident.
    """

    promotion_rule = "successive_halving_jax"

    _jitted = None

    def _advance_to_next_stage(
        self, config_ids: List[ConfigId], losses: np.ndarray
    ) -> np.ndarray:
        import jax.numpy as jnp

        from hpbandster_tpu.ops.bracket import sh_promotion_mask_compiled

        if JaxSuccessiveHalving._jitted is None:
            # tracked_jit: the promotion kernel's compile lands in the
            # same xla_compile ledger as the fused brackets
            JaxSuccessiveHalving._jitted = sh_promotion_mask_compiled()
        k = self.num_configs[self.stage + 1]
        mask = JaxSuccessiveHalving._jitted(
            jnp.asarray(losses, jnp.float32), jnp.asarray(k, jnp.int32)
        )
        return np.asarray(mask)
