"""Alias module: the reference's dispatcher lives at ``core/dispatcher.py``
(SURVEY.md §1 layer map); the implementation here sits in the parallel tier
next to its sibling executors."""

from hpbandster_tpu.parallel.dispatcher import Dispatcher, WorkerProxy  # noqa: F401
from hpbandster_tpu.core.job import Job  # noqa: F401
