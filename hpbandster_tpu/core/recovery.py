"""Crash recovery: write-ahead result journal + exactly-once replay.

The elastic fleet loses processes, not work (docs/fault_tolerance.md):

* **Idempotency keys** — every job carries one stable key,
  :func:`idempotency_key` of its ``(config_id, budget)``. A job requeued
  onto another worker, a late dead-letter arrival, and a worker's
  delivery retry racing a slow ack all compute the SAME logical result,
  so the key is what lets every ingest point recognize "already have it".
* **:class:`ExactlyOnceGate`** — the thread-safe seen-set those ingest
  points share: ``admit(key)`` is True exactly once per key, so one
  result is registered into the bracket exactly once no matter how many
  copies arrive.
* **:class:`ResultWAL`** — a write-ahead JSONL journal of terminal
  results. The Master appends each result BEFORE bracket bookkeeping
  consumes it; after a crash, the WAL tail covers everything the last
  periodic checkpoint missed. Appends are line-atomic (a crash mid-write
  truncates at most the final line, which replay tolerates), first
  record per key wins.
* **:class:`DeadLetterBox`** — the dispatcher's bounded retention of
  results that arrived for unknown jobs, keyed so a resubmitted job can
  :meth:`~DeadLetterBox.take` its stranded payload and join it back
  exactly once. Overflow is COUNTED (``dispatcher.dead_letters_dropped``)
  instead of silent.
* **:func:`resume_master`** — crash-restart: restore the checkpoint into
  a fresh optimizer, replay the WAL tail into the restored brackets
  (only records matching a still-QUEUED datum at its current budget are
  eligible), and return the stats. A subsequent ``run()`` re-dispatches
  ONLY the configs with no recorded terminal result.

Everything here is host-side stdlib — no jax imports.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from hpbandster_tpu import obs
from hpbandster_tpu.core.iteration import Status
from hpbandster_tpu.core.job import Job

__all__ = [
    "idempotency_key",
    "ExactlyOnceGate",
    "ResultWAL",
    "DeadLetterBox",
    "replay_wal_into_master",
    "ingested_keys",
    "resume_master",
]

logger = logging.getLogger("hpbandster_tpu.recovery")


def idempotency_key(config_id: Iterable[Any], budget: Any) -> str:
    """Stable exactly-once identity of one logical evaluation.

    Keyed by what makes the result a duplicate — the ``(config_id,
    budget)`` pair — NOT by dispatch attempt: a requeue re-computes the
    same logical result, and the second copy to arrive must be
    recognized. ``%g`` budget formatting matches the journal readers'
    (``9`` and ``9.0`` are one rung).
    """
    cid = "-".join(str(int(x)) for x in config_id)
    return f"{cid}@{float(budget):g}"


class ExactlyOnceGate:
    """Thread-safe admit-once set over idempotency keys."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seen: Set[str] = set()

    def admit(self, key: str) -> bool:
        """True the first time ``key`` is presented, False ever after."""
        with self._lock:
            if key in self._seen:
                return False
            self._seen.add(key)
            return True

    def seen(self, key: str) -> bool:
        with self._lock:
            return key in self._seen

    def mark(self, keys: Iterable[str]) -> None:
        """Pre-admit ``keys`` (restore path: results the checkpoint or WAL
        already accounted for must read as duplicates from now on)."""
        with self._lock:
            self._seen.update(keys)

    def __len__(self) -> int:
        with self._lock:
            return len(self._seen)


class ResultWAL:
    """Append-only JSONL write-ahead journal of terminal results.

    One line per result, written (and flushed) BEFORE the in-memory
    bracket state consumes it — so the crash window between "result
    arrived" and "checkpoint wrote it" loses nothing. First record per
    idempotency key wins; duplicates are not re-written.

    ``fsync=True`` additionally fsyncs per append (durability against
    host power loss, at measurable cost); the default flush survives
    process death, which is the failure the fleet actually has.

    ``run_id`` stamps every record: idempotency keys restart at
    ``(0,0,0)@1`` for every run, so a wal_path reused across
    INDEPENDENT runs would otherwise suppress the new run's journaling
    (stale keys pre-seeding the dedup set) and replay the previous
    run's losses after a crash. With the stamp, a foreign run's leftover
    records neither pre-seed dedup nor replay (and a loud warning names
    them); records without a stamp (legacy WALs) keep the old behavior.
    """

    def __init__(
        self, path: str, fsync: bool = False, run_id: Optional[str] = None
    ):
        self.path = path
        self.fsync = bool(fsync)
        self.run_id = run_id
        self._lock = threading.Lock()
        self._seen: Set[str] = set()
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        # a reopened WAL continues its dedup set from disk: a restarted
        # master appending to the same path cannot double-record a key.
        # Only THIS run's (or unstamped legacy) records count — another
        # run's leftovers must not suppress this run's journaling.
        foreign = 0
        for rec in self.read(path):
            if _run_matches(rec, run_id):
                self._seen.add(rec["key"])
            else:
                foreign += 1
        if foreign:
            logger.warning(
                "WAL %s holds %d record(s) from another run (reused "
                "path?); they will not dedup or replay into run %r",
                path, foreign, run_id,
            )
        self._fh = open(path, "a", encoding="utf-8")

    def append(
        self,
        key: str,
        config_id: Iterable[Any],
        budget: float,
        result: Optional[Dict[str, Any]],
        exception: Optional[str],
        timestamps: Optional[Dict[str, float]] = None,
    ) -> bool:
        """Record one terminal result; False if ``key`` was already
        recorded (first wins). Strict JSON — non-finite floats inside
        ``result`` would poison replay, so they are nulled recursively.
        """
        rec = {
            "key": key,
            "config_id": [int(x) for x in config_id],
            "budget": float(budget),
            "result": result,
            "exception": exception,
            "timestamps": dict(timestamps or {}),
        }
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        try:
            line = json.dumps(rec, allow_nan=False)
        except (ValueError, TypeError):
            # the journal's strict-JSON slow path: recursive non-finite
            # nulling + non-JSON-type coercion (numpy scalars in a
            # result dict), one sanitizer for every JSONL surface
            from hpbandster_tpu.obs.journal import _definite, _jsonable

            line = json.dumps(
                _definite(rec), default=_jsonable, allow_nan=False
            )
        with self._lock:
            if key in self._seen:
                return False
            if self._fh.closed:
                return False
            self._seen.add(key)
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
        obs.get_metrics().counter("recovery.wal_records").inc()
        return True

    def keys(self) -> Set[str]:
        with self._lock:
            return set(self._seen)

    def truncate(self) -> None:
        """Drop every record (called right after a successful checkpoint:
        the checkpoint now carries this state, the WAL restarts empty)."""
        with self._lock:
            self._fh.close()
            self._fh = open(self.path, "w", encoding="utf-8")
            self._seen.clear()

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    @staticmethod
    def read(path: str) -> List[Dict[str, Any]]:
        """Replay records from disk, oldest first, first-per-key wins.
        A truncated final line (crash mid-append) is tolerated; corrupt
        interior lines are skipped with a warning."""
        records: List[Dict[str, Any]] = []
        seen: Set[str] = set()
        try:
            fh = open(path, "r", encoding="utf-8")
        except FileNotFoundError:
            return records
        with fh:
            for lineno, line in enumerate(fh, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    logger.warning(
                        "WAL %s line %d unreadable (crash mid-write?); "
                        "skipped", path, lineno,
                    )
                    continue
                key = rec.get("key")
                if not isinstance(key, str) or key in seen:
                    continue
                seen.add(key)
                records.append(rec)
        return records


def _run_matches(rec: Dict[str, Any], run_id: Optional[str]) -> bool:
    """A WAL record belongs to ``run_id`` when either side is unstamped
    (legacy records / callers) or the stamps agree."""
    rec_run = rec.get("run_id")
    return rec_run is None or run_id is None or rec_run == run_id


class DeadLetterBox:
    """Bounded keyed retention of results that arrived for unknown jobs.

    The dispatcher's replacement for its old anonymous ring: same
    ``snapshot()`` surface (the health endpoint's ring tail), plus
    :meth:`take` — a resubmitted job can claim its stranded payload by
    idempotency key and join it back exactly once — and a drop COUNTER
    (``dispatcher.dead_letters_dropped``) where overflow used to be
    silent.
    """

    def __init__(self, capacity: int = 64):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._items: "List[Dict[str, Any]]" = []
        self.dropped = 0

    def append(self, item: Dict[str, Any]) -> None:
        key = item.get("key")
        with self._lock:
            if key is not None and any(
                i.get("key") == key for i in self._items
            ):
                # a second copy of the same stranded result (chaos
                # duplicate frames, delivery retries): one payload is
                # enough to replay — retaining both would let garbage
                # copies evict OTHER jobs' genuine payloads
                duplicate = True
            else:
                duplicate = False
                self._items.append(item)
            overflow = len(self._items) - self.capacity
            if overflow > 0:
                del self._items[:overflow]
                self.dropped += overflow
        if duplicate:
            obs.get_metrics().counter("recovery.duplicates_dropped").inc()
            logger.info(
                "duplicate dead letter for key %s dropped (payload already "
                "retained)", key,
            )
        if overflow > 0:
            obs.get_metrics().counter(
                "dispatcher.dead_letters_dropped"
            ).inc(overflow)
            logger.warning(
                "dead-letter box overflow: %d oldest payload(s) dropped "
                "(capacity %d)", overflow, self.capacity,
            )

    def take(self, key: str) -> Optional[Dict[str, Any]]:
        """Remove and return the oldest retained record whose ``key``
        matches, or None."""
        with self._lock:
            for i, item in enumerate(self._items):
                if item.get("key") == key:
                    return self._items.pop(i)
        return None

    def snapshot(self) -> List[Dict[str, Any]]:
        """Oldest-first copy (HealthEndpoint ring contract)."""
        with self._lock:
            return list(self._items)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


# --------------------------------------------------------------- replay
def _eligible_datum(master, cid: Tuple[int, ...], budget: float):
    """The restored datum a WAL record may replay into: still QUEUED, at
    exactly this budget (a record for an already-promoted or
    already-recorded rung is stale — the checkpoint got there first)."""
    if not (0 <= cid[0] < len(master.iterations)):
        return None
    it = master.iterations[cid[0]]
    d = it.data.get(cid)
    if d is None or d.status != Status.QUEUED:
        return None
    if float(d.budget) != float(budget):
        return None
    return it


def replay_wal_into_master(master, wal_path: str) -> Dict[str, int]:
    """Join WAL records back into a restored Master exactly once.

    Each eligible record becomes a finished :class:`Job` pushed through
    ``master.job_callback`` — the same funnel live results take, so
    result logging, model updates, bracket advancement, and audit events
    all happen exactly as if the result had arrived normally. Records
    whose datum is not QUEUED at the recorded budget are skipped (the
    checkpoint already holds them, or the rung moved on).
    """
    stats = {"replayed": 0, "skipped": 0}
    run_id = getattr(master, "run_id", None)
    foreign = 0
    for rec in ResultWAL.read(wal_path):
        if not _run_matches(rec, run_id):
            # another run's leftovers in a reused wal_path: its keys
            # collide with this run's ((0,0,0)@1 restarts every run) but
            # its LOSSES belong to a different sweep — joining them
            # would silently corrupt the brackets
            foreign += 1
            stats["skipped"] += 1
            continue
        cid = tuple(int(x) for x in rec.get("config_id", ()))
        budget = rec.get("budget")
        if len(cid) != 3 or not isinstance(budget, (int, float)):
            stats["skipped"] += 1
            continue
        with master.thread_cond:
            it = _eligible_datum(master, cid, float(budget))
            if it is None:
                stats["skipped"] += 1
                continue
            d = it.data[cid]
            job = Job(
                cid, config=d.config, budget=float(budget),
                working_directory=getattr(master, "working_directory", "."),
            )
            job.result = rec.get("result")
            job.exception = rec.get("exception")
            for which, t in (rec.get("timestamps") or {}).items():
                if isinstance(t, (int, float)):
                    job.timestamps[which] = float(t)
            # register_result requires RUNNING; the replay IS the run
            d.status = Status.RUNNING
            it.num_running += 1
            master.num_running_jobs += 1
        master.job_callback(job)
        obs.emit(
            obs.RESULT_REPLAYED,
            config_id=list(cid), budget=float(budget),
            source="wal", key=rec.get("key"),
        )
        obs.get_metrics().counter("recovery.replayed_results").inc()
        stats["replayed"] += 1
    if foreign:
        logger.warning(
            "WAL %s: %d record(s) from another run ignored during replay "
            "into run %r (reused path?)", wal_path, foreign, run_id,
        )
    if stats["replayed"]:
        logger.info(
            "WAL replay: %d result(s) joined back, %d stale record(s) "
            "skipped", stats["replayed"], stats["skipped"],
        )
    return stats


def ingested_keys(master) -> Set[str]:
    """Every idempotency key the master's restored bracket state already
    holds a recorded result for (one per ``Datum.results`` rung entry)."""
    keys: Set[str] = set()
    for it in master.iterations:
        for cid, d in it.data.items():
            for b in d.results:
                keys.add(idempotency_key(cid, b))
    return keys


def resume_master(
    master, checkpoint_path: str, wal_path: Optional[str] = None
) -> Dict[str, int]:
    """Crash-restart a fresh optimizer: checkpoint + WAL tail.

    Restores ``checkpoint_path`` (mid-bracket state; interrupted RUNNING
    configs roll back to QUEUED), then replays ``wal_path`` so every
    result that arrived AFTER the last checkpoint re-joins without
    re-running its evaluation. The next ``run(n_iterations=<same
    total>)`` dispatches only genuinely unfinished configs.

    If the executor carries an exactly-once gate (the dispatcher does),
    it is pre-seeded with every key the restored state accounts for —
    a first-life worker that survived the crash and rediscovered the
    new pool must have its late re-delivery read as a duplicate, not a
    fresh unknown result.
    """
    master.load_checkpoint(checkpoint_path)
    stats = (
        replay_wal_into_master(master, wal_path)
        if wal_path is not None else {"replayed": 0, "skipped": 0}
    )
    gate = getattr(master.executor, "_gate", None)
    if isinstance(gate, ExactlyOnceGate):
        gate.mark(ingested_keys(master))
    return stats
