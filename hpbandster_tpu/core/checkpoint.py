"""Mid-run optimizer-state checkpointing.

The reference only checkpoints at the *results* level (JSONL streaming +
warm-start; SURVEY.md §5 "Checkpoint / resume": "No mid-bracket resume of
the Master's internal state"). This module adds that missing capability:
the full Master state — every bracket's Datum bookkeeping, stage pointers,
and the config generator's observations/RNG — serializes to one file, and a
freshly-constructed optimizer resumes exactly where the run stopped.
In-flight (RUNNING) configs are rolled back to QUEUED so their evaluations
re-run after restore.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict

from hpbandster_tpu.core.iteration import Datum, Status

__all__ = [
    "master_state_dict",
    "restore_master_state",
    "save_checkpoint",
    "load_checkpoint",
    "fused_state_dict",
    "restore_fused_state",
    "save_fused_checkpoint",
    "load_fused_checkpoint",
]

_FORMAT_VERSION = 1


def _datum_state(d: Datum) -> Dict[str, Any]:
    status = d.status
    if status == Status.RUNNING:  # re-run interrupted evaluations on resume
        status = Status.QUEUED
    return {
        "config": d.config,
        "config_info": d.config_info,
        "results": d.results,
        "time_stamps": d.time_stamps,
        "exceptions": d.exceptions,
        "infos": d.infos,
        "status": int(status),
        "budget": d.budget,
    }


def _iteration_state(it) -> Dict[str, Any]:
    return {
        "HPB_iter": it.HPB_iter,
        "num_configs": list(it.num_configs),
        "budgets": list(it.budgets),
        "stage": it.stage,
        "actual_num_configs": list(it.actual_num_configs),
        "is_finished": it.is_finished,
        "data": {cid: _datum_state(d) for cid, d in it.data.items()},
    }


def _restore_iteration(it, it_state: Dict[str, Any]) -> None:
    it.stage = it_state["stage"]
    it.actual_num_configs = list(it_state["actual_num_configs"])
    it.is_finished = it_state["is_finished"]
    it.num_running = 0
    it.data = {}
    for cid, ds in it_state["data"].items():
        d = Datum(
            config=ds["config"],
            config_info=ds["config_info"],
            results=ds["results"],
            time_stamps=ds["time_stamps"],
            exceptions=ds["exceptions"],
            status=Status(ds["status"]),
            budget=ds["budget"],
        )
        d.infos = dict(ds.get("infos", {}))
        it.data[tuple(cid)] = d


def _check_iteration_shape(it, it_state: Dict[str, Any]) -> None:
    if list(it.num_configs) != it_state["num_configs"] or [
        float(b) for b in it.budgets
    ] != it_state["budgets"]:
        raise ValueError(
            f"iteration {it_state['HPB_iter']} shape mismatch: checkpoint "
            f"{it_state['num_configs']}@{it_state['budgets']} vs "
            f"{list(it.num_configs)}@{list(it.budgets)} — was the "
            "optimizer constructed with different eta/budget settings?"
        )


def master_state_dict(master) -> Dict[str, Any]:
    """Snapshot a Master (under its own lock) into a picklable dict."""
    with master.thread_cond:
        iterations = [_iteration_state(it) for it in master.iterations]
        state = {
            "format_version": _FORMAT_VERSION,
            "config": dict(master.config),
            "time_ref": master.time_ref,
            "iterations": iterations,
        }
        if hasattr(master.config_generator, "get_state"):
            state["config_generator"] = master.config_generator.get_state()
    return state


def restore_master_state(master, state: Dict[str, Any]) -> None:
    """Rehydrate a freshly-constructed Master from :func:`master_state_dict`.

    The master must have been built with the same bracket arithmetic
    (eta / budgets) — iteration shapes are re-derived via
    ``get_next_iteration`` and verified against the snapshot.
    """
    if state.get("format_version") != _FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {state.get('format_version')}")
    if state.get("kind") == "fused":
        raise ValueError(
            "fused-tier checkpoint (use FusedBOHB.load_checkpoint)"
        )
    with master.thread_cond:
        if master.iterations:
            raise RuntimeError("can only restore into a fresh Master")
        master.config.update(state["config"])
        master.time_ref = state["time_ref"]
        if "config_generator" in state and hasattr(
            master.config_generator, "set_state"
        ):
            master.config_generator.set_state(state["config_generator"])
        for it_state in state["iterations"]:
            it = master.get_next_iteration(
                it_state["HPB_iter"], {"result_logger": master.result_logger}
            )
            _check_iteration_shape(it, it_state)
            _restore_iteration(it, it_state)
            master.iterations.append(it)


def _rank_fn_name(fn) -> Any:
    """Best-effort STABLE identity for a promotion-rank callable.

    ``__qualname__`` when the callable has one (plain functions — the
    FusedH2BO case), else the type's qualname (``functools.partial`` etc.).
    Never ``repr``: that embeds a memory address, which would reject every
    legitimate resume of a qualname-less callable. This guard catches
    class/None mismatches and differently-NAMED functions; two distinct
    callables of the same name (two lambdas, two partials) are on the
    caller to keep consistent — same contract as the eval_fn itself, which
    is not checkpointed at all.
    """
    if fn is None:
        return None
    name = getattr(fn, "__qualname__", None)
    return name if name is not None else type(fn).__qualname__


def fused_state_dict(opt) -> Dict[str, Any]:
    """Snapshot a FusedBOHB-family optimizer at a chunk boundary.

    Captures everything the next chunk's device computation consumes: the
    replayed bracket bookkeeping (for the final ``Result``), the warm
    observation buffers (the device model's entire memory), the bracket
    rotation position, and the numpy RNG state — so a resumed run draws the
    SAME chunk seeds an uninterrupted run would have drawn.
    """
    import numpy as np

    return {
        "format_version": _FORMAT_VERSION,
        "kind": "fused",
        # opt.config alone cannot distinguish FusedBOHB from FusedH2BO
        # (promotion_rank_fn is not a config knob) nor record the scorer
        # backend — pin both so restore cannot silently switch promotion
        # semantics mid-sweep (ADVICE r3)
        "optimizer_class": type(opt).__name__,
        "promotion_rank_fn": _rank_fn_name(opt.promotion_rank_fn),
        "use_pallas": bool(opt.use_pallas),
        "config": dict(opt.config),
        "iterations": [_iteration_state(it) for it in opt.iterations],
        "warm_v": {b: np.asarray(v) for b, v in opt._warm_v.items()},
        "warm_l": {b: np.asarray(l) for b, l in opt._warm_l.items()},
        "rng_state": opt.rng.bit_generator.state,
        "total_evaluated": opt.total_evaluated,
        "run_stats": list(opt.run_stats),
    }


def restore_fused_state(opt, state: Dict[str, Any]) -> None:
    """Rehydrate a freshly-constructed fused optimizer from
    :func:`fused_state_dict`; the next ``run()`` continues with the
    remaining brackets (same constructor args required — shapes verified)."""
    from hpbandster_tpu.core.successive_halving import SuccessiveHalving

    if state.get("format_version") != _FORMAT_VERSION:
        raise ValueError(
            f"unsupported checkpoint version {state.get('format_version')}"
        )
    if state.get("kind") != "fused":
        raise ValueError("not a fused-tier checkpoint (use load_checkpoint)")
    if opt.iterations:
        raise RuntimeError("can only restore into a fresh optimizer")
    # class/semantics guard (ADVICE r3): a FusedH2BO checkpoint must not
    # restore into a plain FusedBOHB — the remaining brackets would switch
    # from LC-extrapolated to raw-loss promotion without any error. Old
    # (round-3) checkpoints lack these keys; skip the guard for those.
    if "optimizer_class" in state:
        if state["optimizer_class"] != type(opt).__name__:
            raise ValueError(
                f"checkpoint was written by {state['optimizer_class']}, "
                f"restoring into {type(opt).__name__} — promotion semantics "
                "would silently change; construct the matching class"
            )
        mine_rank = _rank_fn_name(opt.promotion_rank_fn)
        if state["promotion_rank_fn"] != mine_rank:
            raise ValueError(
                f"checkpoint promotion_rank_fn "
                f"{state['promotion_rank_fn']!r} != optimizer's "
                f"{mine_rank!r} — resume requires identical promotion "
                "semantics"
            )
        if state["use_pallas"] != bool(opt.use_pallas):
            raise ValueError(
                f"checkpoint used use_pallas={state['use_pallas']}, "
                f"optimizer has use_pallas={opt.use_pallas} — pass the "
                "same scorer backend to resume"
            )
    # bracket shapes alone don't pin the optimizer's behavior — the KDE
    # knobs (num_samples, top_n_percent, ...) must match too, or the
    # resumed run silently diverges while its artifacts report the
    # checkpoint's values
    ckpt_knobs = {k: v for k, v in state["config"].items() if k != "time_ref"}
    mine = {k: v for k, v in opt.config.items() if k != "time_ref"}
    if ckpt_knobs != mine:
        diff = sorted(
            k
            for k in set(ckpt_knobs) | set(mine)
            if ckpt_knobs.get(k) != mine.get(k)
        )
        raise ValueError(
            f"checkpoint optimizer settings differ from constructor "
            f"settings in {diff} — resume requires identical knobs"
        )

    def no_sampler(budget):
        raise RuntimeError("restored fused brackets must not sample configs")

    # build + validate everything BEFORE touching the optimizer, so a shape
    # mismatch leaves it untouched (and retryable with the right checkpoint)
    restored = []
    for it_state in state["iterations"]:
        plan = opt._plan(it_state["HPB_iter"])
        it = SuccessiveHalving(
            HPB_iter=it_state["HPB_iter"],
            num_configs=list(plan.num_configs),
            budgets=list(plan.budgets),
            config_sampler=no_sampler,
            result_logger=opt.result_logger,
        )
        _check_iteration_shape(it, it_state)
        _restore_iteration(it, it_state)
        restored.append(it)
    opt.config.update(state["config"])
    opt.iterations.extend(restored)
    opt._warm_v = {float(b): v for b, v in state["warm_v"].items()}
    opt._warm_l = {float(b): l for b, l in state["warm_l"].items()}
    opt.rng.bit_generator.state = state["rng_state"]
    opt.total_evaluated = int(state["total_evaluated"])
    # resumed chunks continue the chunk numbering and keep the dead run's
    # timing trail — fused_timings.json stays a complete artifact record
    opt.run_stats = list(state.get("run_stats", []))


def _atomic_pickle(state: Dict[str, Any], path: str) -> None:
    import os

    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        pickle.dump(state, fh)
    os.replace(tmp, path)  # atomic: a crash mid-write never corrupts


def save_checkpoint(master, path: str) -> None:
    _atomic_pickle(master_state_dict(master), path)


def load_checkpoint(master, path: str) -> None:
    with open(path, "rb") as fh:
        state = pickle.load(fh)
    restore_master_state(master, state)


def save_fused_checkpoint(opt, path: str) -> None:
    _atomic_pickle(fused_state_dict(opt), path)


def load_fused_checkpoint(opt, path: str) -> None:
    with open(path, "rb") as fh:
        state = pickle.load(fh)
    restore_fused_state(opt, state)
