"""Mid-run optimizer-state checkpointing.

The reference only checkpoints at the *results* level (JSONL streaming +
warm-start; SURVEY.md §5 "Checkpoint / resume": "No mid-bracket resume of
the Master's internal state"). This module adds that missing capability:
the full Master state — every bracket's Datum bookkeeping, stage pointers,
and the config generator's observations/RNG — serializes to one file, and a
freshly-constructed optimizer resumes exactly where the run stopped.
In-flight (RUNNING) configs are rolled back to QUEUED so their evaluations
re-run after restore.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict

from hpbandster_tpu.core.iteration import Datum, Status

__all__ = ["master_state_dict", "restore_master_state", "save_checkpoint", "load_checkpoint"]

_FORMAT_VERSION = 1


def _datum_state(d: Datum) -> Dict[str, Any]:
    status = d.status
    if status == Status.RUNNING:  # re-run interrupted evaluations on resume
        status = Status.QUEUED
    return {
        "config": d.config,
        "config_info": d.config_info,
        "results": d.results,
        "time_stamps": d.time_stamps,
        "exceptions": d.exceptions,
        "status": int(status),
        "budget": d.budget,
    }


def master_state_dict(master) -> Dict[str, Any]:
    """Snapshot a Master (under its own lock) into a picklable dict."""
    with master.thread_cond:
        iterations = []
        for it in master.iterations:
            iterations.append(
                {
                    "HPB_iter": it.HPB_iter,
                    "num_configs": list(it.num_configs),
                    "budgets": list(it.budgets),
                    "stage": it.stage,
                    "actual_num_configs": list(it.actual_num_configs),
                    "is_finished": it.is_finished,
                    "data": {cid: _datum_state(d) for cid, d in it.data.items()},
                }
            )
        state = {
            "format_version": _FORMAT_VERSION,
            "config": dict(master.config),
            "time_ref": master.time_ref,
            "iterations": iterations,
        }
        if hasattr(master.config_generator, "get_state"):
            state["config_generator"] = master.config_generator.get_state()
    return state


def restore_master_state(master, state: Dict[str, Any]) -> None:
    """Rehydrate a freshly-constructed Master from :func:`master_state_dict`.

    The master must have been built with the same bracket arithmetic
    (eta / budgets) — iteration shapes are re-derived via
    ``get_next_iteration`` and verified against the snapshot.
    """
    if state.get("format_version") != _FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {state.get('format_version')}")
    with master.thread_cond:
        if master.iterations:
            raise RuntimeError("can only restore into a fresh Master")
        master.config.update(state["config"])
        master.time_ref = state["time_ref"]
        if "config_generator" in state and hasattr(
            master.config_generator, "set_state"
        ):
            master.config_generator.set_state(state["config_generator"])
        for it_state in state["iterations"]:
            it = master.get_next_iteration(
                it_state["HPB_iter"], {"result_logger": master.result_logger}
            )
            if list(it.num_configs) != it_state["num_configs"] or [
                float(b) for b in it.budgets
            ] != it_state["budgets"]:
                raise ValueError(
                    f"iteration {it_state['HPB_iter']} shape mismatch: checkpoint "
                    f"{it_state['num_configs']}@{it_state['budgets']} vs "
                    f"{list(it.num_configs)}@{list(it.budgets)} — was the "
                    "optimizer constructed with different eta/budget settings?"
                )
            it.stage = it_state["stage"]
            it.actual_num_configs = list(it_state["actual_num_configs"])
            it.is_finished = it_state["is_finished"]
            it.num_running = 0
            it.data = {
                tuple(cid): Datum(
                    config=ds["config"],
                    config_info=ds["config_info"],
                    results=ds["results"],
                    time_stamps=ds["time_stamps"],
                    exceptions=ds["exceptions"],
                    status=Status(ds["status"]),
                    budget=ds["budget"],
                )
                for cid, ds in it_state["data"].items()
            }
            master.iterations.append(it)


def save_checkpoint(master, path: str) -> None:
    state = master_state_dict(master)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        pickle.dump(state, fh)
    import os

    os.replace(tmp, path)  # atomic: a crash mid-write never corrupts


def load_checkpoint(master, path: str) -> None:
    with open(path, "rb") as fh:
        state = pickle.load(fh)
    restore_master_state(master, state)
