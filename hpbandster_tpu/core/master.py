"""Master — the optimizer main loop.

Reference semantics preserved (SURVEY.md §2 "Master" row, §3.1/§3.3):
owns a job executor + config generator + list of iteration objects;
``run()`` waits for workers, pulls ready runs from active iterations, creates
new iterations up to ``n_iterations``, submits jobs, and sleeps on a
condition variable when the in-flight queue is full; ``job_callback``
registers results, updates the model, and advances brackets.

The executor seam is this rebuild's key generalization: the same Master
drives either the asynchronous host worker pool (``parallel.Dispatcher``,
the reference's architecture) or the batched on-device TPU path
(``parallel.BatchedExecutor``) where a whole wave of configs is one sharded
XLA computation. Batched executors buffer submitted jobs and evaluate them
when the Master drains its ready queue and calls ``flush()``.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from hpbandster_tpu import obs
from hpbandster_tpu.core.iteration import BaseIteration
from hpbandster_tpu.core.job import ConfigId, Job
from hpbandster_tpu.core.result import Result
from hpbandster_tpu.core.warmstart import WarmStartIteration

__all__ = ["Master"]


class Master:
    def __init__(
        self,
        run_id: str,
        config_generator,
        executor=None,
        working_directory: str = ".",
        logger: Optional[logging.Logger] = None,
        result_logger=None,
        previous_result: Optional[Result] = None,
        job_queue_sizes: Tuple[int, int] = (-1, 0),
        dynamic_queue_size: bool = True,
        # reference-compatible nameserver kwargs; used only when no executor
        # is passed explicitly and a Dispatcher must be constructed:
        nameserver: str = "127.0.0.1",
        nameserver_port: Optional[int] = None,
        host: Optional[str] = None,
        ping_interval: float = 60.0,
        shutdown_workers: bool = True,
        checkpoint_path: Optional[str] = None,
        checkpoint_interval: float = 30.0,
        wal_path: Optional[str] = None,
        collector: Any = None,
        tenant_id: Optional[str] = None,
    ):
        self.run_id = run_id
        self.config_generator = config_generator
        #: serving-tier identity (hpbandster_tpu/serve): when set, every
        #: event this master's loop emits — job lifecycle, bracket audit,
        #: config_sampled from its iterations — carries ``tenant_id``, and
        #: every RPC it makes ships the tenant in the ``_obs`` envelope.
        #: None (the default) changes nothing: single-tenant journals stay
        #: byte-identical.
        self.tenant_id = tenant_id
        self.working_directory = working_directory
        self.logger = logger or logging.getLogger("hpbandster_tpu.master")
        self.result_logger = result_logger

        self.iterations: List[BaseIteration] = []
        self.jobs: List[Job] = []
        self.num_running_jobs = 0
        self.job_queue_sizes = job_queue_sizes
        self.dynamic_queue_size = dynamic_queue_size
        if job_queue_sizes[0] >= job_queue_sizes[1]:
            raise ValueError("job_queue_sizes: need lower < upper")

        self.time_ref: Optional[float] = None
        self.config: Dict[str, Any] = {"time_ref": None}

        # optional mid-run state checkpointing (capability the reference
        # lacks — see core/checkpoint.py); auto-saves at most every
        # checkpoint_interval seconds from job_callback. Monotonic clock:
        # an NTP step must not suppress (or force) a checkpoint
        self.checkpoint_path = checkpoint_path
        self.checkpoint_interval = float(checkpoint_interval)
        self._last_checkpoint_mono = 0.0

        # write-ahead result journal (core/recovery.py): every terminal
        # result is journaled BEFORE bracket bookkeeping consumes it, so
        # checkpoint + WAL tail together lose no work across a crash —
        # resume via Master.resume(checkpoint_path, wal_path). The WAL
        # truncates after each successful checkpoint (the checkpoint now
        # carries that state), keeping it a tail, not a second history.
        self.wal_path = wal_path
        self._wal = None
        if wal_path is not None:
            from hpbandster_tpu.core.recovery import ResultWAL

            # run_id-stamped: a wal_path reused by a DIFFERENT run must
            # not have this run's journaling suppressed (or its replay
            # polluted) by the leftover records
            self._wal = ResultWAL(wal_path, run_id=run_id)

        # re-entrant: batched executors fire job_callback synchronously from
        # inside flush(), which runs under this same condition
        self.thread_cond = threading.Condition(threading.RLock())

        self.warmstart_iteration: List[Any] = []
        if previous_result is not None:
            self.warmstart_iteration = [
                WarmStartIteration(previous_result, self.config_generator)
            ]

        if executor is None:
            from hpbandster_tpu.parallel.dispatcher import Dispatcher

            executor = Dispatcher(
                run_id=run_id,
                nameserver=nameserver,
                nameserver_port=nameserver_port,
                host=host,
                ping_interval=ping_interval,
            )
        self.executor = executor
        self.executor.start(
            new_result_callback=self.job_callback,
            new_worker_callback=self.adjust_queue_size,
        )
        if getattr(self.executor, "unbounded_queue", False):
            self.dynamic_queue_size = False
            self.job_queue_sizes = (-1, float("inf"))
        # how many brackets may run concurrently before buffered work is
        # evaluated. Batched executors prefer 1 (each bracket's samples then
        # see all earlier results — the most sample-efficient, and each stage
        # is still one big device batch); async pools default to unlimited,
        # matching the reference's create-iterations-freely behavior.
        self.parallel_brackets: float = getattr(
            self.executor, "preferred_parallel_brackets", float("inf")
        )

        # fleet observatory (obs/collector.py, docs/observability.md
        # "Fleet observatory"): collector=True (defaults) or a dict of
        # FleetCollector kwargs (interval_s, series_path, ...) gives the
        # master its own health endpoint server AND a collector polling
        # master + dispatcher + every discovered worker into the derived
        # fleet gauges. Purely additive: no collector, no new threads.
        self.health_server = None
        self.fleet_collector = None
        if collector:
            self._start_collector(
                collector if isinstance(collector, dict) else {}
            )

    # ------------------------------------------------------ fleet observatory
    def _start_collector(self, options: Dict[str, Any]) -> None:
        """Serve this master's own ``obs_snapshot`` endpoint and start a
        :class:`~hpbandster_tpu.obs.collector.FleetCollector` polling the
        whole fleet — master + dispatcher + every discovered worker (the
        endpoint listing is re-read per round, so an elastic pool is
        tracked as it churns)."""
        from hpbandster_tpu.obs.collector import FleetCollector
        from hpbandster_tpu.parallel.rpc import RPCServer

        server = RPCServer(getattr(self.executor, "host", None) or "127.0.0.1", 0)
        obs.HealthEndpoint(
            component="master",
            identity=obs.process_identity(run_id=self.run_id),
            in_flight=self._health_in_flight,
        ).register(server)
        server.start()
        self.health_server = server
        self.fleet_collector = FleetCollector(
            endpoints=self._fleet_endpoints, **options
        ).start()

    def _health_in_flight(self) -> Dict[str, Any]:
        with self.thread_cond:
            return {
                "running_jobs": self.num_running_jobs,
                "iterations": len(self.iterations),
                "active_iterations": len(self.active_iterations()),
            }

    def _fleet_endpoints(self) -> Dict[str, str]:
        """The collector's per-round endpoint listing: every fleet process
        that answers ``obs_snapshot`` right now."""
        eps: Dict[str, str] = {}
        if self.health_server is not None:
            eps["master"] = self.health_server.uri
        server = getattr(self.executor, "_server", None)
        uri = getattr(server, "uri", None)
        if uri:
            eps["dispatcher"] = uri
        workers = getattr(self.executor, "workers", None)
        if isinstance(workers, dict):
            for name, w in list(workers.items()):
                w_uri = getattr(w, "uri", None)
                if w_uri:
                    eps[name] = w_uri
        return eps

    # ----------------------------------------------------------------- hooks
    def get_next_iteration(
        self, iteration: int, iteration_kwargs: Dict[str, Any]
    ) -> BaseIteration:
        """Instantiate the next bracket — implemented by optimizer subclasses."""
        raise NotImplementedError

    # -------------------------------------------------------------- plumbing
    def adjust_queue_size(self, number_of_workers: Optional[int] = None) -> None:
        """Retarget the in-flight window to the worker count (reference:
        ``dynamic_queue_size``; queue = (n_workers-1, n_workers))."""
        with self.thread_cond:
            n = (
                number_of_workers
                if number_of_workers is not None
                else self.executor.number_of_workers()
            )
            if self.dynamic_queue_size:
                self.job_queue_sizes = (max(n - 1, 0), max(n, 1))
                self.logger.debug("queue sizes adjusted to %s", self.job_queue_sizes)
            self.thread_cond.notify_all()

    def job_callback(self, job: Job, update_model: bool = True) -> None:
        """Result ingestion: log -> iteration bookkeeping -> model update ->
        stage advancement -> wake the run loop (reference §3.3).

        ``update_model=False`` records the observation but defers the model
        refit (burst deliveries from batched executors: N results of one
        wave arrive before any proposal can happen, so N-1 eager refits
        would be computed and immediately discarded). The host-pool tier
        always passes True — its trickle semantics are pinned by
        ``tests/test_trickle.py``.
        """
        # result ingestion is the one point every execution tier funnels
        # through, so job_finished/job_failed (with monotonic queue/run
        # durations) are emitted here — before the lock: sinks do I/O.
        # Emitted under the job's own trace (not the ambient one): batched
        # tiers deliver many jobs from one thread, and each event must
        # carry its own job's trace_id.
        loss = job.loss
        run_s = job.mono_duration("started", "finished")
        with obs.use_tenant(self.tenant_id), obs.use_trace(
            getattr(job, "trace", None)
        ):
            obs.emit(
                obs.JOB_FAILED if job.exception is not None else obs.JOB_FINISHED,
                config_id=list(job.id),
                budget=job.kwargs.get("budget"),
                worker=job.worker_name,
                queue_s=job.mono_duration("submitted", "started"),
                run_s=run_s,
                # non-finite (crashed NaN / diverged inf) journals as null
                # — json.dumps would write bare NaN/Infinity, which strict
                # JSON readers reject; the event name keeps the crashed vs
                # finished distinction
                loss=loss if math.isfinite(loss) else None,
            )
        if isinstance(run_s, (int, float)):
            # feeds the obs_snapshot `latency` section: evaluation-time
            # quantiles visible over RPC with no journal on disk
            obs.get_metrics().histogram("master.job_run_s").observe(run_s)
            # ... and the budget-keyed twin: the per-budget evaluation
            # cost aggregate multi-objective promotion ranks by
            # (obs.budget_cost_from_obs — the obs-histogram cost feed,
            # promote/pareto.py) instead of each job's noisy wall span.
            # Budgets are a short ladder, so the family count is bounded;
            # export renders them as one labeled family.
            budget = job.kwargs.get("budget")
            if isinstance(budget, (int, float)):
                obs.get_metrics().histogram(
                    f"master.job_run_s.b{float(budget):g}"
                ).observe(run_s)
        # the tenant wrap covers the bracket bookkeeping too: promotion /
        # audit events emitted by process_results() carry the stamp; the
        # run wrap scopes the straggler-ledger drain (obs/audit.py) to
        # THIS sweep — config-id triples restart every run, so an
        # unscoped drain could absorb a finished sweep's markers
        with obs.use_tenant(self.tenant_id), obs.use_run(
            self.run_id
        ), self.thread_cond:
            self.num_running_jobs -= 1
            if self._wal is not None:
                # write-ahead: on disk before any in-memory consumption,
                # so a crash after this line re-joins the result from the
                # WAL instead of re-running the evaluation
                from hpbandster_tpu.core.recovery import idempotency_key

                budget = job.kwargs.get("budget", 0.0)
                self._wal.append(
                    getattr(job, "idem_key", None)
                    or idempotency_key(job.id, budget),
                    job.id, budget, job.result, job.exception,
                    job.timestamps,
                )
            if self.result_logger is not None:
                self.result_logger(job)
            self.iterations[job.id[0]].register_result(job)
            self.config_generator.new_result(job, update_model=update_model)
            self.iterations[job.id[0]].process_results()
            if self.num_running_jobs <= self.job_queue_sizes[0]:
                self.thread_cond.notify_all()
            if (
                self.checkpoint_path is not None
                and time.monotonic() - self._last_checkpoint_mono
                > self.checkpoint_interval
            ):
                self.save_checkpoint(self.checkpoint_path)

    def _submit_job(self, config_id: ConfigId, config: Dict[str, Any], budget: float) -> None:
        job = Job(
            config_id,
            config=config,
            budget=budget,
            working_directory=self.working_directory,
        )
        # bracket shape piggybacks on the job so batched executors can fuse
        # an entire bracket into one device computation (ops/fused.py)
        it = self.iterations[config_id[0]]
        job.bracket_info = {
            "num_configs": tuple(it.num_configs),
            "budgets": tuple(it.budgets),
            "stage": it.stage,
        }
        # mint the job's trace identity here — the one id that survives the
        # master -> dispatcher -> worker -> result round-trip (obs/trace.py)
        job.trace = obs.new_trace(self.run_id)
        job.tenant_id = self.tenant_id
        # exactly-once identity (core/recovery.py): requeues, late dead
        # letters, and delivery retries all resolve to this one key
        from hpbandster_tpu.core.recovery import idempotency_key

        job.idem_key = idempotency_key(config_id, budget)
        job.time_it("submitted")
        with obs.use_tenant(self.tenant_id), obs.use_trace(job.trace):
            obs.emit(obs.JOB_SUBMITTED, config_id=list(config_id), budget=budget)
            with self.thread_cond:
                self.num_running_jobs += 1
                self.jobs.append(job)
            # submit under the tenant too: an RPC-backed executor ships
            # the tenant in the _obs envelope of the dispatch itself
            self.executor.submit_job(job)

    def active_iterations(self) -> List[int]:
        return [i for i, it in enumerate(self.iterations) if not it.is_finished]

    def best_loss_at(self, budget: float) -> Optional[float]:
        """Best (lowest) recorded loss at ``budget`` across every bracket
        so far, or None — the sweep-wide incumbent reader promotion rules
        use as their early-stopping cut (promote/earlystop.py). Callers
        run inside the result-ingestion path, which already holds the
        master lock; the read is plain dict traversal either way."""
        best: Optional[float] = None
        for it in self.iterations:
            for d in it.data.values():
                v = d.results.get(budget)
                if v is not None and (best is None or v < best):
                    best = float(v)
        return best

    def wait_for_workers(self, min_n_workers: int) -> None:
        while self.executor.number_of_workers() < min_n_workers:
            self.logger.debug(
                "waiting for workers: %d/%d",
                self.executor.number_of_workers(), min_n_workers,
            )
            time.sleep(0.05)

    # ------------------------------------------------------------------- run
    def run(
        self,
        n_iterations: int = 1,
        min_n_workers: int = 1,
        iteration_kwargs: Optional[Dict[str, Any]] = None,
    ) -> Result:
        """Drive ``n_iterations`` brackets to completion and return a Result."""
        iteration_kwargs = dict(iteration_kwargs or {})
        self.wait_for_workers(min_n_workers)
        self.adjust_queue_size()

        if self.time_ref is None:
            self.time_ref = time.time()
            self.config["time_ref"] = self.time_ref
        iteration_kwargs.setdefault("result_logger", self.result_logger)
        if getattr(self.executor, "prefers_batched_sampling", False) and hasattr(
            self.config_generator, "get_config_batch"
        ):
            iteration_kwargs.setdefault(
                "config_sampler_batch", self.config_generator.get_config_batch
            )

        # resumed masters already hold restored iterations: n_iterations is
        # the TOTAL bracket count, matching the semantics of re-running the
        # original call after a crash
        n_remaining = max(n_iterations - len(self.iterations), 0)

        # schedule announcement seam (ops/buckets.py): optimizers that can
        # compute their bracket shapes ahead of time (iteration_plan) hand
        # the remaining schedule to executors that can precompile for it
        # (prepare_schedule) — the batched executor buckets the shapes and
        # AOT-compiles the bucket programs in the background, overlapped
        # with the stage-0 sampling this loop is about to start. Purely an
        # optimization: any failure here degrades to per-shape compiles.
        plan_of = getattr(self, "iteration_plan", None)
        prepare = getattr(self.executor, "prepare_schedule", None)
        if callable(plan_of) and callable(prepare) and n_remaining > 0:
            try:
                prepare([
                    plan_of(i)
                    for i in range(
                        len(self.iterations),
                        len(self.iterations) + n_remaining,
                    )
                ])
            except Exception:
                self.logger.exception(
                    "executor schedule preparation failed; continuing "
                    "with per-shape compilation"
                )
        # the whole drive loop runs under the tenant identity: fresh
        # samples (config_sampled via get_next_run -> add_configuration)
        # and bracket_created audit records carry the stamp. use_tenant of
        # None is a passthrough, so the single-tenant path is unchanged.
        with obs.use_tenant(self.tenant_id):
            return self._run_loop(n_remaining, iteration_kwargs)

    def _run_loop(
        self, n_remaining: int, iteration_kwargs: Dict[str, Any]
    ) -> Result:
        while True:
            with self.thread_cond:
                # respect the in-flight window (async executors)
                while self.num_running_jobs > self.job_queue_sizes[1]:
                    self.thread_cond.wait(0.5)

                next_run = None
                for i in self.active_iterations():
                    next_run = self.iterations[i].get_next_run()
                    if next_run is not None:
                        break

                if next_run is not None:
                    self.logger.debug("submitting job %s", next_run[0])
                    self._submit_job(*next_run)
                    continue

                if (
                    n_remaining > 0
                    and len(self.active_iterations()) < self.parallel_brackets
                ):
                    self.iterations.append(
                        self.get_next_iteration(len(self.iterations), iteration_kwargs)
                    )
                    n_remaining -= 1
                    continue

                # nothing ready: let batched executors evaluate their buffer
                # (fires job_callback synchronously under this RLock) before
                # any new bracket samples — so fresh proposals see the
                # latest model state
                if hasattr(self.executor, "flush") and self.executor.flush():
                    continue

                if n_remaining > 0:
                    self.iterations.append(
                        self.get_next_iteration(len(self.iterations), iteration_kwargs)
                    )
                    n_remaining -= 1
                    continue

                if not self.active_iterations() and self.num_running_jobs == 0:
                    break

                self.thread_cond.wait(0.5)

        return Result(
            [i for i in self.iterations] + self.warmstart_iteration, self.config
        )

    def shutdown(self, shutdown_workers: bool = False) -> None:
        self.logger.debug("master shutdown (workers=%s)", shutdown_workers)
        if self.fleet_collector is not None:
            self.fleet_collector.stop()
            self.fleet_collector = None
        if self.health_server is not None:
            self.health_server.shutdown()
            self.health_server = None
        if self._wal is not None:
            self._wal.close()
        self.executor.shutdown(shutdown_workers)

    # ------------------------------------------------------------ checkpoint
    def save_checkpoint(self, path: str) -> None:
        """Snapshot full optimizer state (brackets + model) to ``path``."""
        from hpbandster_tpu.core.checkpoint import save_checkpoint

        t0 = time.monotonic()
        # the lock covers snapshot AND WAL truncation: a result ingested
        # between the two would be in neither the checkpoint nor the WAL
        # (thread_cond is re-entrant — the auto-checkpoint path already
        # holds it)
        with self.thread_cond:
            save_checkpoint(self, path)
            if self._wal is not None:
                self._wal.truncate()
        self._last_checkpoint_mono = time.monotonic()
        obs.emit(
            obs.CHECKPOINT_WRITTEN,
            path=path, duration_s=round(time.monotonic() - t0, 6),
        )
        self.logger.debug("checkpoint written to %s", path)

    def load_checkpoint(self, path: str) -> None:
        """Restore state saved by :meth:`save_checkpoint` into this (fresh)
        optimizer; a subsequent ``run(n_iterations=<same total>)`` resumes
        mid-bracket."""
        from hpbandster_tpu.core.checkpoint import load_checkpoint

        load_checkpoint(self, path)

    def resume(
        self, checkpoint_path: str, wal_path: Optional[str] = None
    ) -> Dict[str, int]:
        """Crash-restart: restore ``checkpoint_path``, then replay the
        write-ahead result journal tail so results that arrived after the
        last checkpoint join back without re-running (core/recovery.py).
        Returns the replay stats; ``run(n_iterations=<same total>)``
        then re-dispatches only unfinished configs."""
        from hpbandster_tpu.core.recovery import resume_master

        return resume_master(self, checkpoint_path, wal_path)
