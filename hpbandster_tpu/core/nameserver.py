"""NameServer — service discovery for the elastic host worker pool.

API-compatible with the reference's Pyro4-nameserver wrapper
(``core/nameserver.py``, SURVEY.md §2): ``start() -> (host, port)``,
``shutdown()``, optional credential file in a shared working directory so
cluster workers can bootstrap (same ``HPB_run_<id>_pyro.pkl`` filename
convention). Internally it is a tiny TCP registry (see parallel/rpc.py)
instead of a Pyro4 daemon.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from typing import Dict, Optional, Tuple

from hpbandster_tpu.parallel.rpc import RPCServer
from hpbandster_tpu.utils.network import nic_name_to_host

__all__ = ["NameServer"]


class NameServer:
    def __init__(
        self,
        run_id: str,
        working_directory: Optional[str] = None,
        host: Optional[str] = None,
        port: int = 0,
        nic_name: Optional[str] = None,
        logger: Optional[logging.Logger] = None,
    ):
        self.run_id = run_id
        self.working_directory = working_directory
        self.host = host if host is not None else nic_name_to_host(nic_name)
        self.port = port
        self.logger = logger or logging.getLogger("hpbandster_tpu.nameserver")

        self._registry: Dict[str, Tuple[str, float]] = {}  # name -> (uri, t_reg)
        self._lock = threading.Lock()
        self._server: Optional[RPCServer] = None
        self.conf_fn: Optional[str] = None

    # ------------------------------------------------------------ rpc methods
    def _register(self, name: str, uri: str) -> bool:
        with self._lock:
            self._registry[name] = (uri, time.time())
        self.logger.debug("registered %s -> %s", name, uri)
        return True

    def _unregister(self, name: str) -> bool:
        with self._lock:
            return self._registry.pop(name, None) is not None

    def _list(self, prefix: str = "") -> Dict[str, str]:
        with self._lock:
            return {
                name: uri
                for name, (uri, _) in self._registry.items()
                if name.startswith(prefix)
            }

    def _ping(self) -> str:
        return "pong"

    # -------------------------------------------------------------- lifecycle
    def start(self) -> Tuple[str, int]:
        """Start serving; optionally drop a credentials file for cluster use."""
        if self._server is not None:
            return (self.host, self.port)
        self._server = RPCServer(self.host, self.port)
        self._server.register("register", self._register)
        self._server.register("unregister", self._unregister)
        self._server.register("list", self._list)
        self._server.register("ping", self._ping)
        self._server.start()
        self.host, self.port = self._server.host, self._server.port

        if self.working_directory is not None:
            os.makedirs(self.working_directory, exist_ok=True)
            # keep the reference's filename so cluster scripts carry over
            self.conf_fn = os.path.join(
                self.working_directory, f"HPB_run_{self.run_id}_pyro.pkl"
            )
            with open(self.conf_fn, "wb") as fh:
                pickle.dump((self.host, self.port), fh)
        self.logger.info("nameserver running at %s:%d", self.host, self.port)
        return (self.host, self.port)

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self.conf_fn is not None and os.path.exists(self.conf_fn):
            os.remove(self.conf_fn)
            self.conf_fn = None
