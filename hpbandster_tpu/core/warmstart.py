"""Warm-start support: replay a previous Result into a fresh optimizer.

Reference behavior (SURVEY.md §5 "Checkpoint / resume"): passing
``previous_result=`` to an optimizer replays every logged (config, budget,
loss) into the config generator so the KDE model resumes from old data; the
replayed data is carried along into the final Result under fresh negative
iteration indices so ids never collide with live brackets.
"""

from __future__ import annotations

from typing import Any, Dict

from hpbandster_tpu.core.iteration import Datum, Status
from hpbandster_tpu.core.job import Job

__all__ = ["WarmStartIteration"]


class WarmStartIteration:
    """A finished pseudo-iteration wrapping a previous run's data."""

    is_finished = True

    def __init__(self, result, config_generator):
        self.data: Dict[Any, Datum] = {}
        id2conf = result.get_id2config_mapping()
        # re-key EVERY old iteration (live >= 0 AND previously-warmed < 0)
        # onto fresh negative indices, descending by old index — chained warm
        # starts then can never collide with the new run's live brackets
        # (-1 - old would map an old -1 back to 0, shadowing live data)
        remap = {
            old: -1 - rank
            for rank, old in enumerate(
                sorted({cid[0] for cid in id2conf}, reverse=True)
            )
        }
        for old_id, conf in id2conf.items():
            runs = result.get_runs_by_id(old_id)
            if not runs:
                continue
            new_id = (remap[old_id[0]], old_id[1], old_id[2])
            datum = Datum(
                config=conf["config"],
                config_info=conf["config_info"],
                status=Status.COMPLETED,
            )
            for r in runs:
                datum.results[r.budget] = r.loss
                datum.time_stamps[r.budget] = r.time_stamps
                datum.exceptions[r.budget] = r.error_logs
                datum.budget = r.budget

                job = Job(new_id, config=conf["config"], budget=r.budget)
                job.result = None if r.loss is None else {"loss": r.loss, "info": r.info}
                job.exception = r.error_logs
                config_generator.new_result(job, update_model=(r is runs[-1]))
            self.data[new_id] = datum

    # the Master only ever touches finished iterations through these:
    def get_next_run(self):
        return None

    def process_results(self) -> bool:
        return False
