"""Job — one (config, budget) evaluation travelling through the system.

Mirrors the reference's ``Job`` record (SURVEY.md §2 "Dispatcher" row):
config id + kwargs, submitted/started/finished wall-clock timestamps, and a
result-or-exception outcome. The timestamp schema is preserved verbatim so
``Result`` analysis and the JSONL log format stay compatible.

Beside the verbatim wall-clock schema, ``time_it`` also records a
monotonic-clock twin (``Job.mono``) for the obs layer: durations derived
via :meth:`mono_duration` are immune to wall-clock jumps, while
``Job.timestamps`` stays byte-identical to what the reference logs.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

__all__ = ["Job"]

ConfigId = Tuple[int, int, int]


class Job:
    def __init__(self, id: ConfigId, **kwargs: Any):
        self.id: ConfigId = tuple(id)  # type: ignore[assignment]
        self.kwargs: Dict[str, Any] = kwargs
        self.timestamps: Dict[str, float] = {}
        #: monotonic twins of ``timestamps`` (obs spans; never serialized)
        self.mono: Dict[str, float] = {}
        self.result: Optional[Dict[str, Any]] = None
        self.exception: Optional[str] = None
        self.worker_name: Optional[str] = None
        #: obs trace identity (hpbandster_tpu.obs.trace.TraceContext) minted
        #: by the master at submit time; survives requeues, so one trace_id
        #: tells a job's whole story including redispatch. Never serialized
        #: into ``timestamps``/result schema.
        self.trace: Optional[Any] = None
        #: exactly-once identity (core/recovery.py idempotency_key) minted
        #: beside the trace: stable across requeues and redispatches, so
        #: every copy of this job's result resolves to one key
        self.idem_key: Optional[str] = None
        #: elastic-recovery bookkeeping (parallel/dispatcher.py): how many
        #: times this job was orphaned by a dying worker and requeued, and
        #: the earliest monotonic instant it may redispatch (capped
        #: exponential backoff — a crashing config must not hot-loop
        #: through the surviving pool)
        self.requeue_count: int = 0
        self.not_before_mono: float = 0.0

    def time_it(self, which_time: str) -> "Job":
        """Record a wall-clock timestamp ('submitted' | 'started' | 'finished')."""
        self.timestamps[which_time] = time.time()
        self.mono[which_time] = time.monotonic()
        return self

    def mono_duration(self, start: str, end: str) -> Optional[float]:
        """Monotonic seconds between two recorded stamps, or None if either
        is missing (e.g. a requeued job re-records 'started')."""
        try:
            return self.mono[end] - self.mono[start]
        except KeyError:
            return None

    @property
    def loss(self) -> float:
        """The scalar loss, or NaN for crashed/invalid results."""
        if self.result is None:
            return float("nan")
        try:
            return float(self.result["loss"])
        except (KeyError, TypeError, ValueError):
            return float("nan")

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Job(id={self.id}, budget={self.kwargs.get('budget')}, "
            f"result={self.result!r}, exception={self.exception!r})"
        )
