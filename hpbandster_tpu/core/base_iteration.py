"""Alias module: the reference exposes these classes at
``core/base_iteration.py`` (SURVEY.md §1 layer map); kept here so migrating
imports work unchanged."""

from hpbandster_tpu.core.iteration import BaseIteration, Datum, Status  # noqa: F401
