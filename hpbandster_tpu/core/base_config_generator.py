"""Alias module: the reference exposes the generator interface at
``core/base_config_generator.py`` (SURVEY.md §1 layer map); kept here so
migrating imports work unchanged."""

from hpbandster_tpu.models.base import base_config_generator  # noqa: F401
