"""hpbandster_tpu — a TPU-native hyperparameter-optimization framework.

Re-implements the full capability surface of HpBandSter (HyperBand + BOHB +
RandomSearch over an elastic master/worker pool; see SURVEY.md) with a
TPU-first architecture:

* the successive-halving bracket math and the BOHB KDE model are pure,
  jittable functions over arrays (``hpbandster_tpu.ops``),
* config evaluation can run as one large batched/sharded computation on a
  ``jax.sharding.Mesh`` (``hpbandster_tpu.parallel.VmapBackend``) instead of
  one config per RPC round-trip,
* the reference's asynchronous master/worker protocol is preserved as the
  host (DCN) tier — a Pyro4-free TCP nameserver/dispatcher/worker stack —
  so heterogeneous external (non-JAX) workers still interoperate.

Reference behavior parity is documented per-module against SURVEY.md
(the upstream mount was empty; see the provenance warning there).
"""

__version__ = "0.1.0"

# Lazy top-level re-exports: keep `import hpbandster_tpu.space` cheap (no JAX
# import) while still offering the reference-style flat API
# (`hpbandster_tpu.BOHB`, `.Worker`, `.NameServer`, ...).
_EXPORTS = {
    "Result": "hpbandster_tpu.core.result",
    "Run": "hpbandster_tpu.core.result",
    "json_result_logger": "hpbandster_tpu.core.result",
    "logged_results_to_HBS_result": "hpbandster_tpu.core.result",
    "Worker": "hpbandster_tpu.core.worker",
    "NameServer": "hpbandster_tpu.core.nameserver",
    "TPUBatchedWorker": "hpbandster_tpu.parallel.batched_worker",
    "RPCBatchBackend": "hpbandster_tpu.parallel.batched_worker",
    "JaxSuccessiveHalving": "hpbandster_tpu.core.successive_halving",
    "BOHB": "hpbandster_tpu.optimizers",
    "HyperBand": "hpbandster_tpu.optimizers",
    "RandomSearch": "hpbandster_tpu.optimizers",
    "FusedBOHB": "hpbandster_tpu.optimizers",
    "FusedHyperBand": "hpbandster_tpu.optimizers",
    "FusedRandomSearch": "hpbandster_tpu.optimizers",
    "FusedH2BO": "hpbandster_tpu.optimizers",
}


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(_EXPORTS[name])
        value = getattr(mod, name)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
