"""SLO alert lifecycle over the event bus: pending -> firing -> resolved.

:class:`AlertManager` is an ordinary bus sink (subscribe it, or let
``obs.configure(slo=True)`` do it) wrapping the pure
:class:`hpbandster_tpu.obs.slo.SLOEvaluator`: every record feeds the
burn-rate windows, and each (spec, severity) pair runs a small state
machine with hysteresis —

* **ok -> pending** when the burn condition breaches and the spec
  declares a ``for_s`` hold (breaches shorter than the hold resolve
  silently back to ok: no journal noise for a single hot window);
* **pending -> firing** once the breach has held ``for_s`` (specs with
  ``for_s=0`` skip pending and fire immediately);
* **firing -> resolved** only after the condition has stayed clear for
  ``clear_for_s`` — a flapping signal that re-breaches inside the hold
  resets the clear timer and yields ONE firing -> resolved cycle, not a
  page storm. Re-breaches while firing are deduped by ``key``
  (``<slo>:<severity>``), the same suppression idea as the anomaly
  detector's per-(rule, subject) cooldown but stateful: an alert that
  never resolves never re-fires.

Each transition is appended to :attr:`AlertManager.transitions` (a
record dict stamped with the *triggering record's* time, never a clock)
and — live only — journaled as one ``slo_alert`` event, counted on
``alert.transitions*``, and reflected into the
``slo.<name>.{burn_rate,budget_remaining,state}`` / ``alert.firing``
gauges the collector and exporter read. Offline (``bus=None``,
:func:`scan_slo_records`) the same code path collects transitions and
:meth:`AlertManager.published` values without emitting or counting,
which is what makes ``obs slo --journal`` replay a journaled run
**byte-identically**: live manager and offline scan are the same object
fed the same records.

The manager never raises into the bus, never reacts to its own
``slo_alert`` events (or the anomaly detector's ``alert``s — alerting
on alerts is a feedback loop), and holds one internal RLock (re-entrant
because emitting a transition re-enters the sink via the bus before the
name guard can skip it).
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Deque, Dict, List, Optional, Sequence

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.journal import event_to_record
from hpbandster_tpu.obs.metrics import MetricsRegistry, get_metrics
from hpbandster_tpu.obs.slo import SLOEvaluator, SLOSpec, default_slo_pack

__all__ = ["AlertManager", "scan_slo_records", "STATE_CODES"]

#: the ``slo.<name>.state`` gauge encoding (max over the spec's
#: severities): the collector's fleet rollup and ``watch`` rows decode
#: it with the same table
STATE_CODES = {"ok": 0, "pending": 1, "firing": 2}


class AlertManager:
    """Bus sink owning SLO evaluation + alert lifecycle.

    ``bus=None`` (offline mode) collects transitions and published
    values without emitting or counting; with a bus, every transition
    emits one ``slo_alert`` event, increments ``alert.transitions`` plus
    ``alert.transitions.<slo>``, and refreshes the SLO gauges.
    """

    def __init__(
        self,
        specs: Optional[Sequence[SLOSpec]] = None,
        bus: Optional[Any] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        self._eval = SLOEvaluator(
            list(specs) if specs is not None else default_slo_pack()
        )
        self._bus = bus
        self._registry = registry
        self._lock = threading.RLock()
        #: every lifecycle transition (record dicts, oldest first),
        #: bounded so a pathological run cannot grow it without limit
        self.transitions: Deque[Dict[str, Any]] = collections.deque(
            maxlen=256
        )
        self.transition_counts: Dict[str, int] = {}
        # (slo, severity) -> {"state", "since", "clear_start"}
        self._life: Dict[Any, Dict[str, Any]] = {}
        self._firing: set = set()
        #: per-spec last gauge values (live == what the registry holds;
        #: offline == what it WOULD hold) — the replay-parity surface
        self._last_published: Dict[str, Dict[str, Any]] = {}

    @property
    def specs(self) -> List[SLOSpec]:
        return self._eval.specs

    # ------------------------------------------------------------- plumbing
    def __call__(self, event: Any) -> None:
        """Bus-sink entry point; must never raise into the bus."""
        try:
            self.process(event_to_record(event))
        except Exception:
            E.logger.exception("alert manager failed on %r", event)

    def process(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Feed one journal-schema record; returns the transitions it
        caused (already emitted/counted when a bus is attached)."""
        name = rec.get("event")
        if not name or name in (E.SLO_ALERT, E.ALERT):
            return []
        with self._lock:
            return self._process_locked(rec)

    def _process_locked(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        measured = self._eval.update(rec)
        if not measured:
            return out
        now = self._eval.last_t or 0.0
        for meas in measured:
            spec = self._eval.states[meas["slo"]].spec
            out.extend(self._lifecycle(spec, meas, rec, now))
            self._publish(spec, meas)
        return out

    # ------------------------------------------------------------ lifecycle
    def _lifecycle(
        self,
        spec: SLOSpec,
        meas: Dict[str, Any],
        rec: Dict[str, Any],
        now: float,
    ) -> List[Dict[str, Any]]:
        fired: List[Dict[str, Any]] = []
        for w in spec.windows:
            sev = w.severity
            key = (spec.name, sev)
            life = self._life.setdefault(
                key, {"state": "ok", "since": now, "clear_start": None}
            )
            breached = meas["severities"][sev]["breached"]
            if breached:
                # any re-breach resets the resolve hold: flapping inside
                # clear_for_s stays ONE firing episode
                life["clear_start"] = None
                if life["state"] == "ok":
                    nxt = "pending" if spec.for_s > 0 else "firing"
                    life["state"], life["since"] = nxt, now
                    fired.append(
                        self._transition(spec, sev, nxt, meas, rec, now)
                    )
                elif (
                    life["state"] == "pending"
                    and now - life["since"] >= spec.for_s
                ):
                    life["state"], life["since"] = "firing", now
                    fired.append(
                        self._transition(spec, sev, "firing", meas, rec, now)
                    )
            else:
                if life["state"] == "pending":
                    # never fired: drop back silently (no transition —
                    # pending exists exactly to absorb this)
                    life["state"], life["since"] = "ok", now
                elif life["state"] == "firing":
                    if life["clear_start"] is None:
                        life["clear_start"] = now
                    elif now - life["clear_start"] >= spec.clear_for_s:
                        life["state"], life["since"] = "ok", now
                        life["clear_start"] = None
                        fired.append(
                            self._transition(
                                spec, sev, "resolved", meas, rec, now
                            )
                        )
            if life["state"] == "firing":
                self._firing.add(key)
            else:
                self._firing.discard(key)
        return fired

    def _transition(
        self,
        spec: SLOSpec,
        severity: str,
        state: str,
        meas: Dict[str, Any],
        rec: Dict[str, Any],
        now: float,
    ) -> Dict[str, Any]:
        info = meas["severities"][severity]
        dedup = f"{spec.name}:{severity}"
        tr = {
            "event": E.SLO_ALERT,
            # the triggering record's time, not a clock: offline replay
            # of the same journal rebuilds this dict byte-identically
            "t_wall": now,
            "t_mono": rec.get("t_mono"),
            "slo": spec.name,
            "severity": severity,
            "state": state,
            "burn_short": info["burn_short"],
            "burn_long": info["burn_long"],
            "budget_remaining": meas["budget_remaining"],
            "key": dedup,
        }
        self.transitions.append(tr)
        self.transition_counts[spec.name] = (
            self.transition_counts.get(spec.name, 0) + 1
        )
        if self._bus is not None:
            reg = (
                self._registry if self._registry is not None else get_metrics()
            )
            reg.counter("alert.transitions").inc()
            reg.counter(f"alert.transitions.{spec.name}").inc()
            # reserved envelope fields (t_wall/t_mono/...) stay OFF the
            # emit — the bus stamps its own; the transition dict above is
            # the journaled-record-shaped twin
            self._bus.emit(
                E.SLO_ALERT,
                slo=spec.name,
                severity=severity,
                state=state,
                burn_short=info["burn_short"],
                burn_long=info["burn_long"],
                budget_remaining=meas["budget_remaining"],
                key=dedup,
            )
        return tr

    # ------------------------------------------------------------- publish
    def _state_code(self, name: str) -> int:
        code = 0
        for (slo, _sev), life in self._life.items():
            if slo == name:
                code = max(code, STATE_CODES.get(life["state"], 0))
        return code

    def _publish(self, spec: SLOSpec, meas: Dict[str, Any]) -> None:
        pub = {
            "burn_rate": meas["burn_rate"],
            "budget_remaining": meas["budget_remaining"],
            "state": self._state_code(spec.name),
        }
        self._last_published[spec.name] = pub
        if self._bus is None:
            return
        reg = self._registry if self._registry is not None else get_metrics()
        name = spec.name
        if pub["burn_rate"] is not None:
            reg.gauge(f"slo.{name}.burn_rate").set(float(pub["burn_rate"]))
        reg.gauge(f"slo.{name}.budget_remaining").set(
            float(pub["budget_remaining"])
        )
        reg.gauge(f"slo.{name}.state").set(float(pub["state"]))
        reg.gauge("alert.firing").set(float(len(self._firing)))

    def published(self) -> Dict[str, Dict[str, Any]]:
        """Last per-spec gauge values — compare a live manager's against
        an offline scan's for replay parity."""
        with self._lock:
            return {k: dict(v) for k, v in self._last_published.items()}

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable manager state for the health endpoint."""
        with self._lock:
            rates = [
                p["burn_rate"]
                for p in self._last_published.values()
                if p["burn_rate"] is not None
            ]
            return {
                "specs": [s.name for s in self._eval.specs],
                "firing": len(self._firing),
                "worst_burn_rate": max(rates) if rates else None,
                "by_slo": {
                    k: dict(v)
                    for k, v in sorted(self._last_published.items())
                },
                "recent": list(self.transitions)[-8:],
            }


def scan_slo_records(
    records: Sequence[Dict[str, Any]],
    specs: Optional[Sequence[SLOSpec]] = None,
) -> AlertManager:
    """Offline, deterministic replay of the SLO pack over journal records.

    No bus, no metrics, no wall clock — returns the fed manager so the
    caller can read :attr:`AlertManager.transitions` AND
    :meth:`AlertManager.published` (both halves of the live==offline
    parity check ``obs slo`` performs). ``slo_alert``/``alert`` records
    already in the journal are skipped by :meth:`AlertManager.process`,
    so replaying a live-journaled run does not double-feed its own
    output.
    """
    mgr = AlertManager(specs=specs, bus=None)
    for rec in records:
        mgr.process(rec)
    return mgr
