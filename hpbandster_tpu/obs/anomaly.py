"""Streaming anomaly detection over the event bus.

A :class:`AnomalyDetector` is an ordinary bus sink: subscribe it (or let
``obs.configure(anomaly=True)`` do it) and it watches the run's event
stream for the failure shapes an HPO fleet actually exhibits, emitting
``alert`` events (plus ``anomaly.alerts*`` counters) the moment a rule
fires — surfaced live by ``watch``, by the ``obs_snapshot`` health RPC,
and post-hoc by the report CLI's alert digest.

Rules (every threshold is a knob on :class:`AnomalyRules`, see
docs/observability.md "Alert rules"):

* **straggler** — a duration-carrying event (``run_s``, ``compute_s``,
  any span's ``duration_s``) exceeding ``straggler_factor`` × the rolling
  per-stage p95. Catches the one worker quietly 10× slower than its
  peers, which percentile summaries alone hide until the journal is read.
* **worker_flapping** — the same worker dropped ``flap_threshold`` times
  within ``flap_window_s``: a host that keeps rejoining and dying wastes
  requeues and poisons utilization; dropping it once is routine, cycling
  is an incident.
* **nan_burst** — ``nan_burst_threshold`` non-finite-loss / failed
  evaluations within the last ``nan_burst_window`` results. One diverged
  config is BOHB-normal (crashed-as-worst); a burst means the objective
  or a budget rung is broken. The rule has TWO feeds: host job events
  (the per-result window above), and the device crash counters a
  ``device_telemetry`` record carries (``obs/device_metrics.py``) — a
  fused/resident sweep journals no per-job events, so its crashes fire
  the rule through the decoded counters instead: ``crashes >=
  nan_burst_threshold`` AND crash rate >= ``nan_burst_device_rate``
  (an absolute count alone would false-positive at 100k configs).
* **bracket_skew** — a ``device_telemetry`` record whose crashed
  evaluations concentrate in a few brackets: the max per-bracket crash
  count is at least ``bracket_skew_min_crashes`` and its skew over the
  median ((max - median) / max) reaches ``bracket_skew``. Spread-out
  crashes are the objective's problem (nan_burst's beat); one straggling
  bracket means a specific budget rung or rotation slot is broken.
* **kde_refit_stall** — ``kde_stall_results`` results ingested since the
  last ``kde_refit`` while a model exists: the optimizer has silently
  degraded to random search (e.g. every new result lands on a budget
  whose fit keeps failing the min-points gate).
* **fleet_imbalance** — the fleet collector's ``fleet_sample`` records
  report ``device_mem_skew`` at or above ``imbalance_skew`` for
  ``imbalance_consecutive`` consecutive samples: one device is carrying
  the memory the mesh sharding was supposed to spread, and a single hot
  sample is a transient while a sustained streak is a placement bug.
* **worker_churn** — a ``fleet_sample``'s ``worker_churn_per_min``
  (worker drops + endpoint losses, windowed by the collector) at or
  above ``churn_per_min``: distinct from ``worker_flapping`` (ONE host
  cycling), this is the fleet-wide rate that says rungs are being
  rebalanced faster than they can drain.
* **recompile_storm** — one function's ``xla_compile`` events
  (``obs/runtime.py``'s ``tracked_jit``) arriving
  ``recompile_threshold`` times within ``recompile_window_s``. A compile
  per fresh bracket shape is BOHB-normal — the default threshold clears
  a healthy sweep's legitimate compile set (one per bracket shape, one
  per batch-pad size); the same function compiling past it means shapes
  are churning (a jit constructed in a loop, an unpadded batch) and XLA
  is eating the wall-clock the fused paths were supposed to save.
  Subjects key per fn (``tracked_jit`` events carry no budget); a
  foreign journal whose ``xla_compile`` records DO carry a ``budget``
  field gets per-(fn, budget) windows like the straggler rule.

The detector never raises into the bus (rule state is all stdlib), never
reacts to its own ``alert`` events, and rate-limits per (rule, subject)
via ``cooldown_s`` so one stuck worker cannot flood the journal.

Offline, :func:`scan_records` replays the same rules deterministically
over journal records — timestamps come from the records, not the wall
clock — which is how ``report`` synthesizes an alert digest for runs
that journaled without a live detector attached.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import statistics
import threading
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.journal import event_to_record
from hpbandster_tpu.obs.metrics import MetricsRegistry, get_metrics

__all__ = ["AnomalyRules", "AnomalyDetector", "scan_records"]

#: duration fields a record may carry, in stage-name terms: the master's
#: end-to-end run_s, the worker's compute_s, and any span's duration_s
#: (keyed by the span's event name)
_DURATION_FIELDS = ("run_s", "compute_s", "duration_s")


@dataclasses.dataclass(frozen=True)
class AnomalyRules:
    """Tuning knobs; defaults sized for minutes-scale HPO evaluations."""

    #: straggler: value > factor × rolling p95 of the same stage (the
    #: stage key includes the budget — multi-fidelity rungs never pool)
    straggler_factor: float = 3.0
    #: ... but only once the stage has this many samples (cold-start guard)
    straggler_min_samples: int = 20
    #: rolling window per stage (samples)
    straggler_window: int = 256
    #: p95 floor inside the threshold (factor × max(p95, floor)): a
    #: micro-duration baseline cannot flag trivial blips as "30×", while
    #: a genuinely huge outlier still fires
    straggler_floor_s: float = 0.05

    #: worker_flapping: this many drops of one worker within the window
    flap_threshold: int = 3
    flap_window_s: float = 600.0

    #: nan_burst: this many bad results within the last window results
    nan_burst_threshold: int = 5
    nan_burst_window: int = 32
    #: ... and the device-counter feed: a device_telemetry record fires
    #: nan_burst when its crashes reach the threshold AND this fraction
    #: of its evaluations (rate-gated: 5 crashes in a 100k-config sweep
    #: is healthy, 5 in 12 is not). 0 disables the device feed.
    nan_burst_device_rate: float = 0.25

    #: bracket_skew (device_telemetry records): fire when the max
    #: per-bracket crash count reaches `bracket_skew_min_crashes` and
    #: (max - median) / max over the per-bracket crash counts reaches
    #: `bracket_skew` — crashes concentrated in one bracket mean a
    #: broken budget rung, not a flaky objective. min_crashes=0 disables.
    bracket_skew: float = 0.5
    bracket_skew_min_crashes: int = 8

    #: kde_refit_stall: results since the last refit (0 disables)
    kde_stall_results: int = 64

    #: recompile_storm: this many xla_compile events for one fn subject
    #: within the window (0 disables; records carrying a budget field —
    #: foreign journals — key per (fn, budget)). The default clears a
    #: healthy sweep's LEGITIMATE compile set — one compile per bracket
    #: shape (max_SH_iter = 4 shapes at budgets 1..81) and per log2
    #: batch-pad size — while a loop-constructed wrapper blows past it
    recompile_threshold: int = 6
    recompile_window_s: float = 600.0

    #: fleet_imbalance: device_mem_skew >= this for `imbalance_consecutive`
    #: consecutive fleet_sample records (consecutive=0 disables). The
    #: default skew clears a ragged-but-working fleet (last bracket chunk
    #: pads unevenly) while a device holding ~everything fires
    imbalance_skew: float = 0.6
    imbalance_consecutive: int = 3

    #: worker_churn: fleet_sample worker_churn_per_min >= this (0
    #: disables) — drops + endpoint losses per minute, fleet-wide over
    #: the collector's fixed churn window (default 1.0 = ten churn
    #: events inside a 10-minute window)
    churn_per_min: float = 1.0

    #: per-(rule, subject) re-alert suppression
    cooldown_s: float = 60.0


class AnomalyDetector:
    """Bus sink / record processor implementing the rules above.

    ``bus=None`` (offline mode) collects alert records on ``.alerts``
    without emitting or counting; with a bus, every fired rule emits one
    ``alert`` event and increments ``anomaly.alerts`` plus
    ``anomaly.alerts.<rule>``.

    Thread-safe like every other sink (the bus delivers from whichever
    thread emitted — master, ping loop, and RPC handler threads all emit
    concurrently): rule state mutates under one internal RLock (re-entrant
    because firing an alert re-enters the sink via the bus before the
    ALERT-name guard can skip it). State is plain dicts/deques sized by
    the rule windows, so memory is bounded regardless of run length.
    """

    def __init__(
        self,
        rules: Optional[AnomalyRules] = None,
        bus: Optional[Any] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.rules = rules or AnomalyRules()
        self._bus = bus
        self._registry = registry
        self._lock = threading.RLock()
        #: every alert this detector fired (record dicts, oldest first),
        #: bounded so a pathological run cannot grow it without limit
        self.alerts: Deque[Dict[str, Any]] = collections.deque(maxlen=256)
        self.alert_counts: Dict[str, int] = {}
        # rule state
        self._stage_windows: Dict[str, Deque[float]] = {}
        self._drop_times: Dict[str, Deque[float]] = {}
        self._result_window: Deque[int] = collections.deque(
            maxlen=max(int(self.rules.nan_burst_window), 1)
        )
        self._results_since_refit = 0
        self._refit_seen = False
        self._compile_times: Dict[str, Deque[float]] = {}
        self._imbalance_streak = 0
        self._last_alert: Dict[Tuple[str, str], float] = {}

    # ------------------------------------------------------------- plumbing
    def __call__(self, event: Any) -> None:
        """Bus-sink entry point; must never raise into the bus."""
        try:
            self.process(event_to_record(event))
        except Exception:
            E.logger.exception("anomaly detector failed on %r", event)

    def _fire(
        self, rec: Dict[str, Any], rule: str, subject: str, **detail: Any
    ) -> Optional[Dict[str, Any]]:
        now = rec.get("t_wall")
        now = float(now) if isinstance(now, (int, float)) else 0.0
        key = (rule, subject)
        last = self._last_alert.get(key)
        if last is not None and now - last < self.rules.cooldown_s:
            return None
        self._last_alert[key] = now
        if (
            rule == "straggler"
            and self._bus is not None  # offline replays must not pollute
            # the process ledger with a foreign journal's config ids
            and detail.get("config_id") is not None
        ):
            # close the anomaly -> scheduler loop: the flagged config id
            # rides its rung's next promotion_decision record as
            # `straggler_observed` (obs/audit.py ledger), so replays can
            # correlate stalls with promotion timing
            from hpbandster_tpu.obs.audit import note_straggler

            note_straggler(
                detail.get("config_id"), budget=rec.get("budget")
            )
        alert = {
            "event": E.ALERT,
            "t_wall": now,
            "t_mono": rec.get("t_mono"),
            "rule": rule,
            "subject": subject,
            "source_event": rec.get("event"),
            **detail,
        }
        self.alerts.append(alert)
        self.alert_counts[rule] = self.alert_counts.get(rule, 0) + 1
        if self._bus is not None:
            reg = self._registry if self._registry is not None else get_metrics()
            reg.counter("anomaly.alerts").inc()
            reg.counter(f"anomaly.alerts.{rule}").inc()
            self._bus.emit(
                E.ALERT,
                rule=rule, subject=subject,
                source_event=rec.get("event"),
                **detail,
            )
        return alert

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable detector state for the health endpoint."""
        with self._lock:
            return {
                "total": sum(self.alert_counts.values()),
                "by_rule": dict(sorted(self.alert_counts.items())),
                "recent": list(self.alerts)[-8:],
            }

    # ----------------------------------------------------------------- rules
    def process(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Run every rule over one journal-schema record; returns the
        alerts fired (already emitted/counted when a bus is attached)."""
        name = rec.get("event")
        if not name or name == E.ALERT:
            return []
        with self._lock:
            return self._process_locked(rec, name)

    def _process_locked(
        self, rec: Dict[str, Any], name: str
    ) -> List[Dict[str, Any]]:
        fired: List[Dict[str, Any]] = []
        r = self.rules

        # --- straggler: per-stage rolling p95. The window keys include
        # the budget: a budget-9 evaluation is ~9x a budget-1 one by
        # DESIGN in a multi-fidelity sweep, and pooling them would fire
        # a false alert at every rung transition.
        for field in _DURATION_FIELDS:
            v = rec.get(field)
            if not isinstance(v, (int, float)) or not math.isfinite(v):
                continue
            budget = rec.get("budget")
            stage = f"{name}.{field}" + (
                f"@{budget:g}" if isinstance(budget, (int, float)) else ""
            )
            win = self._stage_windows.get(stage)
            if win is None:
                win = self._stage_windows[stage] = collections.deque(
                    maxlen=max(int(r.straggler_window), 2)
                )
            if len(win) >= r.straggler_min_samples:
                ordered = sorted(win)
                p95 = ordered[min(
                    int(round(0.95 * (len(ordered) - 1))), len(ordered) - 1
                )]
                # the floor enters the THRESHOLD: a baseline of micro
                # durations (p95 ~2ms) must not flag a trivial 60ms blip
                # at "30x", yet a genuinely huge outlier still fires
                cut = r.straggler_factor * max(p95, r.straggler_floor_s)
                if v > cut:
                    a = self._fire(
                        rec, "straggler", stage,
                        value_s=round(float(v), 6),
                        p95_s=round(float(p95), 6),
                        # a 0.0 baseline (sub-microsecond stage) has no
                        # meaningful ratio; the floor-based cut still fired
                        factor=round(float(v) / p95, 2) if p95 > 0 else None,
                        worker=rec.get("worker"),
                        config_id=rec.get("config_id"),
                    )
                    if a:
                        fired.append(a)
            win.append(float(v))

        # --- worker flapping: repeated drops of one worker
        if name == E.WORKER_DROPPED:
            worker = str(rec.get("worker") or "?")
            tw = rec.get("t_wall")
            tw = float(tw) if isinstance(tw, (int, float)) else 0.0
            times = self._drop_times.setdefault(
                worker, collections.deque(maxlen=max(int(r.flap_threshold), 1) * 4)
            )
            times.append(tw)
            recent = [t for t in times if tw - t <= r.flap_window_s]
            if len(recent) >= r.flap_threshold:
                a = self._fire(
                    rec, "worker_flapping", worker,
                    drops=len(recent), window_s=r.flap_window_s,
                )
                if a:
                    fired.append(a)

        # --- result-driven rules (the loss-carrying record is the master
        # funnel's / fused replay's — exactly one per job, so counting
        # those avoids double-counting the worker-side twins)
        if name in (E.JOB_FINISHED, E.JOB_FAILED) and "loss" in rec:
            loss = rec.get("loss")
            # bad = failed, OR no finite loss: the emitters journal any
            # non-finite (NaN/inf-diverged) loss as null for strict JSON,
            # so null on a loss-carrying record IS the divergence signal
            # (the isfinite check additionally covers foreign journals
            # that wrote raw non-finite values)
            bad = name == E.JOB_FAILED or loss is None or (
                isinstance(loss, (int, float)) and not math.isfinite(loss)
            )
            self._result_window.append(1 if bad else 0)
            if (
                sum(self._result_window) >= r.nan_burst_threshold
                and len(self._result_window) > 0
            ):
                a = self._fire(
                    rec, "nan_burst", "losses",
                    bad_results=sum(self._result_window),
                    window=self._result_window.maxlen,
                    config_id=rec.get("config_id"),
                )
                if a:
                    fired.append(a)
                    self._result_window.clear()
            if r.kde_stall_results > 0 and self._refit_seen:
                self._results_since_refit += 1
                if self._results_since_refit > r.kde_stall_results:
                    a = self._fire(
                        rec, "kde_refit_stall", "kde",
                        results_since_refit=self._results_since_refit,
                        stall_after=r.kde_stall_results,
                    )
                    if a:
                        fired.append(a)
                        self._results_since_refit = 0
        elif name == E.KDE_REFIT:
            self._refit_seen = True
            self._results_since_refit = 0

        # --- device-counter feeds: a fused/resident sweep journals ONE
        # device_telemetry record instead of per-job events, so the
        # result-shaped rules read its decoded crash counters directly.
        if name == E.DEVICE_TELEMETRY:
            crashes = rec.get("crashes")
            evals = rec.get("evaluations")
            if (
                r.nan_burst_device_rate > 0
                and isinstance(crashes, (int, float))
                and isinstance(evals, (int, float)) and evals > 0
                and crashes >= r.nan_burst_threshold
                and crashes / evals >= r.nan_burst_device_rate
            ):
                a = self._fire(
                    rec, "nan_burst", "device",
                    bad_results=int(crashes),
                    evaluations=int(evals),
                    crash_rate=round(float(crashes) / float(evals), 4),
                )
                if a:
                    fired.append(a)
            per_bracket = rec.get("per_bracket_crashes")
            if (
                r.bracket_skew_min_crashes > 0
                and isinstance(per_bracket, list) and len(per_bracket) >= 2
                and all(
                    isinstance(c, (int, float)) and not isinstance(c, bool)
                    for c in per_bracket
                )
            ):
                counts = [float(c) for c in per_bracket]
                hi = max(counts)
                # true median (statistics.median interpolates even
                # lengths) — the upper-middle element would understate
                # the skew for even bracket counts and silently disable
                # the rule on symmetric crash splits
                median = statistics.median(counts)
                skew = 0.0 if hi <= 0 else (hi - median) / hi
                if hi >= r.bracket_skew_min_crashes and skew >= r.bracket_skew:
                    worst = max(
                        range(len(per_bracket)),
                        key=lambda i: float(per_bracket[i]),
                    )
                    a = self._fire(
                        rec, "bracket_skew", f"bracket{worst}",
                        max_crashes=int(hi),
                        median_crashes=round(median, 1),
                        skew=round(skew, 4),
                        threshold=r.bracket_skew,
                    )
                    if a:
                        fired.append(a)

        # --- recompile storm: one function's tracked_jit boundary keeps
        # compiling. Subjects key per fn (tracked_jit events carry no
        # budget; a foreign record that does gets (fn, budget) windows
        # like the straggler rule): a bounded compile set — one per
        # bracket shape / pad size — stays under the threshold by
        # design; the SAME subject churning past it is the incident.
        if name == E.XLA_COMPILE and r.recompile_threshold > 0:
            fn = str(rec.get("fn") or "?")
            budget = rec.get("budget")
            subject = fn + (
                f"@{budget:g}" if isinstance(budget, (int, float)) else ""
            )
            tw = rec.get("t_wall")
            tw = float(tw) if isinstance(tw, (int, float)) else 0.0
            times = self._compile_times.setdefault(
                subject,
                collections.deque(maxlen=max(int(r.recompile_threshold), 1) * 4),
            )
            times.append(tw)
            recent = [t for t in times if tw - t <= r.recompile_window_s]
            if len(recent) >= r.recompile_threshold:
                a = self._fire(
                    rec, "recompile_storm", subject,
                    compiles=len(recent), window_s=r.recompile_window_s,
                    compile_s=rec.get("compile_s"),
                    signature=rec.get("signature"),
                )
                if a:
                    fired.append(a)

        # --- fleet rules: the collector's derived gauges. Live samples
        # arrive flattened on the bus event; series-file lines nest them
        # under "fleet" — both shapes are read, which is what keeps
        # scan_records over a series file in parity with the live sink.
        if name == E.FLEET_SAMPLE:
            fleet = rec.get("fleet")
            if not isinstance(fleet, dict):
                fleet = rec
            skew = fleet.get("device_mem_skew")
            if r.imbalance_consecutive > 0:
                if (
                    isinstance(skew, (int, float)) and math.isfinite(skew)
                    and skew >= r.imbalance_skew
                ):
                    self._imbalance_streak += 1
                    if self._imbalance_streak >= r.imbalance_consecutive:
                        a = self._fire(
                            rec, "fleet_imbalance", "devices",
                            skew=round(float(skew), 4),
                            threshold=r.imbalance_skew,
                            consecutive=self._imbalance_streak,
                        )
                        if a:
                            fired.append(a)
                            self._imbalance_streak = 0
                else:
                    self._imbalance_streak = 0
            churn = fleet.get("worker_churn_per_min")
            if (
                r.churn_per_min > 0
                and isinstance(churn, (int, float)) and math.isfinite(churn)
                and churn >= r.churn_per_min
            ):
                a = self._fire(
                    rec, "worker_churn", "fleet",
                    churn_per_min=round(float(churn), 4),
                    threshold=r.churn_per_min,
                    lost_endpoints=fleet.get("lost"),
                    churn_events=fleet.get("churn_events"),
                )
                if a:
                    fired.append(a)

        return fired


def scan_records(
    records: List[Dict[str, Any]],
    rules: Optional[AnomalyRules] = None,
) -> List[Dict[str, Any]]:
    """Offline, deterministic replay of the rules over journal records.

    No bus, no metrics, no wall clock — alerts are stamped with the
    triggering record's ``t_wall``/``t_mono``, so two scans of the same
    journal produce identical output (the report CLI's determinism bar).
    """
    det = AnomalyDetector(rules=rules, bus=None)
    out: List[Dict[str, Any]] = []
    for rec in records:
        out.extend(det.process(rec))
    return out
