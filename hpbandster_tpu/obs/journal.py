"""Run-journal sinks: rotating JSONL files + an in-memory ring buffer.

:class:`JsonlJournal` is the durable sink — one JSON object per line,
size-based rotation (``journal.jsonl`` -> ``journal.jsonl.1`` -> ``.2``
...), so a long sweep's telemetry is bounded on disk and the newest
events are always in the live file. :class:`RingBuffer` is the
post-mortem sink — the last N events stay in memory even when no journal
is configured, which is what the dispatcher's dead-letter path and crash
analysis read.

Rotation semantics (pinned by ``tests/test_obs.py``): a write that would
push the live file PAST ``max_bytes`` rotates first, so every rotated
file is <= ``max_bytes`` — unless a single line alone exceeds it, which
is written whole to a fresh file (a journal must never split a line).
No line is ever dropped by rotation itself; only files older than
``max_files`` rotations are deleted.

Writes are batched: encoded lines accumulate in an in-process buffer
(``buffer_bytes``, 0 = write-through) and hit the file in one
write+flush when the buffer fills, when a lifecycle-boundary record
arrives (chunk span closes, job results, alerts, fleet samples —
``_FLUSH_EVENTS``), on rotation, and on ``flush()``/``close()``. The
per-record syscall pair was the critical-path attribution floor trailing
every span (each emit paid a synchronous write+flush inside the sink);
batching amortizes it across a chunk's worth of micro-spans while the
boundary set keeps live tails (``watch``, the collector series) at most
one chunk stale and pins the records a post-mortem cannot lose. Size
accounting happens at buffer time, so rotate-before-exceed semantics are
byte-identical to the write-through path.
"""

from __future__ import annotations

import collections
import json
import math
import os
import socket
import threading
from typing import Any, Dict, List, Optional, Tuple

from hpbandster_tpu.obs.events import Event
from hpbandster_tpu.obs import events as E

__all__ = [
    "JsonlJournal", "RingBuffer", "journal_paths", "read_journal",
    "read_journal_ex", "process_identity",
]


def _jsonable(x: Any) -> Any:
    """Best-effort coercion for event fields (numpy scalars, tuples...)."""
    try:
        return float(x)
    except (TypeError, ValueError):
        return str(x)


def _definite(x: Any) -> Any:
    """Recursively replace non-finite floats with None (the slow path of
    write_record): a journal line must be STRICT JSON — bare NaN/Infinity
    (e.g. a diverged run's inf loss inside a promotion_decision's losses
    list) breaks jq/JS readers of the very post-mortem they exist for."""
    if isinstance(x, dict):
        return {k: _definite(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_definite(v) for v in x]
    if isinstance(x, float):  # np.float64 subclasses float; covered
        return x if math.isfinite(x) else None
    if x is None or isinstance(x, (str, int, bool)):
        return x
    y = _jsonable(x)
    if isinstance(y, float) and not math.isfinite(y):
        return None
    return y


def event_to_record(ev: Event) -> Dict[str, Any]:
    """The on-disk schema: event name + stamps flattened with the fields
    (field names never collide — ``event``/``t_wall``/``t_mono``, plus the
    identity/trace stamps ``host``/``pid``/``trace_id``, are reserved,
    docs/observability.md)."""
    rec = {"event": ev.name, "t_wall": ev.t_wall, "t_mono": ev.t_mono}
    rec.update(ev.fields)
    return rec


def process_identity(**extra: Any) -> Dict[str, Any]:
    """The standard per-process identity stamp for
    ``JsonlJournal(static_fields=...)``: ``{host, pid}`` plus any
    caller-specific fields (``worker_id``, ``component``, ...). Merged
    journals from many hosts stay attributable record by record."""
    ident: Dict[str, Any] = {"host": socket.gethostname(), "pid": os.getpid()}
    ident.update(extra)
    return ident


class RingBuffer:
    """Keep the newest ``capacity`` items; usable directly as a bus sink."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._items: collections.deque = collections.deque(maxlen=self.capacity)

    def __call__(self, event: Event) -> None:
        self.append(event)

    def append(self, item: Any) -> None:
        with self._lock:
            self._items.append(item)

    def snapshot(self) -> List[Any]:
        """Oldest-first copy of the current contents."""
        with self._lock:
            return list(self._items)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


#: records that drain the write buffer the moment they are journaled:
#: chunk-span closes (the sweep/serve heartbeat — keeps live tails at
#: most one chunk stale), per-job results and worker incidents (the
#: dispatcher post-mortem evidence), checkpoints, alerts, and fleet
#: samples (the collector series is tailed while live)
_FLUSH_EVENTS = frozenset({
    "sweep_chunk", "serve_chunk",
    E.JOB_FINISHED, E.JOB_FAILED,
    E.WORKER_DROPPED, E.WORKER_QUARANTINED,
    E.CHECKPOINT_WRITTEN, E.CHAOS_FAULT,
    E.ALERT, E.SLO_ALERT,
    E.FLEET_SAMPLE, E.DEVICE_TELEMETRY,
})


class JsonlJournal:
    """Rotating JSONL event sink; subscribe it to a bus, or call directly."""

    def __init__(
        self,
        path: str,
        max_bytes: int = 16 * 1024 * 1024,
        max_files: int = 3,
        static_fields: Optional[Dict[str, Any]] = None,
        buffer_bytes: int = 64 * 1024,
    ):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        self.path = path
        self.max_bytes = int(max_bytes)
        self.max_files = max(int(max_files), 1)
        #: write-buffer threshold; 0 restores write-through (one
        #: write+flush per record)
        self.buffer_bytes = max(int(buffer_bytes), 0)
        #: identity stamp merged into every record (record keys win) —
        #: see :func:`process_identity`
        self.static_fields = dict(static_fields) if static_fields else None
        self._lock = threading.Lock()
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")
        self._size = os.path.getsize(path)
        self.rotations = 0
        self._pending: List[str] = []
        self._pending_bytes = 0
        #: physical write+flush count — a batched run's flushes stay far
        #: below its record count (asserted by the timeline e2e test)
        self.flushes = 0

    # --------------------------------------------------------------- writing
    def __call__(self, event: Event) -> None:
        self.write_record(event_to_record(event))

    def write_record(self, record: Dict[str, Any]) -> None:
        if self.static_fields:
            record = dict(record)
            for k, v in self.static_fields.items():
                record.setdefault(k, v)
        try:
            line = json.dumps(record, default=_jsonable, allow_nan=False) + "\n"
        except ValueError:
            # non-finite float somewhere in the record: sanitize to null
            # (strict-JSON guarantee; the fast path above stays one dumps)
            line = json.dumps(
                _definite(record), default=_jsonable, allow_nan=False
            ) + "\n"
        data = line.encode("utf-8")
        with self._lock:
            if self._fh is None:
                return  # closed: late emits from draining threads are dropped
            # _size counts buffered bytes too, so rotate-before-exceed
            # judges exactly what WILL be in the file once flushed —
            # byte-identical to the write-through path
            if self._size > 0 and self._size + len(data) > self.max_bytes:
                self._rotate_locked()
            self._pending.append(line)
            self._pending_bytes += len(data)
            self._size += len(data)
            if (
                self._pending_bytes >= self.buffer_bytes
                or record.get("event") in _FLUSH_EVENTS
            ):
                self._flush_locked()

    def _flush_locked(self) -> None:
        # callers hold self._lock (write_record / _rotate_locked /
        # flush / close)
        if not self._pending:
            return
        self._fh.write("".join(self._pending))  # graftlint: disable=lock-coverage — caller holds self._lock
        self._fh.flush()  # graftlint: disable=lock-coverage — caller holds self._lock
        self._pending.clear()
        self._pending_bytes = 0  # graftlint: disable=lock-coverage — caller holds self._lock
        self.flushes += 1

    def flush(self) -> None:
        """Drain the write buffer to disk now."""
        with self._lock:
            if self._fh is not None:
                self._flush_locked()

    def _rotate_locked(self) -> None:
        # sole caller is write_record, inside `with self._lock:`
        self._flush_locked()  # buffered lines belong to the OLD file
        self._fh.close()  # graftlint: disable=lock-coverage — caller holds self._lock
        oldest = f"{self.path}.{self.max_files}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for k in range(self.max_files - 1, 0, -1):
            src = f"{self.path}.{k}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{k + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "a", encoding="utf-8")  # graftlint: disable=lock-coverage — caller holds self._lock
        self._size = 0  # graftlint: disable=lock-coverage — caller holds self._lock
        self.rotations += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._flush_locked()
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "JsonlJournal":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ---------------------------------------------------------------- reading
def journal_paths(path: str) -> List[str]:
    """Every on-disk file of one journal, oldest first: ``path.N`` down to
    ``path.1``, then the live ``path``."""
    rotated = []
    k = 1
    while os.path.exists(f"{path}.{k}"):
        rotated.append(f"{path}.{k}")
        k += 1
    out = list(reversed(rotated))
    if os.path.exists(path):
        out.append(path)
    return out


def read_journal_ex(path: str) -> "Tuple[List[Dict[str, Any]], int]":
    """All records of a (possibly rotated) journal, oldest first, plus
    the number of unparseable/non-object lines that were skipped.

    Skipping (a crash mid-write tears the final line) is deliberate — a
    post-mortem reader must survive the crash it documents — but the
    count is reported so the CLI can WARN instead of silently narrowing
    the evidence.
    """
    records: List[Dict[str, Any]] = []
    skipped = 0
    for fn in journal_paths(path):
        with open(fn, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
                else:
                    skipped += 1
    return records, skipped


def read_journal(path: str) -> List[Dict[str, Any]]:
    """:func:`read_journal_ex` without the skip count."""
    return read_journal_ex(path)[0]
