"""Fleet health: the ``obs_snapshot`` RPC endpoint + crash forensics.

Every long-lived process in the fleet (worker, dispatcher) exposes one
introspection RPC, ``obs_snapshot``, returning a JSON-serializable
:meth:`HealthEndpoint.snapshot`: identity (host/pid/...), uptime, the
in-flight job, an atomic metrics snapshot, and the tail of the local
event ring buffer. The dispatcher's heartbeat loop collects these from
workers (falling back to plain ``ping`` for older peers — the endpoint
is additive, never required), feeding the ``dispatcher.workers_alive`` /
per-worker last-seen-age gauges.

:func:`install_crash_dump` is the other half of fleet forensics: an
unhandled exception (main thread via ``sys.excepthook``, any worker
thread via ``threading.excepthook``) writes the same snapshot — plus the
traceback — to a JSON file before the process dies, so a dead run leaves
a record instead of a silence.

This module is deliberately transport-agnostic: it never imports
``parallel/rpc.py`` (which imports ``obs`` — the dependency points one
way). ``register(server)`` only needs a ``server.register(name, fn)``
callable, which both :class:`~hpbandster_tpu.parallel.rpc.RPCServer` and
any future transport satisfy.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from hpbandster_tpu.obs.journal import (
    RingBuffer,
    event_to_record,
    process_identity,
)
from hpbandster_tpu.obs.metrics import MetricsRegistry, get_metrics

__all__ = ["HealthEndpoint", "install_crash_dump"]

logger = logging.getLogger("hpbandster_tpu.obs")


def _ring_tail(ring: Optional[RingBuffer], tail: int) -> List[Dict[str, Any]]:
    if ring is None:
        return []
    items = ring.snapshot()[-max(int(tail), 0):]
    # rings hold Events (bus sink) or plain record dicts (worker ring,
    # dead letters) — normalize to the journal record schema
    return [i if isinstance(i, dict) else event_to_record(i) for i in items]


class HealthEndpoint:
    """One process's introspection surface; register it on an RPC server.

    ``in_flight`` is a zero-arg callable returning a JSON-serializable
    description of what the process is working on right now (a worker's
    current config id, a dispatcher's running/waiting census) — or None.
    """

    def __init__(
        self,
        component: str,
        identity: Optional[Dict[str, Any]] = None,
        ring: Optional[RingBuffer] = None,
        in_flight: Optional[Callable[[], Any]] = None,
        registry: Optional[MetricsRegistry] = None,
        anomaly: Optional[Any] = None,
        slo: Optional[Any] = None,
    ):
        self.component = component
        self.identity = dict(identity) if identity is not None else process_identity()
        self._ring = ring
        self._in_flight = in_flight
        self._registry = registry
        #: optional obs.anomaly.AnomalyDetector whose alert tally rides
        #: the snapshot (anything with a .snapshot() -> dict works)
        self._anomaly = anomaly
        #: optional obs.alerts.AlertManager whose SLO verdict rides the
        #: snapshot (same duck-typed .snapshot() contract as anomaly)
        self._slo = slo
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()

    def snapshot(self, tail: int = 32) -> Dict[str, Any]:
        """The ``obs_snapshot`` RPC body: identity + uptime + in-flight
        work + atomic metrics cut (histograms include p50/p95) + a
        ``latency`` convenience section + newest ``tail`` ring events."""
        reg = self._registry if self._registry is not None else get_metrics()
        in_flight = None
        if self._in_flight is not None:
            try:
                in_flight = self._in_flight()
            except Exception:
                # introspection must never take the serving process down
                logger.exception("obs_snapshot in_flight callable failed")
        metrics = reg.snapshot()
        # the quantile cut `watch --snapshot` renders: latency visibility
        # with no journal on disk (histogram bounds cap the resolution —
        # the p50/p95 are bucket upper bounds, conservative by design)
        latency = {
            name: {"count": h["count"], "p50": h["p50"], "p95": h["p95"]}
            for name, h in metrics.get("histograms", {}).items()
        }
        out = {
            "component": self.component,
            "identity": self.identity,
            "uptime_s": round(time.monotonic() - self._t0_mono, 3),
            "started_t_wall": self._t0_wall,
            "in_flight": in_flight,
            "metrics": metrics,
            "latency": latency,
            "runtime": self._runtime_section(),
            "ring_tail": _ring_tail(self._ring, tail),
        }
        if self._anomaly is not None:
            try:
                out["alerts"] = self._anomaly.snapshot()
            except Exception:
                logger.exception("obs_snapshot anomaly snapshot failed")
        if self._slo is not None:
            try:
                out["slo"] = self._slo.snapshot()
            except Exception:
                logger.exception("obs_snapshot slo snapshot failed")
        return out

    def _runtime_section(self) -> Dict[str, Any]:
        """The XLA-runtime tier of the snapshot: compile ledger + newest
        device census (obs/runtime.py). Never initializes a jax backend."""
        from hpbandster_tpu.obs.runtime import runtime_snapshot

        try:
            return runtime_snapshot()
        except Exception:
            # introspection must never take the serving process down
            logger.exception("obs_snapshot runtime section failed")
            return {"compile": None, "devices": None}

    def metrics_text(self) -> str:
        """The Prometheus text exposition of this process's registry —
        the same atomic cut :meth:`snapshot` serializes as JSON, in the
        format a standard scraper ingests (obs/export.py)."""
        from hpbandster_tpu.obs.export import render_registry

        return render_registry(self._registry)

    # ------------------------------------------------------ deep profiling
    def start_profile(self, log_dir: Optional[str] = None) -> Dict[str, Any]:
        """The ``start_profile`` RPC body: begin a ``jax.profiler`` trace
        capture in THIS process (obs/profile.py) — remote, on demand,
        no construction-time ``profile_dir`` required."""
        from hpbandster_tpu.obs.profile import get_profile_session

        return get_profile_session().start(log_dir=log_dir)

    def stop_profile(self) -> Dict[str, Any]:
        """The ``stop_profile`` RPC body: end the live capture; reports
        the trace dir, duration, and file count."""
        from hpbandster_tpu.obs.profile import get_profile_session

        return get_profile_session().stop()

    def profile_status(self) -> Dict[str, Any]:
        from hpbandster_tpu.obs.profile import get_profile_session

        return get_profile_session().status()

    def register(self, server: Any) -> None:
        """Expose :meth:`snapshot` as the ``obs_snapshot`` RPC method,
        :meth:`metrics_text` as ``metrics_text``, and the on-demand
        profiling trio (``start_profile`` / ``stop_profile`` /
        ``profile_status``) — every fleet process is scrapeable AND
        profileable through its existing health port."""
        server.register("obs_snapshot", self.snapshot)
        server.register("metrics_text", self.metrics_text)
        server.register("start_profile", self.start_profile)
        server.register("stop_profile", self.stop_profile)
        server.register("profile_status", self.profile_status)


def install_crash_dump(
    path: str,
    component: str = "",
    ring: Optional[RingBuffer] = None,
    registry: Optional[MetricsRegistry] = None,
) -> Callable[[], None]:
    """Dump ring buffer + metrics + traceback to ``path`` on an unhandled
    exception, then chain to the previous hooks (output still appears).

    Covers the main thread (``sys.excepthook``) and worker threads
    (``threading.excepthook``). Returns an idempotent ``uninstall()``
    restoring the previous hooks.
    """
    prev_sys = sys.excepthook
    prev_threading = threading.excepthook
    state = {"installed": True}

    def _dump(exc_type: type, exc: BaseException, tb: Any,
              thread_name: Optional[str] = None) -> None:
        try:
            reg = registry if registry is not None else get_metrics()
            dump = {
                "t_wall": time.time(),
                "component": component,
                "identity": process_identity(),
                "thread": thread_name,
                "exception": {
                    "type": getattr(exc_type, "__name__", str(exc_type)),
                    "message": str(exc),
                    "traceback": "".join(
                        traceback.format_exception(exc_type, exc, tb)
                    ),
                },
                "metrics": reg.snapshot(),
                "ring_tail": _ring_tail(ring, 256),
            }
            directory = os.path.dirname(os.path.abspath(path))
            os.makedirs(directory, exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(dump, fh, indent=1, default=str)
        except Exception:
            # forensics must never mask the crash it documents
            logger.exception("crash dump to %s failed", path)

    def _sys_hook(exc_type, exc, tb):
        _dump(exc_type, exc, tb)
        prev_sys(exc_type, exc, tb)

    def _threading_hook(args):
        _dump(
            args.exc_type, args.exc_value, args.exc_traceback,
            thread_name=getattr(args.thread, "name", None),
        )
        prev_threading(args)

    sys.excepthook = _sys_hook
    threading.excepthook = _threading_hook

    def uninstall() -> None:
        if state["installed"]:
            state["installed"] = False
            sys.excepthook = prev_sys
            threading.excepthook = prev_threading

    return uninstall
