"""Optimizer decision audit: why did BOHB do what it did?

PRs 2–3 made the *infrastructure* observable; this module makes the
*algorithm* observable. Two record kinds ride the existing JSONL journal
schema (``docs/observability.md`` "Optimizer decision audit"):

* ``config_sampled`` — one record per config entering a bracket, emitted
  by :meth:`core.iteration.BaseIteration.add_configuration` (the one
  place a config receives its id). The decision details come from the
  config generator's info dict: was the pick model-based or random (and
  WHY random — no trained model yet vs the ``random_fraction`` coin vs a
  model failure), which budget's KDE proposed it, how many observations
  that model had, and the winning ``log l(x) - log g(x)`` acquisition
  score (BOHB §3, Falkner et al. 2018).
* ``promotion_decision`` — one record per rung advancement, emitted by
  :meth:`core.iteration.BaseIteration.process_results`: the rung, its
  budget and the next one, every candidate's loss, the promotion mask,
  and the effective cut threshold (the worst promoted loss). When the
  promotion rule ranked by something other than the raw losses (H2BO's
  learning-curve extrapolation), the rule's scores ride along — the
  record shows what the decision was actually based on.

Both kinds carry ``config_id`` triples, so
:func:`config_lineage` can replay a journal into per-config stories
(sampled → evaluated per budget → promoted/terminated at each rung) —
the join the report CLI (``obs/report.py``) builds its model-vs-random
win rate and promotion-regret tables from.

Emission goes through the event bus, so the no-sink cost is the usual
~zero (the ``audit_emit_ns`` micro in the bench's ``obs_overhead`` tier
measures it), and the ``obs-reserved-fields`` graftlint rule applies
unchanged: audit call sites never stamp ``trace_id``/``host`` by hand.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from hpbandster_tpu.obs import events as E

__all__ = [
    "AUDIT_EVENTS",
    "SAMPLING_INFO_KEYS",
    "AUDIT_RULE_FIELDS",
    "emit_bracket_created",
    "emit_bracket_promotion",
    "emit_config_sampled",
    "emit_promotion_decision",
    "emit_sweep_incumbent",
    "note_straggler",
    "drain_stragglers",
    "config_key",
    "config_lineage",
]

#: the audit vocabulary (subset of ``obs.EVENT_TYPES``)
AUDIT_EVENTS = frozenset(
    {E.CONFIG_SAMPLED, E.PROMOTION_DECISION, E.SWEEP_INCUMBENT}
)

#: promotion-audit field names only the dedicated emitters below may
#: stamp (the ``obs-reserved-fields`` graftlint rule enforces it for
#: generic ``emit``/``span`` call sites outside the obs substrate): the
#: active promotion rule and rung, the Pareto ranking a multi-objective
#: decision ranked by, and the straggler correlation marker. An ad-hoc
#: emitter inventing any of these would corrupt the replay/regret join.
AUDIT_RULE_FIELDS = frozenset(
    {"rule", "rung", "pareto_rank", "straggler_observed"}
)

#: config-generator info keys copied into the ``config_sampled`` record.
#: Generators attach these to the info dict they already return (the dict
#: that lands in ``Datum.config_info`` / results.json), so the audit
#: record and the Result stay consistent by construction.
SAMPLING_INFO_KEYS = (
    "model_based_pick",   # bool — model proposal vs random draw
    "sample_reason",      # "model" | "no_model" | "random_fraction" | "model_failure" | "random_search" | "fused_sweep"
    "model_budget",       # which budget's KDE proposed it
    "n_points_in_model",  # observations the proposing KDE was fit on
    "lg_score",           # winning log l(x) - log g(x) acquisition score
    "bandwidth_factor",   # sampling bandwidth multiplier in effect
)


def emit_bracket_created(
    iteration: int,
    num_configs: Sequence[int],
    budgets: Sequence[float],
    eta: Optional[float] = None,
    random_fraction: Optional[float] = None,
) -> None:
    """One ``bracket_created`` record — the bracket plan plus the knobs
    its sampling decisions run under. The single emitter every optimizer
    tier (BOHB, H2BO, fused replay) calls, so the record shape the
    report's bracket table consumes cannot drift between tiers."""
    E.emit(
        "bracket_created",
        iteration=int(iteration),
        num_configs=list(num_configs),
        budgets=list(budgets),
        eta=eta,
        random_fraction=random_fraction,
    )


# -------------------------------------------------------- straggler ledger
#: (run, tenant, config id) triples the anomaly detector's straggler
#: rule flagged, awaiting their rung's next promotion decision (bounded:
#: a run that never promotes must not grow this without limit). The
#: ledger is process-global, so entries are SCOPED by the ambient run
#: (``obs.use_run`` — the master wraps its ingestion path; sinks fall
#: back to the job trace's run_id) and tenant: config-id triples restart
#: at (0, 0, 0) every sweep, and without the scope a marker from one
#: finished sweep — or a concurrent tenant's — would drain into an
#: unrelated decision. Guarded by _STRAGGLER_LOCK — the detector fires
#: from whatever thread emitted the slow event while the master's
#: bookkeeping thread drains.
_StragglerEntry = Tuple[
    Optional[str], Optional[str], Optional[float], Tuple[int, ...]
]
_STRAGGLER_LEDGER: Deque[_StragglerEntry] = collections.deque(maxlen=512)
_STRAGGLER_LOCK = threading.Lock()


def _straggler_scope() -> Tuple[Optional[str], Optional[str]]:
    from hpbandster_tpu.obs.trace import current_run, current_tenant

    return current_run(), current_tenant()


def note_straggler(config_id: Any, budget: Optional[float] = None) -> None:
    """Record a straggler verdict against ``config_id`` (called by the
    anomaly detector when its straggler rule fires on a job event). The
    id joins that rung's next ``promotion_decision`` record — same run,
    tenant, and (when known) the budget the slow evaluation ran at — as
    a ``straggler_observed`` entry, closing the anomaly -> scheduler
    loop one notch: replays can correlate stalls with promotion timing.
    The budget matters under async rules: a config promoted from rung 0
    and flagged while running at budget 3 appears in BOTH rungs'
    candidate censuses, and the marker belongs on the rung that actually
    stalled."""
    key = config_key(config_id)
    if key is None:
        return
    budget = (
        float(budget) if isinstance(budget, (int, float)) else None
    )
    entry = (*_straggler_scope(), budget, key)
    with _STRAGGLER_LOCK:
        if entry not in _STRAGGLER_LEDGER:
            _STRAGGLER_LEDGER.append(entry)


def drain_stragglers(
    config_ids: Sequence[Sequence[int]],
    budget: Optional[float] = None,
) -> List[Tuple[int, ...]]:
    """Flagged ids among ``config_ids`` in the current run/tenant scope
    at ``budget``, removed from the ledger (each straggler verdict rides
    exactly one promotion record). Ids flagged for other rungs — or
    other runs or tenants — stay queued for their own decision. A
    budget of None on either side is a wildcard (hand-rolled notes and
    foreign journals without budget fields still correlate)."""
    keys = {config_key(cid) for cid in config_ids}
    keys.discard(None)
    run, tenant = _straggler_scope()
    budget = (
        float(budget) if isinstance(budget, (int, float)) else None
    )
    with _STRAGGLER_LOCK:
        matched = [
            e for e in _STRAGGLER_LEDGER
            if e[0] == run and e[1] == tenant and e[3] in keys
            and (e[2] is None or budget is None or e[2] == budget)
        ]
        for e in matched:
            _STRAGGLER_LEDGER.remove(e)
    return [e[3] for e in matched]


def emit_bracket_promotion(
    iteration: int,
    rung: int,
    rule: str,
    promoted: int,
    candidates: int,
    budget: float,
    next_budget: Optional[float],
) -> None:
    """One ``bracket_promotion`` event stamped with the active promotion
    rule and rung — the single emitter every promotion tier calls, so the
    labeled Prometheus family and the journal event cannot drift.

    Beside the event, the ``bracket.promotions.<rule>.<rung>`` counter
    advances by the promoted-config count; ``obs/export.py`` renders it
    as ``bracket_promotions_total{rule=..., rung=...}``. The counter
    advances even with no bus sink (metrics are always-on, like every
    other registry family); the event costs ~nothing unheard.
    """
    from hpbandster_tpu.obs.metrics import get_metrics

    get_metrics().counter(
        f"bracket.promotions.{rule}.{int(rung)}"
    ).inc(int(promoted))
    E.emit(
        E.BRACKET_PROMOTION,
        iteration=int(iteration),
        # `stage` keeps the historical meaning (the stage being ENTERED)
        # so pre-existing journal readers stay correct; `rung` is the
        # stage the decision ranked (= stage - 1 for sync advancement)
        stage=int(rung) + 1,
        rung=int(rung),
        rule=rule,
        promoted=int(promoted),
        candidates=int(candidates),
        budget=budget,
        next_budget=next_budget,
    )


def emit_config_sampled(
    config_id: Sequence[int],
    budget: float,
    config_info: Optional[Dict[str, Any]] = None,
) -> None:
    """Emit one per-sample decision record (no-op with no sink attached).

    Only the :data:`SAMPLING_INFO_KEYS` present in ``config_info`` are
    copied — a generator that predates a key simply produces a sparser
    record, never a schema error.
    """
    if not E.get_bus().active:
        return  # no sink: skip even the field-dict build (hot sample loop)
    fields: Dict[str, Any] = {
        "config_id": list(config_id), "budget": budget,
    }
    if config_info:
        for key in SAMPLING_INFO_KEYS:
            if key in config_info:
                fields[key] = config_info[key]
    E.emit(E.CONFIG_SAMPLED, **fields)


def emit_promotion_decision(
    iteration: int,
    rung: int,
    budget: float,
    next_budget: Optional[float],
    config_ids: Sequence[Sequence[int]],
    losses: Sequence[Optional[float]],
    promoted: Sequence[bool],
    rule: str = "successive_halving",
    scores: Optional[Sequence[Optional[float]]] = None,
    pareto_rank: Optional[Sequence[Optional[int]]] = None,
    costs: Optional[Sequence[Optional[float]]] = None,
) -> None:
    """Emit one per-rung promotion record (no-op with no sink attached).

    ``losses`` may contain None (crashed configs); ``scores`` is the
    promotion rule's ranking values when they differ from the raw losses
    (H2BO extrapolation / learning-curve early stopping). The cut
    threshold is the worst promoted loss — the rung's effective survival
    bar in hindsight analysis. ``pareto_rank`` carries the domination
    counts a multi-objective decision ranked by; ``costs`` the measured
    per-candidate evaluation cost (seconds), which is what makes a
    recorded journal Pareto-replayable after the fact. Config ids the
    straggler rule flagged since the last decision join the record as
    ``straggler_observed`` (see :func:`note_straggler`).
    """
    if not E.get_bus().active:
        return  # no sink: skip the per-candidate list builds
    promoted = [bool(p) for p in promoted]
    survivor_losses = [
        l for l, p in zip(losses, promoted) if p and l is not None
    ]
    fields: Dict[str, Any] = {
        "iteration": int(iteration),
        "rung": int(rung),
        "budget": budget,
        "next_budget": next_budget,
        "rule": rule,
        "config_ids": [list(cid) for cid in config_ids],
        "losses": list(losses),
        "promoted": promoted,
        "n_promoted": sum(promoted),
        "n_candidates": len(promoted),
        "cut_threshold": max(survivor_losses) if survivor_losses else None,
        "survivor_losses": sorted(survivor_losses),
    }
    if scores is not None:
        fields["scores"] = list(scores)
    if pareto_rank is not None:
        fields["pareto_rank"] = [
            None if r is None else int(r) for r in pareto_rank
        ]
    if costs is not None:
        fields["costs"] = [
            None if c is None else float(c) for c in costs
        ]
    flagged = drain_stragglers(config_ids, budget=budget)
    if flagged:
        fields["straggler_observed"] = [list(k) for k in flagged]
    E.emit(E.PROMOTION_DECISION, **fields)


def emit_sweep_incumbent(
    vector: Sequence[float],
    loss: Optional[float],
    bracket: int,
    per_bracket_loss: Sequence[Optional[float]],
    evaluations: Optional[int] = None,
    n_configs: Optional[int] = None,
    d2h_bytes: Optional[int] = None,
    h2d_bytes: Optional[int] = None,
    host_syncs: Optional[int] = None,
) -> None:
    """Journal a resident (incumbent-only) sweep's single device->host
    payload — the ONE decision record such a sweep produces.

    When the whole HyperBand outer loop runs in-trace
    (``ops/sweep.py`` ``resident=True`` + ``incumbent_only=True``),
    per-rung promotion decisions never leave the device; this record
    carries everything that did: the winning configuration vector, its
    final-stage loss, which bracket produced it, and each bracket's best
    final loss — enough for ``obs replay`` to re-score the incumbent
    pick against the per-bracket bests (the regret surface that remains
    when per-rung candidates were never materialized host-side). The
    per-sweep transfer accounting (``d2h_bytes``/``h2d_bytes``/
    ``host_syncs``, from :func:`obs.runtime.publish_sweep_transfers`)
    rides along so the flat-d2h claim is replayable from the journal.

    Non-finite losses journal as None (strict-JSON rule, like the
    master's loss-carrying records).
    """

    def _j(v: Any) -> Optional[float]:
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            v = float(v)
            return v if v == v and v not in (float("inf"), float("-inf")) else None
        return None

    fields: Dict[str, Any] = {
        "vector": [_j(x) for x in vector],
        "loss": _j(loss),
        "bracket": int(bracket),
        "per_bracket_loss": [_j(l) for l in per_bracket_loss],
    }
    if evaluations is not None:
        fields["evaluations"] = int(evaluations)
    if n_configs is not None:
        fields["n_configs"] = int(n_configs)
    if d2h_bytes is not None:
        fields["d2h_bytes"] = int(d2h_bytes)
    if h2d_bytes is not None:
        fields["h2d_bytes"] = int(h2d_bytes)
    if host_syncs is not None:
        fields["host_syncs"] = int(host_syncs)
    E.emit(E.SWEEP_INCUMBENT, **fields)


# ------------------------------------------------------------------ replay
def config_key(config_id: Any) -> Optional[Tuple[int, ...]]:
    """Journal ``config_id`` field -> hashable lineage key (or None)."""
    if isinstance(config_id, (list, tuple)) and config_id:
        try:
            return tuple(int(x) for x in config_id)
        except (TypeError, ValueError):
            return None
    return None


def config_lineage(
    records: List[Dict[str, Any]],
) -> Dict[Tuple[int, ...], Dict[str, Any]]:
    """Replay journal records into per-config decision lineages.

    Returns ``{config_id: lineage}`` where each lineage carries:

    * ``sampled`` — the ``config_sampled`` audit fields (first wins);
    * ``results`` — ``{budget: loss}`` from master-side
      ``job_finished`` records (first completed evaluation per budget;
      ``None`` = crashed);
    * ``rungs`` — ordered ``(iteration, rung, budget, promoted)``
      promotion outcomes this config was a candidate in.

    Deterministic in the record order (callers pass
    ``summarize.read_merged`` output, which is wall-clock sorted).
    """
    lineages: Dict[Tuple[int, ...], Dict[str, Any]] = {}

    def slot(key: Tuple[int, ...]) -> Dict[str, Any]:
        return lineages.setdefault(
            key, {"sampled": None, "results": {}, "rungs": []}
        )

    for rec in records:
        name = rec.get("event")
        if name == E.CONFIG_SAMPLED:
            key = config_key(rec.get("config_id"))
            if key is None:
                continue
            s = slot(key)
            if s["sampled"] is None:
                s["sampled"] = {
                    k: rec[k] for k in SAMPLING_INFO_KEYS if k in rec
                }
        elif name in (E.JOB_FINISHED, E.JOB_FAILED):
            key = config_key(rec.get("config_id"))
            budget = rec.get("budget")
            # the loss-carrying record is authoritative (master funnel /
            # fused replay); worker-side twins carry compute_s, no loss
            if key is None or not isinstance(budget, (int, float)):
                continue
            if "loss" not in rec:
                continue
            s = slot(key)
            if float(budget) not in s["results"]:
                loss = rec.get("loss")
                s["results"][float(budget)] = (
                    float(loss) if isinstance(loss, (int, float)) else None
                )
        elif name == E.PROMOTION_DECISION:
            ids = rec.get("config_ids")
            promoted = rec.get("promoted")
            if not isinstance(ids, list) or not isinstance(promoted, list):
                continue
            for cid, prom in zip(ids, promoted):
                key = config_key(cid)
                if key is None:
                    continue
                slot(key)["rungs"].append((
                    rec.get("iteration"), rec.get("rung"),
                    rec.get("budget"), bool(prom),
                ))
    return lineages
