"""Unified sweep timeline: flight recorder, Perfetto export, critical path.

The stack already emits five telemetry planes — host spans
(``obs/events.py``), trace contexts and RPC hop envelopes
(``obs/trace.py`` + ``parallel/rpc.py``), ``tracked_jit`` compile events
(``obs/runtime.py``), serve lane lifecycle records
(``serve/continuous.py``), and the device metrics plane's per-rung
sections (``obs/device_metrics.py``, ordered by the ``rung_seq`` stamp
the in-trace accumulator writes). Each answers its own question; none
answers *where the wall-clock of one sweep went*. This module joins
them into one causally-ordered timeline:

* :func:`to_chrome_trace` exports merged journal records as Chrome
  trace-event JSON (open in https://ui.perfetto.dev): one process row
  per ``(host, pid)``, thread rows for the main loop, each worker, each
  serve lane and the device loop, duration slices for every span-shaped
  record, per-rung device slices laid out in ``rung_seq`` order, and
  flow arrows following a ``trace_id`` across RPC hops into the device
  loop. ``python -m hpbandster_tpu.obs timeline <journal> --out
  trace.json`` is the CLI face.
* :func:`critical_path` walks the same span set and attributes the
  journal's end-to-end wall-clock to the named phases below. Overlapping
  concurrent spans never double-count: the attribution sweeps elementary
  time segments and charges each to the highest-priority active phase,
  so phase seconds always sum to <= the end-to-end span (a property test
  pins this for arbitrary journals). ``obs critical-path`` renders the
  per-phase table; the machine-readable verdict lands in bench.py's
  artifact next to the budget verdicts.

Clock discipline (the cross-host alignment fix): merged records are
ordered on each host's monotonic clock re-anchored by the host's MEDIAN
``t_wall - t_mono`` offset — the wall/mono twin-stamp convention every
event and ``core.job.Job`` already carries. A wall-clock step (NTP jump)
mid-run moves a record's ``t_wall`` but not its ``t_mono``, and one
host's skewed records cannot shuffle another host's ordering; durations
were always monotonic-measured at the emitting site and are used as-is.

Recording discipline: the span API below (:func:`phase_span`,
:func:`mark`) delegates to ``obs.events`` — near-zero with no sink
attached, and NEVER legal inside a jitted function (the
``obs-emit-in-jit`` graftlint rule covers these names too). With the
recorder off, behavior is byte-identical to not having it: no clock
reads, no event construction.
"""

from __future__ import annotations

import statistics
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.journal import event_to_record, process_identity

__all__ = [
    "ADMISSION",
    "COMPILE",
    "TRANSFER",
    "RUNG_COMPUTE",
    "PROMOTION",
    "KDE_REFIT",
    "RPC",
    "PHASES",
    "phase_span",
    "mark",
    "TimelineRecorder",
    "clock_offsets",
    "normalized_time",
    "align_clocks",
    "build_timeline",
    "to_chrome_trace",
    "critical_path",
    "format_critical_path",
]

# ------------------------------------------------------------ phase taxonomy
#: master-side wait before a job/chunk is admitted to execution
ADMISSION = "admission_wait"
#: XLA compilation (tracked_jit ledger, sweep_chunk compile splits)
COMPILE = "compile"
#: host<->device transfer (h2d staging, d2h fetch)
TRANSFER = "transfer"
#: rung evaluation work — device execute windows, worker compute spans
RUNG_COMPUTE = "rung_compute"
#: promotion/successive-halving bookkeeping
PROMOTION = "promotion"
#: KDE model refits
KDE_REFIT = "kde_refit"
#: RPC dispatch/delivery hops and retries
RPC = "rpc"

#: the closed phase vocabulary (docs/observability.md "Timeline &
#: critical path") — ``phase_span`` refuses names outside it so the
#: critical-path table cannot silently grow unaggregatable rows
PHASES = (ADMISSION, COMPILE, TRANSFER, RUNG_COMPUTE, PROMOTION,
          KDE_REFIT, RPC)

#: attribution priority when concurrent spans overlap (lower = wins):
#: device/eval work is the sweep's purpose, so overhead phases only
#: claim time no compute span covers
_PHASE_PRIORITY = {
    RUNG_COMPUTE: 0, COMPILE: 1, TRANSFER: 2, KDE_REFIT: 3,
    PROMOTION: 4, RPC: 5, ADMISSION: 6,
}

#: event name -> phase, for the signals that predate the explicit
#: ``phase=`` field (an explicit field always wins)
_EVENT_PHASE = {
    E.XLA_COMPILE: COMPILE,
    E.KDE_REFIT: KDE_REFIT,
    E.BRACKET_PROMOTION: PROMOTION,
    E.PROMOTION_DECISION: PROMOTION,
    E.RPC_RETRY: RPC,
    E.RPC_CLIENT_CALL: RPC,
    "sweep_chunk": RUNG_COMPUTE,
    "wave_evaluate": RUNG_COMPUTE,
    "serve_chunk": RUNG_COMPUTE,
}

#: journal stage fields (obs/summarize.py _STAGE_FIELDS) -> phase; each
#: is a duration measured at its emitting site, ending at the record
_STAGE_PHASE = (
    ("queue_wait_s", ADMISSION),
    ("dispatch_s", RPC),
    ("compute_s", RUNG_COMPUTE),
    ("delivery_s", RPC),
)


# --------------------------------------------------------- timeline span API
def phase_span(name: str, phase: str, **fields: Any):
    """A named duration region pre-attributed to one of :data:`PHASES`.

    Thin wrapper over :func:`obs.events.span` that stamps the ``phase``
    field the critical-path analyzer attributes by — same near-zero
    inactive path (no sinks + no jax annotation backend = no clock
    reads), same monotonic measurement, same ban on use inside jitted
    code (``obs-emit-in-jit``). Returns the span context manager
    directly rather than wrapping it in a second generator frame: the
    validation happens once at call time, so the inactive ``with`` costs
    ONE context frame, not two (bench_timeline_overhead measures this
    path)."""
    if phase not in _PHASE_PRIORITY:
        raise ValueError(
            f"unknown phase {phase!r}; expected one of {PHASES}"
        )
    return E.span(name, phase=phase, **fields)


def mark(name: str, phase: str, **fields: Any) -> Optional[E.Event]:
    """Emit one instant timeline event attributed to ``phase`` — the
    point-in-time sibling of :func:`phase_span` (no-op without a sink,
    like every emit; never legal inside jitted code)."""
    if phase not in _PHASE_PRIORITY:
        raise ValueError(
            f"unknown phase {phase!r}; expected one of {PHASES}"
        )
    return E.emit(name, phase=phase, **fields)


class TimelineRecorder:
    """In-memory flight recorder: a bus sink that accumulates
    journal-shaped records (identity-stamped like ``JsonlJournal``
    lines), so benches and tests can build timelines without a journal
    on disk. ``attach()``/``detach()`` manage the subscription; the
    recorded list (:attr:`records`) feeds :func:`to_chrome_trace` /
    :func:`critical_path` directly."""

    def __init__(self, static_fields: Optional[Dict[str, Any]] = None):
        self.static_fields = (
            dict(static_fields) if static_fields is not None
            else process_identity()
        )
        self._events: List[E.Event] = []
        self._records: List[Dict[str, Any]] = []
        self._detach = None

    def __call__(self, ev: E.Event) -> None:
        # hot path: ONE list append. Flattening into journal-shaped dicts
        # is deferred to :attr:`records` — the recorded process pays
        # O(100ns) per event, not the µs-scale dict build (the
        # timeline_overhead bench bar rides on this)
        self._events.append(ev)

    @property
    def records(self) -> List[Dict[str, Any]]:
        """Journal-shaped dicts for everything recorded so far (flattened
        lazily and cached; safe to read mid-recording)."""
        while len(self._records) < len(self._events):
            rec = event_to_record(self._events[len(self._records)])
            for k, v in self.static_fields.items():
                rec.setdefault(k, v)
            self._records.append(rec)
        return self._records

    def attach(self, bus: Optional[E.EventBus] = None) -> "TimelineRecorder":
        if self._detach is None:
            self._detach = (bus if bus is not None else E.get_bus()).subscribe(self)
        return self

    def detach(self) -> None:
        if self._detach is not None:
            self._detach()
            self._detach = None

    def __enter__(self) -> "TimelineRecorder":
        return self.attach()

    def __exit__(self, *exc: Any) -> None:
        self.detach()


# ----------------------------------------------------------- clock alignment
def _num(v: Any) -> Optional[float]:
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        v = float(v)
        if v == v and v not in (float("inf"), float("-inf")):
            return v
    return None


def _proc_key(rec: Dict[str, Any]) -> Tuple[str, int]:
    pid = rec.get("pid")
    return (
        str(rec.get("host", "?")),
        int(pid) if isinstance(pid, int) and not isinstance(pid, bool) else 0,
    )


def clock_offsets(
    records: Sequence[Dict[str, Any]],
) -> Dict[Tuple[str, int], float]:
    """Per-``(host, pid)`` wall-anchoring offset: the MEDIAN of each
    process's ``t_wall - t_mono`` twin stamps. The median is the skew
    estimator: a wall-clock step mid-run shifts a minority of stamps and
    leaves the estimate on the stable majority, while monotonic clocks
    (which never jump) carry all intra-process ordering."""
    groups: Dict[Tuple[str, int], List[float]] = {}
    for rec in records:
        tw, tm = _num(rec.get("t_wall")), _num(rec.get("t_mono"))
        if tw is not None and tm is not None:
            groups.setdefault(_proc_key(rec), []).append(tw - tm)
    return {k: statistics.median(v) for k, v in groups.items()}


def normalized_time(
    rec: Dict[str, Any],
    offsets: Dict[Tuple[str, int], float],
) -> float:
    """One record's position on the merged timeline: its monotonic stamp
    re-anchored by its process's offset; records without a twin stamp
    fall back to raw ``t_wall`` (they can only order, never measure)."""
    tm = _num(rec.get("t_mono"))
    if tm is not None:
        off = offsets.get(_proc_key(rec))
        if off is not None:
            return off + tm
    tw = _num(rec.get("t_wall"))
    return tw if tw is not None else 0.0


def align_clocks(
    records: Sequence[Dict[str, Any]],
) -> Tuple[List[Dict[str, Any]], Dict[Tuple[str, int], float]]:
    """Merged records re-ordered on normalized (mono-anchored) time, plus
    the per-process offsets used — the ordering every timeline consumer
    downstream of ``read_merged_ex``'s wall-clock sort should use."""
    offsets = clock_offsets(records)
    ordered = sorted(records, key=lambda r: normalized_time(r, offsets))
    return ordered, offsets


# -------------------------------------------------------- interval extraction
def phase_of(rec: Dict[str, Any]) -> Optional[str]:
    """The phase one journal record belongs to: an explicit ``phase``
    field (the timeline span API) wins; known event names map via
    :data:`_EVENT_PHASE`; anything else is unattributed."""
    p = rec.get("phase")
    if isinstance(p, str) and p in _PHASE_PRIORITY:
        return p
    name = rec.get("event")
    return _EVENT_PHASE.get(name) if isinstance(name, str) else None


def _intervals(
    records: Sequence[Dict[str, Any]],
    offsets: Dict[Tuple[str, int], float],
) -> List[Dict[str, Any]]:
    """Every duration the journal carries, as
    ``{t0, t1, phase, name, row, rec}`` dicts (``phase`` may be None for
    span-shaped records outside the taxonomy; ``row`` is the thread-row
    hint for the exporter). Durations are the monotonic measurements in
    the records — never re-derived from wall stamps."""
    out: List[Dict[str, Any]] = []

    def add(t1, dur, phase, name, rec, row=None):
        dur = _num(dur)
        if dur is None or dur <= 0:
            return
        out.append({
            "t0": t1 - dur, "t1": t1, "phase": phase, "name": name,
            "rec": rec, "row": row,
        })

    for rec in records:
        t = normalized_time(rec, offsets)
        name = rec.get("event")
        name = name if isinstance(name, str) else "?"
        dur = _num(rec.get("duration_s"))
        if name == "sweep_chunk" and dur is not None:
            # one fused/chunked dispatch: the span covers compile (cache
            # misses only) + execute + fetch; split the compile share out
            # so the phase table separates them
            comp = _num(rec.get("compile_s")) or 0.0
            comp = min(max(comp, 0.0), dur)
            if comp > 0:
                add(t - dur + comp, comp, COMPILE, "sweep_chunk compile", rec)
            add(t, dur - comp, RUNG_COMPUTE, name, rec)
        elif dur is not None:
            add(t, dur, phase_of(rec), name, rec)
        elif name == E.XLA_COMPILE:
            add(t, _num(rec.get("compile_s")), COMPILE, name, rec)
        for field, phase in _STAGE_PHASE:
            if field == "compute_s" and dur is not None:
                continue  # a span already measured the compute window
            add(t, _num(rec.get(field)), phase, f"{name}.{field}", rec)
        if name == E.DEVICE_TELEMETRY:
            out.extend(_device_intervals(rec, t))
    return out


def _device_intervals(rec: Dict[str, Any], t: float) -> List[Dict[str, Any]]:
    """Per-rung device slices for one ``device_telemetry`` record: the
    decoded ``rung_order`` section (``rung_seq``-ordered) laid back to
    back across the sweep's measured ``execute_s`` window, ending at the
    record (the decode happens on the sweep's final d2h)."""
    execute_s = _num(rec.get("execute_s"))
    order = rec.get("rung_order")
    if execute_s is None or execute_s <= 0 or not isinstance(order, list):
        return []
    entries = [
        e for e in order
        if isinstance(e, dict) and _num(e.get("est_s")) is not None
    ]
    if not entries:
        return []
    entries.sort(key=lambda e: (e.get("seq", 0)))
    total = sum(float(e["est_s"]) for e in entries)
    scale = execute_s / total if total > 0 else 0.0
    t0 = t - execute_s
    out = []
    for e in entries:
        d = float(e["est_s"]) * scale
        out.append({
            "t0": t0, "t1": t0 + d, "phase": RUNG_COMPUTE,
            "name": "rung b%s r%s budget=%g" % (
                e.get("bracket", "?"), e.get("stage", "?"),
                float(e.get("budget", 0.0)),
            ),
            "rec": rec, "row": "device",
        })
        t0 += d
    return out


# ------------------------------------------------------------- chrome export
def _row_of(interval: Dict[str, Any]) -> str:
    """Thread-row label for one interval within its process."""
    if interval.get("row"):
        return str(interval["row"])
    rec = interval["rec"]
    worker = rec.get("worker")
    if isinstance(worker, str) and worker:
        return f"worker {worker}"
    lane = rec.get("lane")
    if isinstance(lane, int) and not isinstance(lane, bool):
        return f"lane {lane}"
    return "main"


def _flow_id(trace_id: str) -> int:
    return zlib.crc32(trace_id.encode("utf-8", "replace")) & 0x7FFFFFFF


def build_timeline(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The assembled timeline: Chrome trace events plus summary stats.

    Returns ``{"traceEvents": [...], "stats": {...}}``; use
    :func:`to_chrome_trace` for the plain Perfetto-loadable dict.
    Timestamps are microseconds relative to the earliest normalized
    record (Chrome trace format wants us, not s)."""
    ordered, offsets = align_clocks(list(records))
    intervals = _intervals(ordered, offsets)

    times = [normalized_time(r, offsets) for r in ordered]
    times += [iv["t0"] for iv in intervals]
    t_base = min(times) if times else 0.0
    t_end = max(times + [iv["t1"] for iv in intervals]) if times else 0.0

    def us(t: float) -> int:
        return int(round((t - t_base) * 1e6))

    # process rows: one per (host, pid); thread rows assigned on demand
    pids: Dict[Tuple[str, int], int] = {}
    tids: Dict[Tuple[int, str], int] = {}
    events: List[Dict[str, Any]] = []

    def pid_of(key: Tuple[str, int]) -> int:
        if key not in pids:
            pids[key] = len(pids) + 1
            events.append({
                "ph": "M", "name": "process_name", "pid": pids[key],
                "tid": 0, "args": {"name": "%s:%d" % key},
            })
        return pids[key]

    def tid_of(pid: int, row: str) -> int:
        key = (pid, row)
        if key not in tids:
            tids[key] = sum(1 for p, _r in tids if p == pid) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tids[key], "args": {"name": row},
            })
        return tids[key]

    #: args whitelist: small scalar fields worth carrying into Perfetto
    _ARG_FIELDS = (
        "trace_id", "tenant_id", "config_id", "budget", "worker", "lane",
        "family", "tenant", "fn", "evaluations", "brackets", "seq",
        "lanes", "compile_cache_hit", "h2d_bytes", "d2h_bytes", "method",
    )

    slice_rows: Dict[int, Tuple[int, int]] = {}
    for iv in intervals:
        rec = iv["rec"]
        pid = pid_of(_proc_key(rec))
        tid = tid_of(pid, _row_of(iv))
        args = {
            k: rec[k] for k in _ARG_FIELDS
            if k in rec and isinstance(rec[k], (str, int, float, bool))
        }
        if iv["phase"]:
            args["phase"] = iv["phase"]
        events.append({
            "ph": "X", "name": iv["name"],
            "cat": iv["phase"] or "span",
            "pid": pid, "tid": tid,
            "ts": us(iv["t0"]),
            "dur": max(int(round((iv["t1"] - iv["t0"]) * 1e6)), 1),
            "args": args,
        })
        slice_rows[id(rec)] = (pid, tid)

    # lane occupancy slices: lane_assigned opens, the next assignment or
    # lane_released closes (an open lane at journal end closes there)
    open_lanes: Dict[Tuple[Tuple[str, int], int], Tuple[float, Dict[str, Any]]] = {}

    def close_lane(key, t1):
        t0, rec = open_lanes.pop(key)
        pid = pid_of(key[0])
        tid = tid_of(pid, f"lane {key[1]}")
        events.append({
            "ph": "X",
            "name": "tenant %s" % rec.get("tenant", "?"),
            "cat": "lane", "pid": pid, "tid": tid,
            "ts": us(t0), "dur": max(int(round((t1 - t0) * 1e6)), 1),
            "args": {
                k: rec[k] for k in ("lane", "family", "tenant", "trace_id")
                if isinstance(rec.get(k), (str, int, float, bool))
            },
        })

    for rec in ordered:
        name = rec.get("event")
        lane = rec.get("lane")
        if name not in (E.LANE_ASSIGNED, E.LANE_RELEASED):
            continue
        if not isinstance(lane, int) or isinstance(lane, bool):
            continue
        t = normalized_time(rec, offsets)
        key = (_proc_key(rec), lane)
        if key in open_lanes:
            close_lane(key, t)
        if name == E.LANE_ASSIGNED:
            open_lanes[key] = (t, rec)
    for key in list(open_lanes):
        close_lane(key, t_end)

    # instants: point-in-time records worth a mark on their row
    _INSTANT_EVENTS = frozenset({
        E.JOB_SUBMITTED, E.SWEEP_INCUMBENT, E.LANE_ASSIGNED,
        E.LANE_RELEASED, E.WORKER_DISCOVERED, E.WORKER_DROPPED,
        E.CHECKPOINT_WRITTEN,
    })
    for rec in ordered:
        name = rec.get("event")
        if name not in _INSTANT_EVENTS:
            continue
        pid = pid_of(_proc_key(rec))
        tid = tid_of(pid, _row_of({"rec": rec, "row": None}))
        ev = {
            "ph": "i", "name": str(name), "cat": "event", "pid": pid,
            "tid": tid, "ts": us(normalized_time(rec, offsets)), "s": "t",
            "args": {
                k: rec[k] for k in _ARG_FIELDS
                if isinstance(rec.get(k), (str, int, float, bool))
            },
        }
        events.append(ev)
        slice_rows.setdefault(id(rec), (pid, tid))

    # flow arrows: follow each trace_id across rows; one s/f pair per
    # row transition, anchored at the two records that witnessed the hop
    flows = 0
    by_trace: Dict[str, List[Tuple[float, Dict[str, Any]]]] = {}
    for rec in ordered:
        tid_ = rec.get("trace_id")
        if isinstance(tid_, str) and tid_ and id(rec) in slice_rows:
            by_trace.setdefault(tid_, []).append(
                (normalized_time(rec, offsets), rec)
            )
    for trace_id, seq in sorted(by_trace.items()):
        seq.sort(key=lambda p: p[0])
        base_id = _flow_id(trace_id)
        hop = 0
        for (t_a, rec_a), (t_b, rec_b) in zip(seq, seq[1:]):
            row_a, row_b = slice_rows[id(rec_a)], slice_rows[id(rec_b)]
            if row_a == row_b:
                continue
            fid = base_id + hop
            hop += 1
            flows += 1
            events.append({
                "ph": "s", "id": fid, "name": "trace", "cat": "flow",
                "pid": row_a[0], "tid": row_a[1], "ts": us(t_a),
                "args": {"trace_id": trace_id},
            })
            events.append({
                "ph": "f", "bp": "e", "id": fid, "name": "trace",
                "cat": "flow", "pid": row_b[0], "tid": row_b[1],
                "ts": max(us(t_b), us(t_a) + 1),
                "args": {"trace_id": trace_id},
            })

    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0)))
    return {
        "traceEvents": events,
        "stats": {
            "records": len(ordered),
            "slices": sum(1 for e in events if e["ph"] == "X"),
            "flows": flows,
            "processes": len(pids),
            "rows": len(tids),
            "span_s": round(t_end - t_base, 6),
        },
    }


def to_chrome_trace(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event JSON for merged journal records — the dict to
    ``json.dump`` and open in Perfetto (chrome://tracing works too)."""
    built = build_timeline(records)
    return {
        "traceEvents": built["traceEvents"],
        "displayTimeUnit": "ms",
        "otherData": {"generator": "hpbandster_tpu obs timeline",
                      **built["stats"]},
    }


# ------------------------------------------------------------- critical path
def critical_path(
    records: Sequence[Dict[str, Any]],
    threshold: float = 0.95,
) -> Dict[str, Any]:
    """Attribute a journal's end-to-end wall-clock to the phase taxonomy.

    The attribution is a segment sweep, not a span sum: every elementary
    time segment between interval boundaries is charged to exactly one
    phase — the highest-priority phase active there (compute beats
    compile beats transfer ... beats admission) — or to ``unattributed``
    when no phase covers it. Phase seconds therefore partition the
    end-to-end span exactly: they can never double-count overlapping
    concurrent work, and their sum is <= the end-to-end span by
    construction. The ``verdict`` sub-dict is the machine-readable
    acceptance record bench.py persists next to the budget verdicts."""
    ordered, offsets = align_clocks(list(records))
    intervals = [
        iv for iv in _intervals(ordered, offsets) if iv["phase"] is not None
    ]
    times = [normalized_time(r, offsets) for r in ordered]
    times += [iv["t0"] for iv in intervals] + [iv["t1"] for iv in intervals]
    if not times:
        return {
            "end_to_end_s": 0.0, "phases": {}, "attributed_s": 0.0,
            "unattributed_s": 0.0, "attributed_share": None,
            "verdict": {"attributed_share": None,
                        "threshold": threshold, "ok": False},
        }
    t_lo, t_hi = min(times), max(times)
    phases = {p: 0.0 for p in PHASES}

    bounds = sorted(
        {t_lo, t_hi}
        | {min(max(iv["t0"], t_lo), t_hi) for iv in intervals}
        | {min(max(iv["t1"], t_lo), t_hi) for iv in intervals}
    )
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        active = [
            iv["phase"] for iv in intervals if iv["t0"] <= mid < iv["t1"]
        ]
        if active:
            winner = min(active, key=_PHASE_PRIORITY.__getitem__)
            phases[winner] += b - a

    end_to_end = t_hi - t_lo
    attributed = sum(phases.values())
    share = (attributed / end_to_end) if end_to_end > 0 else None
    return {
        "end_to_end_s": round(end_to_end, 6),
        "phases": {
            p: {
                "s": round(s, 6),
                "share": round(s / end_to_end, 4) if end_to_end > 0 else None,
            }
            for p, s in phases.items() if s > 0
        },
        "attributed_s": round(attributed, 6),
        "unattributed_s": round(max(end_to_end - attributed, 0.0), 6),
        "attributed_share": round(share, 4) if share is not None else None,
        "verdict": {
            "attributed_share": round(share, 4) if share is not None else None,
            "threshold": threshold,
            "ok": share is not None and share >= threshold,
        },
    }


def format_critical_path(cp: Dict[str, Any]) -> str:
    """Text table for one :func:`critical_path` result."""
    lines = [
        "critical path: %.6gs end-to-end, %.6gs attributed (%s)"
        % (
            cp.get("end_to_end_s", 0.0), cp.get("attributed_s", 0.0),
            (
                "%.1f%%" % (100.0 * cp["attributed_share"])
                if isinstance(cp.get("attributed_share"), (int, float))
                else "n/a"
            ),
        ),
        "  %-16s %12s %8s" % ("phase", "seconds", "share"),
    ]
    phases = cp.get("phases") or {}
    for p in sorted(phases, key=lambda p: -phases[p]["s"]):
        entry = phases[p]
        share = entry.get("share")
        lines.append(
            "  %-16s %12.6f %8s"
            % (
                p, entry["s"],
                "%.1f%%" % (100.0 * share)
                if isinstance(share, (int, float)) else "?",
            )
        )
    if _num(cp.get("unattributed_s")):
        lines.append(
            "  %-16s %12.6f" % ("(unattributed)", cp["unattributed_s"])
        )
    v = cp.get("verdict") or {}
    lines.append(
        "  verdict: %s (threshold %.0f%%)"
        % ("ok" if v.get("ok") else "BELOW THRESHOLD",
           100.0 * float(v.get("threshold", 0.95)))
    )
    return "\n".join(lines)
