"""Journal post-processing: ``python -m hpbandster_tpu.obs summarize``.

Reads one or MANY (possibly rotated) JSONL run journals — e.g. the
master's and each worker's — merges them by wall clock, and prints the
run's shape:

* **per-stage latencies** — p50/p95 over the ``queue_s`` (submitted ->
  started) and ``run_s`` (started -> finished) durations carried by
  ``job_finished``/``job_failed`` events, plus every span event's
  ``duration_s`` grouped by name (``kde_refit``, ``wave_evaluate``,
  ``sweep_chunk``, ...);
* **worker utilization** — per worker, busy seconds (sum of ``run_s``)
  over the journal's wall-clock window, with jobs/failures tallied;
* **failure tallies** — failed jobs, RPC retries, dropped workers,
  dead-lettered unknown results;
* **xla runtime** — compile count / seconds from ``xla_compile`` records
  (``obs/runtime.py``), the compile-time share of the journal's
  wall-clock window, and the top recompiling functions;
* **device telemetry** — the decoded in-trace metrics plane
  (``device_telemetry`` records, ``obs/device_metrics.py``): crash rate,
  per-rung loss quantiles and promotion counts for fused/resident sweeps
  whose per-job events never surfaced to host;
* **per-trace timelines** — records sharing a ``trace_id`` (one job's
  round-trip, see ``obs/trace.py``) joined across journals into a
  queue-wait -> dispatch -> compute -> delivery stage breakdown, with the
  set of hosts each trace touched.

Durations are computed at the EMITTING site from monotonic clocks and
carried in the events, so the summary never subtracts wall-clock stamps
(immune to clock jumps) and never compares monotonic clocks across
processes — the cross-host join is on ``trace_id``, and wall clock only
orders the display.

:func:`watch_journal` is the live counterpart: tail a journal as the run
writes it, rendering a one-line status per tick (survives rotation and a
not-yet-created file).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, TextIO, Tuple

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.journal import read_journal_ex

#: sink-free bus for CLI-side collectors (watch/top): a viewer must not
#: inject fleet_sample events into the process it happens to run inside
_NULL_BUS = E.EventBus()

__all__ = [
    "summarize_records", "format_summary", "summarize_path",
    "read_merged", "read_merged_ex", "trace_timelines", "watch_journal",
    "watch_snapshot", "make_viewer_collector",
]


def make_viewer_collector(uris: Sequence[str], interval: float) -> Any:
    """A CLI viewer's collector (``watch --snapshot`` / ``top``): private
    registry + sink-free bus, because a viewer must not publish the
    viewed fleet's gauges or ``fleet_sample`` events into whatever
    process it happens to run inside. Validates every URI up front — a
    malformed one can never succeed, so fail fast (``ValueError`` names
    the offending URI) instead of looping "waiting" forever on a typo.
    """
    # CLI-only imports: the obs substrate itself never pulls in the RPC
    # transport (health.py is deliberately transport-agnostic)
    from hpbandster_tpu.obs.collector import FleetCollector
    from hpbandster_tpu.obs.metrics import MetricsRegistry
    from hpbandster_tpu.parallel.rpc import parse_uri

    for u in uris:
        try:
            parse_uri(u)
        except ValueError as e:
            raise ValueError(f"invalid --snapshot URI {u!r}: {e}") from e
    return FleetCollector(
        endpoints=list(uris), interval_s=interval,
        timeout_s=max(interval, 2.0),
        registry=MetricsRegistry(), bus=_NULL_BUS,
    )

#: journal-record fields -> timeline stage names (the emitting sites:
#: dispatcher JOB_STARTED, worker JOB_FINISHED/JOB_FAILED, worker
#: RESULT_DELIVERED, master JOB_FINISHED/JOB_FAILED)
_STAGE_FIELDS = (
    ("queue_wait_s", "queue_wait_s"),
    ("dispatch_s", "dispatch_s"),
    ("compute_s", "compute_s"),
    ("delivery_s", "delivery_s"),
    ("run_s", "end_to_end_s"),
)

#: events both the master side and the worker side emit for the SAME job
#: (same trace_id) — counted once per (event, trace_id) in summaries
_JOB_LIFECYCLE_EVENTS = frozenset(
    {E.JOB_SUBMITTED, E.JOB_STARTED, E.JOB_FINISHED, E.JOB_FAILED}
)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        raise ValueError("no values")
    k = max(int(round(q * (len(sorted_vals) - 1))), 0)
    return sorted_vals[min(k, len(sorted_vals) - 1)]


def _stats(vals: Iterable[float]) -> Optional[Dict[str, Any]]:
    vals = sorted(float(v) for v in vals)
    if not vals:
        return None
    return {
        "count": len(vals),
        "p50": round(_percentile(vals, 0.50), 6),
        "p95": round(_percentile(vals, 0.95), 6),
        "max": round(vals[-1], 6),
        "total": round(sum(vals), 6),
    }


def read_merged_ex(paths: Sequence[str]) -> "Tuple[List[Dict[str, Any]], int]":
    """Records of N journals merged oldest-first by wall clock (the only
    cross-process ordering available; durations never derive from it),
    plus the total count of skipped corrupt/truncated lines."""
    records: List[Dict[str, Any]] = []
    skipped = 0
    for p in paths:
        recs, skip = read_journal_ex(p)
        records.extend(recs)
        skipped += skip
    records.sort(key=lambda r: r.get("t_wall") if isinstance(r.get("t_wall"), (int, float)) else 0.0)
    return records, skipped


def read_merged(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """:func:`read_merged_ex` without the skip count."""
    return read_merged_ex(paths)[0]


def trace_timelines(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Join records by ``trace_id`` into per-job timelines.

    Each timeline carries the stage durations measured at their emitting
    sites (queue wait and dispatch on the master/dispatcher side, compute
    and delivery on the worker side, end-to-end back on the master), the
    hosts that contributed records, retry/failure flags, and the journal
    wall-clock span. Cross-trace aggregates ride along as
    ``stage_latency_s``. Traces that ran on the fused/resident device
    path additionally carry a ``device`` section — chunk/rung/evaluation
    counts folded from their ``device_telemetry`` records — and a
    ``device_s`` stage accumulating the measured execute windows.
    """
    traces: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        tid = rec.get("trace_id")
        if not isinstance(tid, str) or not tid:
            continue
        slot = traces.setdefault(tid, {
            "trace_id": tid,
            "config_id": None,
            "events": 0,
            "hosts": set(),
            "stages": {},
            "retries": 0,
            "failed": False,
            "dead_lettered": False,
            "t_first": None,
            "t_last": None,
        })
        slot["events"] += 1
        tw = rec.get("t_wall")
        if isinstance(tw, (int, float)):
            slot["t_first"] = tw if slot["t_first"] is None else min(slot["t_first"], tw)
            slot["t_last"] = tw if slot["t_last"] is None else max(slot["t_last"], tw)
        host = rec.get("host")
        if host:
            slot["hosts"].add(str(host))
        if slot["config_id"] is None and rec.get("config_id") is not None:
            slot["config_id"] = rec["config_id"]
        name = rec.get("event")
        if name == E.RPC_RETRY:
            slot["retries"] += 1
        elif name == E.JOB_FAILED:
            slot["failed"] = True
        elif name == E.UNKNOWN_RESULT:
            slot["dead_lettered"] = True
        elif name == E.DEVICE_TELEMETRY:
            # a fused/resident sweep's device sections belong to its
            # trace: fold the decoded rung plane in so the timeline shows
            # where the device window went instead of a gap (device_s
            # accumulates — one record per chunk)
            ex = rec.get("execute_s")
            if isinstance(ex, (int, float)):
                slot["stages"]["device_s"] = (
                    slot["stages"].get("device_s", 0.0) + float(ex)
                )
            dev = slot.setdefault(
                "device", {"chunks": 0, "rungs": 0, "evaluations": 0}
            )
            dev["chunks"] += 1
            order = rec.get("rung_order")
            if isinstance(order, list):
                dev["rungs"] += len(order)
                dev["evaluations"] += sum(
                    int(e.get("evals", 0)) for e in order
                    if isinstance(e, dict)
                )
        for field, stage in _STAGE_FIELDS:
            v = rec.get(field)
            if isinstance(v, (int, float)):
                # keep the LAST occurrence: a requeued job's second
                # dispatch is the one that produced the result
                slot["stages"][stage] = float(v)

    timelines = []
    stage_vals: Dict[str, List[float]] = {}
    for slot in sorted(
        traces.values(), key=lambda s: (s["t_first"] is None, s["t_first"] or 0.0)
    ):
        slot["hosts"] = sorted(slot["hosts"])
        timelines.append(slot)
        for stage, v in slot["stages"].items():
            stage_vals.setdefault(stage, []).append(v)
    return {
        "count": len(timelines),
        "stage_latency_s": {
            stage: _stats(vals) for stage, vals in sorted(stage_vals.items())
        },
        "timelines": timelines,
    }


def summarize_records(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate journal records into the summary dict the CLI renders.

    Merged journals tell each job's story twice — the master/dispatcher
    side and the worker side both emit ``job_*`` under the same names —
    so job lifecycle counts are deduplicated on ``(event, trace_id)``:
    one job, one count, regardless of how many journals witnessed it.
    (Field extraction is NOT deduplicated: the master record carries
    ``queue_s``/``run_s``, the worker record ``compute_s`` — both feed
    the stage and timeline aggregates.)"""
    counts: Dict[str, int] = {}
    queue_s: List[float] = []
    run_s: List[float] = []
    spans: Dict[str, List[float]] = {}
    workers: Dict[str, Dict[str, float]] = {}
    t_wall_min: Optional[float] = None
    t_wall_max: Optional[float] = None
    seen_job_keys: set = set()
    #: host-link bill carried by sweep-level records (``sweep_chunk`` /
    #: ``sweep_incumbent`` stamp h2d_bytes/d2h_bytes/host_syncs)
    link = {"records": 0, "h2d_bytes": 0, "d2h_bytes": 0, "host_syncs": 0}
    #: device-telemetry records (obs/device_metrics.py): the decoded
    #: in-trace counters fused/resident sweeps journal instead of
    #: per-job events
    device_records: List[Dict[str, Any]] = []

    def worker_slot(name: str) -> Dict[str, float]:
        return workers.setdefault(
            name, {"busy_s": 0.0, "jobs": 0, "failed": 0}
        )

    for rec in records:
        name = rec.get("event")
        if not name:
            continue
        tid = rec.get("trace_id")
        if name in _JOB_LIFECYCLE_EVENTS and isinstance(tid, str) and tid:
            key = (name, tid)
            if key not in seen_job_keys:
                seen_job_keys.add(key)
                counts[name] = counts.get(name, 0) + 1
        else:
            counts[name] = counts.get(name, 0) + 1
        tw = rec.get("t_wall")
        if isinstance(tw, (int, float)):
            t_wall_min = tw if t_wall_min is None else min(t_wall_min, tw)
            t_wall_max = tw if t_wall_max is None else max(t_wall_max, tw)

        if name in (E.JOB_FINISHED, E.JOB_FAILED):
            q, r = rec.get("queue_s"), rec.get("run_s")
            if isinstance(q, (int, float)):
                queue_s.append(q)
            if isinstance(r, (int, float)):
                run_s.append(r)
            w = rec.get("worker")
            if w:
                slot = worker_slot(str(w))
                slot["jobs"] += 1
                if isinstance(r, (int, float)):
                    slot["busy_s"] += r
                if name == E.JOB_FAILED:
                    slot["failed"] += 1
        elif isinstance(rec.get("duration_s"), (int, float)):
            spans.setdefault(name, []).append(rec["duration_s"])
        if isinstance(rec.get("h2d_bytes"), (int, float)) or isinstance(
            rec.get("d2h_bytes"), (int, float)
        ):
            link["records"] += 1
            for field in ("h2d_bytes", "d2h_bytes", "host_syncs"):
                v = rec.get(field)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    link[field] += int(v)
        if name == E.DEVICE_TELEMETRY:
            device_records.append(rec)

    window_s = (
        (t_wall_max - t_wall_min)
        if t_wall_min is not None and t_wall_max is not None
        else 0.0
    )
    utilization = {}
    for wname, slot in sorted(workers.items()):
        utilization[wname] = {
            "jobs": int(slot["jobs"]),
            "failed": int(slot["failed"]),
            "busy_s": round(slot["busy_s"], 3),
            "utilization": (
                round(min(slot["busy_s"] / window_s, 1.0), 4)
                if window_s > 0 else None
            ),
        }

    stages: Dict[str, Any] = {}
    if queue_s:
        stages["queue"] = _stats(queue_s)
    if run_s:
        stages["run"] = _stats(run_s)
    for sname in sorted(spans):
        stages[sname] = _stats(spans[sname])

    # one shared aggregation with the report CLI (obs/runtime.py) — the
    # two views of the same journal must agree on compile economics
    from hpbandster_tpu.obs.runtime import compile_stats_from_records

    runtime = compile_stats_from_records(records, window_s)

    # same sharing rule for the device metrics plane: summarize and
    # report both render device_section_from_records' aggregation
    from hpbandster_tpu.obs.device_metrics import device_section_from_records

    device = device_section_from_records(device_records)

    return {
        "events_total": sum(counts.values()),
        "window_s": round(window_s, 3),
        "event_counts": dict(sorted(counts.items())),
        "stage_latency_s": stages,
        "worker_utilization": utilization,
        "runtime": runtime,
        # device<->host byte accounting, when any sweep-level record
        # carried it (the resident tier's flat-d2h evidence in journal form)
        "host_link": link if link["records"] else None,
        # decoded in-trace telemetry (obs/device_metrics.py) — the view
        # of sweeps whose per-job events never surfaced to host
        "device": device,
        "failures": {
            "jobs_failed": counts.get(E.JOB_FAILED, 0),
            "rpc_retries": counts.get(E.RPC_RETRY, 0),
            "workers_dropped": counts.get(E.WORKER_DROPPED, 0),
            "unknown_results_dead_lettered": counts.get(E.UNKNOWN_RESULT, 0),
        },
        "traces": trace_timelines(records),
    }


def summarize_path(path: "str | Sequence[str]") -> Dict[str, Any]:
    paths = [path] if isinstance(path, str) else list(path)
    return summarize_records(read_merged(paths))


def format_summary(s: Dict[str, Any]) -> str:
    lines = [
        f"events: {s['events_total']} over {s['window_s']}s",
        "",
        "stage latency (seconds):",
        f"  {'stage':<24} {'count':>6} {'p50':>10} {'p95':>10} {'max':>10}",
    ]
    for name, st in s["stage_latency_s"].items():
        lines.append(
            f"  {name:<24} {st['count']:>6} {st['p50']:>10.4f} "
            f"{st['p95']:>10.4f} {st['max']:>10.4f}"
        )
    if not s["stage_latency_s"]:
        lines.append("  (no duration-carrying events in this journal)")
    lines.append("")
    lines.append("worker utilization:")
    for wname, u in s["worker_utilization"].items():
        util = "n/a" if u["utilization"] is None else f"{100 * u['utilization']:.1f}%"
        lines.append(
            f"  {wname}: {u['jobs']} jobs ({u['failed']} failed), "
            f"busy {u['busy_s']}s, utilization {util}"
        )
    if not s["worker_utilization"]:
        lines.append("  (no worker-attributed jobs in this journal)")
    rt = s.get("runtime") or {}
    if rt.get("compiles"):
        lines.append("")
        share = rt.get("compile_share_of_wall")
        lines.append(
            "xla runtime: %d compiles, %.3fs compile time%s"
            % (
                rt["compiles"], rt["compile_s"],
                f" ({100 * share:.1f}% of wall)" if share is not None else "",
            )
        )
        for row in rt.get("top_recompilers") or []:
            lines.append(
                f"  {row['fn']}: {row['compiles']} compiles, "
                f"{row['compile_s']:.3f}s"
            )
    link = s.get("host_link")
    if link:
        lines.append("")
        lines.append(
            "host link: h2d %s, d2h %s over %d sweep record(s), "
            "%d host sync(s)"
            % (
                _fmt_bytes(link["h2d_bytes"]), _fmt_bytes(link["d2h_bytes"]),
                link["records"], link["host_syncs"],
            )
        )
    device = s.get("device")
    if device:
        from hpbandster_tpu.obs.device_metrics import format_device_section

        lines.append("")
        lines.extend(format_device_section(device))
    lines.append("")
    f = s["failures"]
    lines.append(
        "failures: %d jobs failed, %d rpc retries, %d workers dropped, "
        "%d unknown results dead-lettered"
        % (
            f["jobs_failed"], f["rpc_retries"],
            f["workers_dropped"], f["unknown_results_dead_lettered"],
        )
    )
    traces = s.get("traces") or {}
    if traces.get("count"):
        lines.append("")
        lines.append(f"trace timelines ({traces['count']} traces):")
        lines.append(
            f"  {'trace':<18} {'config':<12} {'queue_wait':>10} {'dispatch':>9} "
            f"{'compute':>9} {'delivery':>9} {'end_to_end':>10}  hosts"
        )

        def cell(st: Dict[str, Any], key: str) -> str:
            v = st.get(key)
            return f"{v:.4f}" if isinstance(v, (int, float)) else "-"

        shown = traces["timelines"][:_MAX_TIMELINE_ROWS]
        for t in shown:
            flags = "".join(
                mark for mark, on in (
                    ("!", t["failed"]), ("r", t["retries"] > 0),
                    ("d", t["dead_lettered"]),
                ) if on
            )
            st = t["stages"]
            lines.append(
                f"  {t['trace_id'] + flags:<18} {json.dumps(t['config_id']):<12} "
                f"{cell(st, 'queue_wait_s'):>10} {cell(st, 'dispatch_s'):>9} "
                f"{cell(st, 'compute_s'):>9} {cell(st, 'delivery_s'):>9} "
                f"{cell(st, 'end_to_end_s'):>10}  {','.join(t['hosts']) or '-'}"
            )
        if len(traces["timelines"]) > len(shown):
            lines.append(
                f"  ... {len(traces['timelines']) - len(shown)} more "
                "(use --json for all)"
            )
        lines.append("  per-stage across traces (p50/p95/max):")
        for stage, st in traces["stage_latency_s"].items():
            lines.append(
                f"    {stage:<14} {st['count']:>5} traces "
                f"{st['p50']:>10.4f} {st['p95']:>10.4f} {st['max']:>10.4f}"
            )
    lines.append("")
    lines.append("event counts: " + json.dumps(s["event_counts"]))
    return "\n".join(lines)


#: format_summary caps the per-trace table; --json carries every timeline
_MAX_TIMELINE_ROWS = 20


# ------------------------------------------------------------------ watch
class _WatchState:
    """Rolling tallies behind one status line of ``watch``."""

    def __init__(self) -> None:
        self.events = 0
        self.counts: Dict[str, int] = {}
        self.workers: set = set()
        self.last_name: Optional[str] = None
        self.last_t_wall: Optional[float] = None
        self.last_alert: Optional[str] = None
        self.skipped_lines = 0
        self._seen_job_keys: set = set()

    def update(self, rec: Dict[str, Any]) -> None:
        name = rec.get("event")
        if not name:
            return
        self.events += 1
        tid = rec.get("trace_id")
        if name in _JOB_LIFECYCLE_EVENTS and isinstance(tid, str) and tid:
            # both halves of a job journal under the same names — count
            # each (event, trace) once or in_flight goes negative
            key = (name, tid)
            if key not in self._seen_job_keys:
                self._seen_job_keys.add(key)
                self.counts[name] = self.counts.get(name, 0) + 1
        else:
            self.counts[name] = self.counts.get(name, 0) + 1
        w = rec.get("worker") or rec.get("worker_id")
        if w:
            self.workers.add(str(w))
        if name == E.ALERT:
            self.last_alert = (
                f"{rec.get('rule') or '?'}:{rec.get('subject') or '?'}"
            )
        self.last_name = name
        tw = rec.get("t_wall")
        if isinstance(tw, (int, float)):
            self.last_t_wall = float(tw)

    def line(self) -> str:
        c = self.counts
        submitted = c.get(E.JOB_SUBMITTED, 0)
        finished = c.get(E.JOB_FINISHED, 0)
        failed = c.get(E.JOB_FAILED, 0)
        in_flight = max(submitted - finished - failed, 0)
        if self.last_t_wall is not None:
            age = max(time.time() - self.last_t_wall, 0.0)  # graftlint: disable=wallclock-duration — journal records carry another process's wall stamps; monotonic does not compare across hosts
            last = f"{self.last_name} {age:.1f}s ago"
        else:
            last = "-"
        alerts = c.get(E.ALERT, 0)
        alert_part = (
            f" alerts={alerts}({self.last_alert})" if alerts else ""
        )
        compiles = c.get(E.XLA_COMPILE, 0)
        compile_part = f" compiles={compiles}" if compiles else ""
        skip_part = (
            f" skipped_lines={self.skipped_lines}" if self.skipped_lines else ""
        )
        return (
            f"events={self.events} submitted={submitted} finished={finished} "
            f"failed={failed} in_flight={in_flight} "
            f"workers={len(self.workers)} last={last}"
            f"{compile_part}{alert_part}{skip_part}"
        )


def watch_journal(
    path: str,
    interval: float = 2.0,
    ticks: Optional[int] = None,
    stream: Optional[TextIO] = None,
) -> int:
    """Tail a live journal, printing one status line per tick.

    ``ticks=None`` runs until interrupted (the CLI mode); tests pass a
    finite count. Tolerates a journal that does not exist yet (a run
    about to start) and follows through rotation (file shrank -> reopen
    from the top). Partial trailing lines are buffered, never mis-parsed.
    """
    out = stream if stream is not None else sys.stdout
    state = _WatchState()
    pos = 0
    buf = ""
    tick = 0
    while True:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = None
        if size is not None:
            if size < pos:  # rotated under us: the live file restarted
                pos, buf = 0, ""
            with open(path, "r", encoding="utf-8") as fh:
                fh.seek(pos)
                buf += fh.read()
                pos = fh.tell()
            lines = buf.split("\n")
            buf = lines.pop()  # tail w/o newline: kept for the next tick
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # torn/corrupt line: counted, never fatal — the tail
                    # of a crashing run is exactly when watch matters
                    state.skipped_lines += 1
                    continue
                if isinstance(rec, dict):
                    state.update(rec)
                else:
                    state.skipped_lines += 1
            status = state.line()
        else:
            status = f"(waiting for {path})"
        stamp = time.strftime("%H:%M:%S")
        print(f"[{stamp}] {status}", file=out, flush=True)
        tick += 1
        if ticks is not None and tick >= ticks:
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:  # graftlint: disable=swallowed-exception — ^C is the intended way to leave watch
            return 0


def _fmt_bytes(n: Any) -> str:
    if not isinstance(n, (int, float)):
        return "?"
    v = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0
    return f"{v:.1f}TiB"


def _snapshot_runtime_part(snap: Dict[str, Any]) -> str:
    """Render a health snapshot's ``runtime`` section for one watch line:
    compile tally + per-device memory/live-buffer gauges."""
    rt = snap.get("runtime")
    if not isinstance(rt, dict):
        return ""
    parts: List[str] = []
    compile_led = rt.get("compile") or {}
    if compile_led.get("total_compiles"):
        parts.append(
            "compiles=%d(%.1fs)"
            % (compile_led["total_compiles"], compile_led.get("total_compile_s") or 0.0)
        )
    devices = (rt.get("devices") or {}).get("devices") if rt.get("devices") else None
    if isinstance(devices, dict):
        for i in sorted(devices, key=lambda k: (len(k), k)):
            d = devices[i]
            if not isinstance(d, dict):
                continue
            if "bytes_in_use" in d and "bytes_limit" in d:
                mem = f"{_fmt_bytes(d['bytes_in_use'])}/{_fmt_bytes(d['bytes_limit'])}"
            elif "bytes_in_use" in d:
                mem = _fmt_bytes(d["bytes_in_use"])
            else:
                mem = f"{d.get('live_buffers', '?')}buf"
            parts.append(f"dev{i}={mem}")
    return (" runtime: " + " ".join(parts)) if parts else ""


def _snapshot_tenant_part(
    snap: Dict[str, Any], tenant: Optional[str] = None
) -> str:
    """The serving-tier slice of one watch line: per-tenant configs_done
    counters (serve/pool.py). No tenants, no part — single-tenant lines
    stay exactly as they were."""
    from hpbandster_tpu.obs.collector import tenant_counters

    counters = (snap.get("metrics") or {}).get("counters") or {}
    done = tenant_counters(counters)
    if tenant is not None:
        return f" tenant[{tenant}]: configs_done={done.get(tenant, 0)}"
    if not done:
        return ""
    return f" tenants={len(done)}(" + ",".join(
        f"{t}:{v}" for t, v in sorted(done.items())[:4]
    ) + (",..." if len(done) > 4 else "") + ")"


def _snapshot_lane_part(snap: Dict[str, Any]) -> str:
    """The continuous-batching slice of one watch line: lane occupancy,
    starved-lane count and program-warm age (``serve/continuous.py``
    gauges, read through the collector's one parser). No lanes, no part
    — lane-free processes' lines stay exactly as they were."""
    from hpbandster_tpu.obs.collector import lane_gauges

    lanes = lane_gauges((snap.get("metrics") or {}).get("gauges"))
    if not lanes:
        return ""
    parts = []
    if "occupied" in lanes or "total" in lanes:
        parts.append(
            "occ=%d/%d" % (
                int(lanes.get("occupied", 0)), int(lanes.get("total", 0))
            )
        )
    if "starved" in lanes:
        parts.append(f"starved={int(lanes['starved'])}")
    if "warm_age_s" in lanes:
        parts.append(f"warm_age={lanes['warm_age_s']:.1f}s")
    return (" lanes: " + " ".join(parts)) if parts else ""


def _snapshot_slo_part(snap: Dict[str, Any]) -> str:
    """The SLO slice of one watch line: worst burn rate + firing count
    (``obs/alerts.py`` gauges, read through the collector's one parser,
    ``slo_gauges``). No SLOs, no part — lines from manager-free
    processes stay exactly as they were."""
    from hpbandster_tpu.obs.collector import slo_gauges

    slo = slo_gauges((snap.get("metrics") or {}).get("gauges"))
    if not slo:
        return ""
    worst = slo.get("worst_burn_rate")
    return " slo: worst_burn={} firing={}".format(
        f"{worst:.2f}" if isinstance(worst, (int, float)) else "-",
        int(slo.get("firing", 0)),
    )


def _snapshot_device_part(snap: Dict[str, Any]) -> str:
    """The device-metrics-plane slice of one watch line: the last
    sweep's decoded in-trace counters (``sweep.device_metrics.*``
    gauges, obs/device_metrics.py). No telemetry, no part — lines from
    telemetry-free processes stay exactly as they were."""
    from hpbandster_tpu.obs.device_metrics import device_metric_fields

    dm = device_metric_fields((snap.get("metrics") or {}).get("gauges"))
    if not dm:
        return ""
    parts = []
    if "evaluations" in dm:
        parts.append(f"evals={int(dm['evaluations'])}")
    if "crashes" in dm:
        parts.append(f"crashed={int(dm['crashes'])}")
    if "crash_rate" in dm:
        parts.append(f"crash_rate={dm['crash_rate']:.4g}")
    if "rounds" in dm:
        parts.append(f"rounds={int(dm['rounds'])}")
    return (" device: " + " ".join(parts)) if parts else ""


def _snapshot_status_line(
    snap: Dict[str, Any], tenant: Optional[str] = None
) -> str:
    """One endpoint's watch line body from its ``obs_snapshot``."""
    up = snap.get("uptime_s")
    counters = (snap.get("metrics") or {}).get("counters") or {}
    lat = snap.get("latency") or {}
    lat_part = " ".join(
        f"{name}=p50:{v.get('p50'):g}/p95:{v.get('p95'):g}"
        for name, v in sorted(lat.items())
        if isinstance(v, dict)
        and isinstance(v.get("p50"), (int, float))
        and isinstance(v.get("p95"), (int, float))
    )
    alerts = snap.get("alerts") or {}
    return (
        f"{snap.get('component', '?')} up={up}s "
        f"in_flight={json.dumps(snap.get('in_flight'))} "
        f"counters={sum(counters.values())} "
        f"alerts={alerts.get('total', 0)}"
        + (f" latency: {lat_part}" if lat_part else "")
        + _snapshot_tenant_part(snap, tenant)
        + _snapshot_lane_part(snap)
        + _snapshot_slo_part(snap)
        + _snapshot_device_part(snap)
        + _snapshot_runtime_part(snap)
    )


def watch_snapshot(
    uri: "str | List[str]",
    interval: float = 2.0,
    ticks: Optional[int] = None,
    stream: Optional[TextIO] = None,
    tenant: Optional[str] = None,
) -> int:
    """Poll one or many live processes' ``obs_snapshot`` health RPCs —
    latency without a journal on disk.

    Renders each snapshot's histogram quantiles (the ``latency`` section
    :meth:`~hpbandster_tpu.obs.health.HealthEndpoint.snapshot` computes
    from the metrics registry), the in-flight work, and the anomaly
    alert tally. With several URIs (repeat ``--snapshot``), each tick
    prints one row per endpoint, merged through the fleet collector's
    poll/staleness machinery — an unreachable peer prints a waiting line
    (with its staleness once it has been seen at least once) and keeps
    polling; it may simply not be up yet, and one hung peer costs its
    own socket timeout, never the other rows.
    """
    uris = [uri] if isinstance(uri, str) else list(uri)
    out = stream if stream is not None else sys.stdout
    try:
        collector = make_viewer_collector(uris, interval)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    prefix_rows = len(uris) > 1
    tick = 0
    while True:
        collector.poll_once()
        states = collector.endpoint_states()
        snaps = collector.last_snapshots()
        stamp = time.strftime("%H:%M:%S")
        for name in sorted(states):
            st = states[name]
            snap = snaps.get(name)
            if st["ok"] and isinstance(snap, dict):
                status = _snapshot_status_line(snap, tenant)
            else:
                err = (st.get("error") or "?").split(":", 1)[0]
                stale_s = st.get("stale_s")
                stale_part = (
                    f", last seen {stale_s:.0f}s ago"
                    if isinstance(stale_s, (int, float)) else ""
                )
                status = (
                    f"(waiting for obs_snapshot at {st['uri']}: "
                    f"{err}{stale_part})"
                )
            row_prefix = f"{st['uri']} " if prefix_rows else ""
            print(f"[{stamp}] {row_prefix}{status}", file=out, flush=True)
        tick += 1
        if ticks is not None and tick >= ticks:
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:  # graftlint: disable=swallowed-exception — ^C is the intended way to leave watch
            return 0
