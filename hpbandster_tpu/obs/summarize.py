"""Journal post-processing: ``python -m hpbandster_tpu.obs summarize``.

Reads a (possibly rotated) JSONL run journal and prints the run's shape:

* **per-stage latencies** — p50/p95 over the ``queue_s`` (submitted ->
  started) and ``run_s`` (started -> finished) durations carried by
  ``job_finished``/``job_failed`` events, plus every span event's
  ``duration_s`` grouped by name (``kde_refit``, ``wave_evaluate``,
  ``sweep_chunk``, ...);
* **worker utilization** — per worker, busy seconds (sum of ``run_s``)
  over the journal's wall-clock window, with jobs/failures tallied;
* **failure tallies** — failed jobs, RPC retries, dropped workers,
  dead-lettered unknown results.

Durations are computed at the EMITTING site from monotonic clocks and
carried in the events, so the summary never subtracts wall-clock stamps
(immune to clock jumps) and never has to join event streams across
processes.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.journal import read_journal

__all__ = ["summarize_records", "format_summary", "summarize_path"]


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        raise ValueError("no values")
    k = max(int(round(q * (len(sorted_vals) - 1))), 0)
    return sorted_vals[min(k, len(sorted_vals) - 1)]


def _stats(vals: Iterable[float]) -> Optional[Dict[str, Any]]:
    vals = sorted(float(v) for v in vals)
    if not vals:
        return None
    return {
        "count": len(vals),
        "p50": round(_percentile(vals, 0.50), 6),
        "p95": round(_percentile(vals, 0.95), 6),
        "max": round(vals[-1], 6),
        "total": round(sum(vals), 6),
    }


def summarize_records(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate journal records into the summary dict the CLI renders."""
    counts: Dict[str, int] = {}
    queue_s: List[float] = []
    run_s: List[float] = []
    spans: Dict[str, List[float]] = {}
    workers: Dict[str, Dict[str, float]] = {}
    t_wall_min: Optional[float] = None
    t_wall_max: Optional[float] = None

    def worker_slot(name: str) -> Dict[str, float]:
        return workers.setdefault(
            name, {"busy_s": 0.0, "jobs": 0, "failed": 0}
        )

    for rec in records:
        name = rec.get("event")
        if not name:
            continue
        counts[name] = counts.get(name, 0) + 1
        tw = rec.get("t_wall")
        if isinstance(tw, (int, float)):
            t_wall_min = tw if t_wall_min is None else min(t_wall_min, tw)
            t_wall_max = tw if t_wall_max is None else max(t_wall_max, tw)

        if name in (E.JOB_FINISHED, E.JOB_FAILED):
            q, r = rec.get("queue_s"), rec.get("run_s")
            if isinstance(q, (int, float)):
                queue_s.append(q)
            if isinstance(r, (int, float)):
                run_s.append(r)
            w = rec.get("worker")
            if w:
                slot = worker_slot(str(w))
                slot["jobs"] += 1
                if isinstance(r, (int, float)):
                    slot["busy_s"] += r
                if name == E.JOB_FAILED:
                    slot["failed"] += 1
        elif isinstance(rec.get("duration_s"), (int, float)):
            spans.setdefault(name, []).append(rec["duration_s"])

    window_s = (
        (t_wall_max - t_wall_min)
        if t_wall_min is not None and t_wall_max is not None
        else 0.0
    )
    utilization = {}
    for wname, slot in sorted(workers.items()):
        utilization[wname] = {
            "jobs": int(slot["jobs"]),
            "failed": int(slot["failed"]),
            "busy_s": round(slot["busy_s"], 3),
            "utilization": (
                round(min(slot["busy_s"] / window_s, 1.0), 4)
                if window_s > 0 else None
            ),
        }

    stages: Dict[str, Any] = {}
    if queue_s:
        stages["queue"] = _stats(queue_s)
    if run_s:
        stages["run"] = _stats(run_s)
    for sname in sorted(spans):
        stages[sname] = _stats(spans[sname])

    return {
        "events_total": sum(counts.values()),
        "window_s": round(window_s, 3),
        "event_counts": dict(sorted(counts.items())),
        "stage_latency_s": stages,
        "worker_utilization": utilization,
        "failures": {
            "jobs_failed": counts.get(E.JOB_FAILED, 0),
            "rpc_retries": counts.get(E.RPC_RETRY, 0),
            "workers_dropped": counts.get(E.WORKER_DROPPED, 0),
            "unknown_results_dead_lettered": counts.get(E.UNKNOWN_RESULT, 0),
        },
    }


def summarize_path(path: str) -> Dict[str, Any]:
    return summarize_records(read_journal(path))


def format_summary(s: Dict[str, Any]) -> str:
    lines = [
        f"events: {s['events_total']} over {s['window_s']}s",
        "",
        "stage latency (seconds):",
        f"  {'stage':<24} {'count':>6} {'p50':>10} {'p95':>10} {'max':>10}",
    ]
    for name, st in s["stage_latency_s"].items():
        lines.append(
            f"  {name:<24} {st['count']:>6} {st['p50']:>10.4f} "
            f"{st['p95']:>10.4f} {st['max']:>10.4f}"
        )
    if not s["stage_latency_s"]:
        lines.append("  (no duration-carrying events in this journal)")
    lines.append("")
    lines.append("worker utilization:")
    for wname, u in s["worker_utilization"].items():
        util = "n/a" if u["utilization"] is None else f"{100 * u['utilization']:.1f}%"
        lines.append(
            f"  {wname}: {u['jobs']} jobs ({u['failed']} failed), "
            f"busy {u['busy_s']}s, utilization {util}"
        )
    if not s["worker_utilization"]:
        lines.append("  (no worker-attributed jobs in this journal)")
    lines.append("")
    f = s["failures"]
    lines.append(
        "failures: %d jobs failed, %d rpc retries, %d workers dropped, "
        "%d unknown results dead-lettered"
        % (
            f["jobs_failed"], f["rpc_retries"],
            f["workers_dropped"], f["unknown_results_dead_lettered"],
        )
    )
    lines.append("")
    lines.append("event counts: " + json.dumps(s["event_counts"]))
    return "\n".join(lines)
