"""Prometheus-compatible metrics exporter (text exposition format 0.0.4).

The fleet's metrics were only reachable through the bespoke
``obs_snapshot`` RPC; this module renders the same atomic
:meth:`~hpbandster_tpu.obs.metrics.MetricsRegistry.snapshot` as the
strict Prometheus text exposition format any standard scraper ingests:

* counters as ``<family>_total`` with ``# HELP`` / ``# TYPE`` lines;
* gauges verbatim;
* histograms as ``_count`` / ``_sum`` / ``_p50`` / ``_p95`` gauges (the
  quantiles the registry already computes — bucket upper bounds,
  conservative by design);
* dotted registry names flatten to legal metric names, and the
  per-entity families this repo mints dynamically (per-function compile
  counters, per-device gauges, per-worker ages, per-rule alert tallies)
  become proper labeled families with correct label escaping.

Rendering is deterministic: families sort by name, samples by label
string, values format identically call to call — two scrapes of a frozen
registry are byte-identical (pinned by ``tests/test_export.py`` through
the strict round-trip parser :func:`parse_prometheus_text`). Non-finite
values never render (Prometheus accepts NaN; our exposition contract is
NaN-free because every NaN this repo produces is a bug signal that
belongs in the anomaly pipeline, not a scrape).

Serving:

* every :class:`~hpbandster_tpu.obs.health.HealthEndpoint` registers a
  ``metrics_text`` RPC method returning this exposition, so any fleet
  process can be scraped through its existing health port;
* ``python -m hpbandster_tpu.obs export --port N`` runs a standalone
  HTTP exporter serving ``GET /metrics`` — either this process's own
  registry or, with ``--snapshot host:port``, a bridge that polls a
  fleet peer's ``obs_snapshot`` RPC per scrape and re-renders it (the
  Prometheus-side adapter for workers/dispatchers that only speak the
  repo's JSON-RPC).
"""

from __future__ import annotations

import http.server
import json
import logging
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from hpbandster_tpu.obs.metrics import MetricsRegistry, get_metrics

__all__ = [
    "render_snapshot",
    "render_registry",
    "parse_prometheus_text",
    "metric_family",
    "ExporterServer",
    "serve",
    "CONTENT_TYPE",
]

logger = logging.getLogger("hpbandster_tpu.obs")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: default family prefix: every exported metric is namespaced so a shared
#: Prometheus cannot collide with another job's vocabulary
DEFAULT_NAMESPACE = "hpbandster"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")

#: dynamic per-entity registry names -> (family, labels). Everything the
#: repo mints with an entity baked into the dotted name is re-expressed
#: as one labeled family, the idiom scrapers can aggregate over.
#: DOTALL: entity names (worker ids especially) may carry any byte — the
#: label value escaping handles them, so the match must not stop at \n.
#: The third element is a label key (single-label rules: the group is
#: named ``label``) or a tuple of keys (multi-label rules: groups named
#: after the keys themselves).
_LABEL_RULES: Tuple[Tuple[re.Pattern, str, object], ...] = (
    (re.compile(r"^runtime\.device\.(?P<label>\d+)\.(?P<field>[a-z_]+)$"),
     "runtime_device_{field}", "device"),
    # sharded-sweep balance gauges (parallel/multihost.py
    # publish_device_balance): sweep.device.<id>.configs ->
    # sweep_device_configs{device="<id>"} — the per-device config-count /
    # padding family fleet.device_compute_skew is derived from
    (re.compile(r"^sweep\.device\.(?P<label>\d+)\.(?P<field>[a-z_]+)$"),
     "sweep_device_{field}", "device"),
    # per-sweep host-link byte gauges (obs/runtime.py
    # publish_sweep_transfers): sweep.transfer_bytes.h2d ->
    # sweep_transfer_bytes{direction="h2d"} — one labeled family so a
    # scraper can plot both directions on one panel; the resident
    # sweep's flat-d2h acceptance reads this gauge
    (re.compile(r"^sweep\.transfer_bytes\.(?P<label>h2d|d2h)$"),
     "sweep_transfer_bytes", "direction"),
    # device metrics plane (obs/device_metrics.py publish_device_metrics):
    # sweep.rung.<budget>.loss_p95 -> sweep_rung_loss_p95{budget="..."} —
    # per-rung crash/eval/promotion counts and loss quantiles decoded
    # from the in-trace telemetry pytree. Greedy label + dot-free field:
    # a budget rendered with a dot (0.5) keeps it in the label, the LAST
    # dot separates the field (the serve-tenant idiom).
    (re.compile(
        r"^sweep\.rung\.(?P<label>.+)\.(?P<field>[a-zA-Z0-9_]+)$",
        re.DOTALL),
     "sweep_rung_{field}", "budget"),
    # per-budget evaluation-cost estimate derived from device telemetry —
    # the gauge half of the Pareto cost feed (budget_cost_from_obs)
    (re.compile(r"^sweep\.budget_cost_s\.(?P<label>.+)$", re.DOTALL),
     "sweep_budget_cost_s", "budget"),
    # the master's budget-keyed evaluation-time histograms (the histogram
    # half of the cost feed): master.job_run_s.b<budget> histogram
    # families label by budget instead of minting one family per budget
    (re.compile(r"^master\.job_run_s\.b(?P<label>.+)$", re.DOTALL),
     "master_job_run_s_budget", "budget"),
    (re.compile(r"^runtime\.compiles\.(?P<label>.+)$", re.DOTALL),
     "runtime_fn_compiles", "fn"),
    # roofline/cost families (obs/runtime.py _TrackedLowered cost
    # analysis): per-program FLOPs and bytes re-expressed as one labeled
    # family each, so a scraper can sum/aggregate across functions
    (re.compile(r"^runtime\.flops\.(?P<label>.+)$", re.DOTALL),
     "runtime_fn_flops", "fn"),
    (re.compile(r"^runtime\.bytes_accessed\.(?P<label>.+)$", re.DOTALL),
     "runtime_fn_bytes_accessed", "fn"),
    (re.compile(r"^anomaly\.alerts\.(?P<label>.+)$", re.DOTALL),
     "anomaly_rule_alerts", "rule"),
    # promotion-rule counters (obs/audit.py emit_bracket_promotion):
    # bracket.promotions.<rule>.<rung> ->
    # bracket_promotions{rule="<rule>", rung="<rung>"}. The greedy rule
    # group + the digits-only rung tail means a rule name containing
    # dots keeps them in the label (the LAST dot separates the rung).
    (re.compile(
        r"^bracket\.promotions\.(?P<rule>.+)\.(?P<rung>\d+)$", re.DOTALL),
     "bracket_promotions", ("rule", "rung")),
    (re.compile(
        r"^dispatcher\.worker_last_seen_age_s\.(?P<label>.+)$", re.DOTALL),
     "dispatcher_worker_last_seen_age_s", "worker"),
    # per-tenant serving-tier families (serve/pool.py, serve/frontend.py):
    # serve.tenant.<tenant>.configs_done -> serve_tenant_configs_done
    # {tenant="<tenant>"}. The greedy label group + the dot-free field
    # group means a tenant id containing dots keeps them in the label
    # (the LAST dot separates the field); any byte is legal in the label
    # value via the exposition escaping.
    (re.compile(
        r"^serve\.tenant\.(?P<label>.+)\.(?P<field>[a-zA-Z0-9_]+)$",
        re.DOTALL),
     "serve_tenant_{field}", "tenant"),
    # SLO gauges (obs/alerts.py AlertManager): slo.<name>.burn_rate ->
    # slo_burn_rate{slo="<name>"} — one labeled family per field so a
    # scraper alerts on max(slo_burn_rate) across specs. Greedy label +
    # dot-free field (the serve-tenant idiom): a spec name containing
    # dots keeps them in the label, the LAST dot separates the field.
    (re.compile(
        r"^slo\.(?P<label>.+)\.(?P<field>[a-zA-Z0-9_]+)$",
        re.DOTALL),
     "slo_{field}", "slo"),
    # alert lifecycle counters (obs/alerts.py): alert.transitions.<slo>
    # -> slo_alert_transitions{slo="<slo>"} — a DISTINCT family from the
    # flattened global alert.transitions / alert.firing totals (the
    # anomaly_rule_alerts idiom: per-entity and global tallies must not
    # share one exposition family).
    (re.compile(r"^alert\.transitions\.(?P<label>.+)$", re.DOTALL),
     "slo_alert_transitions", "slo"),
)


def _sanitize(name: str) -> str:
    out = _SANITIZE.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def metric_family(name: str, namespace: str = DEFAULT_NAMESPACE) -> Tuple[str, Dict[str, str]]:
    """Registry name -> (exposition family, labels). Dotted names flatten
    (``dispatcher.queue_depth`` -> ``hpbandster_dispatcher_queue_depth``);
    per-entity names matching a label rule become labeled families."""
    for pattern, family_tmpl, label_key in _LABEL_RULES:
        m = pattern.match(name)
        if m is not None:
            groups = m.groupdict()
            if isinstance(label_key, str):
                labels = {label_key: groups["label"]}
                label_groups = {"label"}
            else:  # multi-label rule: groups are named after the keys
                labels = {k: groups[k] for k in label_key}
                label_groups = set(label_key)
            family = family_tmpl.format(
                **{
                    k: _sanitize(v)
                    for k, v in groups.items() if k not in label_groups
                }
            )
            prefix = f"{namespace}_" if namespace else ""
            return prefix + _sanitize(family), labels
    prefix = f"{namespace}_" if namespace else ""
    return prefix + _sanitize(name), {}


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: Any) -> Optional[str]:
    """Deterministic sample value, or None for values that must not
    render (non-finite, non-numeric)."""
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        if v != v or v in (float("inf"), float("-inf")):
            return None
        return repr(v)
    return None


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_sanitize(k)}="{_escape_label_value(str(v))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_snapshot(
    snap: Dict[str, Dict[str, Any]],
    namespace: str = DEFAULT_NAMESPACE,
) -> str:
    """Render one ``MetricsRegistry.snapshot()`` dict as the strict text
    exposition. Counters gain the conventional ``_total`` suffix;
    histograms flatten to ``_count``/``_sum``/``_p50``/``_p95`` gauges."""
    #: family -> {"type": str, "help": str, "samples": [(labels, value)]}
    families: Dict[str, Dict[str, Any]] = {}

    def add(family: str, mtype: str, help_text: str,
            labels: Dict[str, str], value: Any) -> None:
        rendered = _fmt_value(value)
        if rendered is None:
            return
        slot = families.setdefault(
            family, {"type": mtype, "help": help_text, "samples": []}
        )
        if slot["type"] != mtype:
            # a label rule folded two registry kinds into one family name;
            # first kind wins, the straggler is dropped loudly
            logger.warning(
                "metric family %s seen as both %s and %s; dropping the %s sample",
                family, slot["type"], mtype, mtype,
            )
            return
        slot["samples"].append((labels, rendered))

    for name, value in (snap.get("counters") or {}).items():
        family, labels = metric_family(name, namespace)
        add(
            family + "_total", "counter",
            f"hpbandster_tpu counter {name!r}", labels, value,
        )
    for name, value in (snap.get("gauges") or {}).items():
        family, labels = metric_family(name, namespace)
        add(family, "gauge", f"hpbandster_tpu gauge {name!r}", labels, value)
    for name, h in (snap.get("histograms") or {}).items():
        family, labels = metric_family(name, namespace)
        base_help = f"hpbandster_tpu histogram {name!r}"
        add(family + "_count", "gauge", base_help + " (observations)",
            labels, h.get("count"))
        add(family + "_sum", "gauge", base_help + " (sum)",
            labels, h.get("sum"))
        add(family + "_p50", "gauge",
            base_help + " (p50, bucket upper bound)", labels, h.get("p50"))
        add(family + "_p95", "gauge",
            base_help + " (p95, bucket upper bound)", labels, h.get("p95"))

    lines: List[str] = []
    for family in sorted(families):
        slot = families[family]
        if not _NAME_OK.match(family):  # defense in depth; _sanitize upholds it
            logger.warning("skipping illegal metric family %r", family)
            continue
        lines.append(f"# HELP {family} {_escape_help(slot['help'])}")
        lines.append(f"# TYPE {family} {slot['type']}")
        for labels, rendered in sorted(
            slot["samples"], key=lambda s: _label_str(s[0])
        ):
            lines.append(f"{family}{_label_str(labels)} {rendered}")
    return "\n".join(lines) + "\n" if lines else ""


def render_registry(
    registry: Optional[MetricsRegistry] = None,
    namespace: str = DEFAULT_NAMESPACE,
) -> str:
    """Render a registry (default: the process-wide one) — one atomic
    snapshot, then pure formatting."""
    reg = registry if registry is not None else get_metrics()
    return render_snapshot(reg.snapshot(), namespace=namespace)


# --------------------------------------------------------------- strict parse
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)$"
)


def _parse_labels(raw: str, line: str) -> Dict[str, str]:
    """Parse the ``k="v",...`` label body with escape handling; raises
    ``ValueError`` on any deviation from the exposition grammar."""
    labels: Dict[str, str] = {}
    i = 0
    n = len(raw)
    while i < n:
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', raw[i:])
        if m is None:
            raise ValueError(f"malformed label body at {raw[i:]!r} in {line!r}")
        key = m.group(1)
        i += m.end()
        value_chars: List[str] = []
        while True:
            if i >= n:
                raise ValueError(f"unterminated label value in {line!r}")
            c = raw[i]
            if c == "\\":
                if i + 1 >= n:
                    raise ValueError(f"dangling escape in {line!r}")
                nxt = raw[i + 1]
                if nxt == "n":
                    value_chars.append("\n")
                elif nxt in ("\\", '"'):
                    value_chars.append(nxt)
                else:
                    raise ValueError(f"illegal escape \\{nxt} in {line!r}")
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                value_chars.append(c)
                i += 1
        if key in labels:
            raise ValueError(f"duplicate label {key!r} in {line!r}")
        labels[key] = "".join(value_chars)
        if i < n:
            if raw[i] != ",":
                raise ValueError(f"expected ',' between labels in {line!r}")
            i += 1
    return labels


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, Any]]:
    """Strict parser for the exposition this module renders.

    Returns ``{family: {"type", "help", "samples": [(labels, value)]}}``.
    Raises ``ValueError`` on: missing trailing newline, samples before
    their ``# TYPE``, interleaved (non-contiguous) families, malformed
    names/labels/escapes, duplicate samples, or non-finite values — the
    test-suite contract that keeps the renderer honest."""
    if text and not text.endswith("\n"):
        raise ValueError("exposition must end with a newline")
    families: Dict[str, Dict[str, Any]] = {}
    closed: set = set()
    current: Optional[str] = None
    for line in text.splitlines():
        if not line:
            raise ValueError("blank line inside exposition")
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if not _NAME_OK.match(name):
                raise ValueError(f"illegal family name in {line!r}")
            if name in families or name in closed:
                raise ValueError(f"duplicate HELP for {name!r}")
            if current is not None:
                closed.add(current)
            families[name] = {"type": None, "help": help_text, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, mtype = rest.partition(" ")
            if name != current:
                raise ValueError(f"TYPE for {name!r} outside its block: {line!r}")
            if mtype not in ("counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"unknown metric type in {line!r}")
            families[name]["type"] = mtype
            continue
        if line.startswith("#"):
            continue  # comments are legal exposition
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed sample line {line!r}")
        name = m.group("name")
        base = name
        # counter samples carry the family name verbatim (_total included)
        if base not in families:
            raise ValueError(f"sample {name!r} before its HELP/TYPE block")
        if base != current:
            raise ValueError(f"family {base!r} is not contiguous at {line!r}")
        if families[base]["type"] is None:
            raise ValueError(f"sample for {base!r} before its TYPE line")
        labels = _parse_labels(m.group("labels") or "", line) if m.group("labels") else {}
        try:
            value = float(m.group("value"))
        except ValueError:
            raise ValueError(f"unparseable value in {line!r}")
        if value != value or value in (float("inf"), float("-inf")):
            raise ValueError(f"non-finite value in {line!r}")
        key = tuple(sorted(labels.items()))
        if any(tuple(sorted(l.items())) == key for l, _ in families[base]["samples"]):
            raise ValueError(f"duplicate sample {line!r}")
        families[base]["samples"].append((labels, value))
    return families


# ------------------------------------------------------------------- serving
class ExporterServer:
    """Standalone HTTP exporter: ``GET /metrics`` renders ``fetch()``.

    ``fetch`` returns the exposition string per scrape — the local
    registry by default, or a bridge closure that polls a fleet peer's
    ``obs_snapshot``. A fetch failure answers 503 with the error text
    (a scraper marks the target down instead of ingesting garbage).
    """

    def __init__(
        self,
        port: int,
        fetch: Optional[Callable[[], str]] = None,
        host: str = "127.0.0.1",
    ):
        self.fetch = fetch if fetch is not None else render_registry
        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (stdlib API name)
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = exporter.fetch().encode("utf-8")
                except Exception as e:
                    msg = f"scrape failed: {type(e).__name__}: {e}\n".encode()
                    self.send_response(503)
                    self.send_header("Content-Type", "text/plain; charset=utf-8")
                    self.send_header("Content-Length", str(len(msg)))
                    self.end_headers()
                    self.wfile.write(msg)
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt: str, *args: Any) -> None:
                logger.debug("exporter: " + fmt, *args)

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    def start(self) -> "ExporterServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="obs-exporter"
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def close(self) -> None:
        # only a background start() needs the cross-thread shutdown
        # handshake; shutting down a server that never served would block
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()


def snapshot_fetcher(uri: str, timeout: float = 5.0) -> Callable[[], str]:
    """A fetch closure bridging a fleet peer: each scrape calls the
    peer's ``obs_snapshot`` RPC and renders its metrics section."""
    # CLI-only import: the obs substrate never pulls in the RPC transport
    from hpbandster_tpu.parallel.rpc import RPCProxy

    def fetch() -> str:
        snap = RPCProxy(uri, timeout=timeout).call("obs_snapshot")
        metrics = (snap or {}).get("metrics")
        if not isinstance(metrics, dict):
            raise ValueError(f"obs_snapshot from {uri} has no metrics section")
        return render_snapshot(metrics)

    return fetch


def serve(
    port: int,
    snapshot_uri: Optional[str] = None,
    host: str = "127.0.0.1",
) -> ExporterServer:
    """Build + start a background :class:`ExporterServer`; the CLI's
    foreground mode calls ``serve_forever`` on the returned object."""
    fetch = snapshot_fetcher(snapshot_uri) if snapshot_uri else None
    return ExporterServer(port, fetch=fetch, host=host).start()
