"""CLI: ``python -m hpbandster_tpu.obs summarize <journal> [--json]``.

Exit codes: 0 success, 2 usage error / unreadable journal.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from hpbandster_tpu.obs.journal import journal_paths, read_journal
from hpbandster_tpu.obs.summarize import format_summary, summarize_records


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hpbandster_tpu.obs",
        description="observability tooling (see docs/observability.md)",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    p_sum = sub.add_parser(
        "summarize",
        help="per-stage latency percentiles, worker utilization, failures",
    )
    p_sum.add_argument("journal", help="path to a JSONL run journal")
    p_sum.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the summary as JSON instead of text",
    )
    args = parser.parse_args(argv)

    if not os.path.exists(args.journal) and not journal_paths(args.journal):
        print(f"error: journal {args.journal!r} does not exist", file=sys.stderr)
        return 2
    summary = summarize_records(read_journal(args.journal))
    if args.as_json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
