"""CLI: ``python -m hpbandster_tpu.obs <command>``.

* ``summarize <journal> [<journal> ...] [--json]`` — merge one or many
  (possibly rotated) journals by wall clock; print per-stage latency
  percentiles, worker utilization, failure tallies, and the merged
  per-trace timelines (queue wait -> dispatch -> compute -> delivery).
* ``report <journal> [<journal> ...] [--json]`` — the optimizer-decision
  view (``obs/report.py``): incumbent trajectory, model-vs-random win
  rate, per-rung promotion regret, bracket utilization, alert digest.
  Deterministic: two invocations over the same journals are
  byte-identical.
* ``watch <journal> [--interval S] [--ticks N]`` — tail a live journal,
  one status line per tick; runs until ^C unless ``--ticks`` bounds it.
  ``watch --snapshot <uri>`` polls a live process's ``obs_snapshot``
  health RPC instead — latency quantiles, compile counts, and device
  memory with no journal on disk.
* ``export --port N [--snapshot <uri>] [--host H]`` — standalone
  Prometheus exporter (``obs/export.py``): serves ``GET /metrics`` in
  the strict text exposition format, rendering this process's registry
  or, with ``--snapshot``, bridging a fleet peer's ``obs_snapshot`` RPC
  per scrape. ``export --once`` prints one exposition to stdout and
  exits (the curl-equivalent for pipelines and tests).

Corrupt/truncated JSONL lines are skipped with a counted stderr warning,
never fatal (a post-mortem reader must survive the crash it documents).
Exit codes: 0 success, 2 usage error / missing journal.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from hpbandster_tpu.obs.journal import journal_paths
from hpbandster_tpu.obs.report import build_report, format_report
from hpbandster_tpu.obs.summarize import (
    format_summary,
    read_merged_ex,
    summarize_records,
    watch_journal,
    watch_snapshot,
)


def _missing_journals(paths: List[str]) -> List[str]:
    return [
        p for p in paths
        if not os.path.exists(p) and not journal_paths(p)
    ]


def _read_checked(paths: List[str]) -> Optional[list]:
    """Merged records, or None (after a clear stderr message) when any
    journal is missing; corrupt lines are counted and warned about."""
    missing = _missing_journals(paths)
    if missing:
        print(
            f"error: journal(s) {', '.join(repr(p) for p in missing)} do not exist",
            file=sys.stderr,
        )
        return None
    records, skipped = read_merged_ex(paths)
    if skipped:
        print(
            f"warning: skipped {skipped} corrupt/truncated journal line(s)",
            file=sys.stderr,
        )
    return records


def run_export(
    port: int,
    host: str = "127.0.0.1",
    snapshot_uri: Optional[str] = None,
    once: bool = False,
) -> int:
    """The ``export`` subcommand body (separated so tests drive it)."""
    from hpbandster_tpu.obs.export import (
        ExporterServer,
        render_registry,
        snapshot_fetcher,
    )

    if snapshot_uri is not None:
        from hpbandster_tpu.parallel.rpc import parse_uri

        try:
            # a malformed URI can never succeed: fail fast as usage error
            parse_uri(snapshot_uri)
        except ValueError as e:
            print(
                f"error: invalid --snapshot URI {snapshot_uri!r}: {e}",
                file=sys.stderr,
            )
            return 2
        fetch = snapshot_fetcher(snapshot_uri)
    else:
        fetch = render_registry
    if once:
        try:
            sys.stdout.write(fetch())
        except Exception as e:
            print(f"error: scrape failed: {e}", file=sys.stderr)
            return 1
        return 0
    try:
        # positional: the obs-reserved-fields rule reserves `host=` kwargs
        # on obs-resolving calls for the identity stamp; this is a bind
        # address
        server = ExporterServer(port, fetch, host)
    except OSError as e:
        # port in use / privileged port / bad bind address: the CLI
        # contract is a clear message + exit 2, never a raw traceback
        print(
            f"error: cannot bind exporter to {host}:{port}: {e}",
            file=sys.stderr,
        )
        return 2
    print(
        f"serving /metrics on http://{host}:{server.port} "
        + (f"(bridging obs_snapshot at {snapshot_uri})" if snapshot_uri
           else "(local registry)"),
        file=sys.stderr,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # graftlint: disable=swallowed-exception — ^C is the intended way to stop the exporter
        pass
    finally:
        server.close()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hpbandster_tpu.obs",
        description="observability tooling (see docs/observability.md)",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    p_sum = sub.add_parser(
        "summarize",
        help="per-stage latency percentiles, worker utilization, failures, "
        "and merged per-trace timelines",
    )
    p_sum.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — e.g. the master's and each worker's",
    )
    p_sum.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the summary as JSON instead of text",
    )
    p_rep = sub.add_parser(
        "report",
        help="optimizer decision report: incumbent trajectory, "
        "model-vs-random win rate, promotion regret, alert digest",
    )
    p_rep.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — merged before analysis",
    )
    p_rep.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the report as JSON instead of text",
    )
    p_watch = sub.add_parser(
        "watch", help="tail a live journal (or poll a health RPC), "
        "one status line per tick"
    )
    p_watch.add_argument(
        "journal", nargs="?", default=None,
        help="path to a (possibly future) journal",
    )
    p_watch.add_argument(
        "--snapshot", metavar="URI", default=None,
        help="poll obs_snapshot on this RPC endpoint (host:port) instead "
        "of tailing a journal — latency quantiles without a journal",
    )
    p_watch.add_argument(
        "--interval", type=float, default=2.0, help="seconds between ticks"
    )
    p_watch.add_argument(
        "--ticks", type=int, default=None,
        help="stop after N ticks (default: run until ^C)",
    )
    p_exp = sub.add_parser(
        "export",
        help="Prometheus exporter: serve GET /metrics in the strict text "
        "exposition format (see docs/observability.md 'Scraping the fleet')",
    )
    p_exp.add_argument(
        "--port", type=int, default=9090,
        help="HTTP port to serve /metrics on (default 9090)",
    )
    p_exp.add_argument(
        "--host", default="127.0.0.1",
        help="bind address (default 127.0.0.1; use 0.0.0.0 to expose)",
    )
    p_exp.add_argument(
        "--snapshot", metavar="URI", default=None,
        help="bridge mode: per scrape, poll obs_snapshot on this RPC "
        "endpoint (host:port) and export ITS metrics instead of this "
        "process's registry",
    )
    p_exp.add_argument(
        "--once", action="store_true",
        help="print one exposition to stdout and exit (no HTTP server)",
    )
    args = parser.parse_args(argv)

    if args.command == "export":
        return run_export(
            port=args.port, host=args.host, snapshot_uri=args.snapshot,
            once=args.once,
        )

    if args.command == "watch":
        if args.snapshot is not None:
            if args.journal is not None:
                print(
                    "error: watch takes a journal path OR --snapshot, "
                    "not both",
                    file=sys.stderr,
                )
                return 2
            return watch_snapshot(
                args.snapshot, interval=args.interval, ticks=args.ticks
            )
        if args.journal is None:
            print(
                "error: watch needs a journal path or --snapshot URI",
                file=sys.stderr,
            )
            return 2
        return watch_journal(args.journal, interval=args.interval, ticks=args.ticks)

    records = _read_checked(args.journals)
    if records is None:
        return 2
    if args.command == "report":
        rep = build_report(records)
        if args.as_json:
            print(json.dumps(rep, indent=1, sort_keys=True))
        else:
            print(format_report(rep))
        return 0
    summary = summarize_records(records)
    if args.as_json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
