"""CLI: ``python -m hpbandster_tpu.obs <command>``.

* ``summarize <journal> [<journal> ...] [--json]`` — merge one or many
  (possibly rotated) journals by wall clock; print per-stage latency
  percentiles, worker utilization, failure tallies, and the merged
  per-trace timelines (queue wait -> dispatch -> compute -> delivery).
* ``report <journal> [<journal> ...] [--json] [--tenant T]`` — the
  optimizer-decision view (``obs/report.py``): incumbent trajectory,
  model-vs-random win rate, per-rung promotion regret, bracket
  utilization, alert digest. Deterministic: two invocations over the
  same journals are byte-identical. ``--tenant`` replays ONE tenant's
  slice of a multi-tenant serving journal (records without a
  ``tenant_id`` belong to ``default``).
* ``timeline <journal> [<journal> ...] --out trace.json`` — the unified
  sweep timeline (``obs/timeline.py``): every recorded signal — spans,
  RPC hops, compile/dispatch events, lane lifecycle, decoded per-rung
  device sections — assembled into one causally-ordered Chrome
  trace-event JSON, loadable in Perfetto (https://ui.perfetto.dev) or
  ``chrome://tracing``. Process rows per ``(host, pid)``, thread rows
  per worker/lane, flow arrows following each ``trace_id`` across RPC
  hops into the device loop. Cross-host clocks are aligned on each
  record's monotonic/wall twin stamps before assembly.
* ``critical-path <journal> [<journal> ...] [--json]`` — attribute the
  journal's end-to-end wall-clock to named phases (admission wait,
  compile, transfer, rung compute, promotion, KDE refit, RPC): a
  per-phase table plus a machine-readable verdict (attributed share vs
  threshold) — the same verdict ``bench.py``'s ``timeline_overhead``
  tier records next to the budget verdicts. Exit 0 even when the
  verdict fails (it reports, the bench gate enforces).
* ``slo <journal> [<journal> ...] [--json]`` — deterministic offline
  re-evaluation of the SLO pack (``obs/slo.py`` + ``obs/alerts.py``)
  over a journaled run: per-SLO burn rate / budget-remaining / state
  table, the alert-transition replay-parity check (journaled
  ``slo_alert`` records, envelope stripped, must match the offline
  recomputation byte-identically), and a machine-readable verdict
  ``{firing, budget_remaining, ok}`` — the same verdict ``bench.py``'s
  ``slo_overhead`` tier records. Exit 0 even when the verdict fails
  (it reports, the bench gate enforces).
* ``alerts <journal> [<journal> ...] [--json]`` — the alert lifecycle
  ledger: every ``slo_alert`` transition (pending -> firing ->
  resolved) with its burn rates and budget, from the journal's own
  records when the run was live-managed or from an offline scan
  otherwise.
* ``watch <journal> [--interval S] [--ticks N]`` — tail a live journal,
  one status line per tick; runs until ^C unless ``--ticks`` bounds it.
  ``watch --snapshot <uri> [--snapshot <uri> ...]`` polls live
  processes' ``obs_snapshot`` health RPCs instead — latency quantiles,
  compile counts, and device memory with no journal on disk; several
  URIs merge one row per endpoint per tick (collector poll/staleness
  under the hood).
* ``top --snapshot <uri> [--snapshot <uri> ...]`` (or ``top --series
  <file>``) — the live fleet dashboard (``obs/collector.py``): a
  refreshing table of endpoints, in-flight work, device balance, alerts
  and top recompilers, plus the derived fleet gauges. ``q``+Enter or
  ^C quits; ``--ticks``/``--no-clear`` give the scripted/test mode.
* ``export --port N [--snapshot <uri>] [--host H]`` — standalone
  Prometheus exporter (``obs/export.py``): serves ``GET /metrics`` in
  the strict text exposition format, rendering this process's registry
  or, with ``--snapshot``, bridging a fleet peer's ``obs_snapshot`` RPC
  per scrape. ``export --once`` prints one exposition to stdout and
  exits (the curl-equivalent for pipelines and tests).

Corrupt/truncated JSONL lines are skipped with a counted stderr warning,
never fatal (a post-mortem reader must survive the crash it documents).
Exit codes: 0 success, 2 usage error / missing journal.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, List, Optional, Tuple

from hpbandster_tpu.obs.journal import journal_paths
from hpbandster_tpu.obs.report import build_report, filter_tenant, format_report
from hpbandster_tpu.obs.summarize import (
    format_summary,
    read_merged_ex,
    summarize_records,
    watch_journal,
    watch_snapshot,
)


def _missing_journals(paths: List[str]) -> List[str]:
    return [
        p for p in paths
        if not os.path.exists(p) and not journal_paths(p)
    ]


def _read_checked(paths: List[str]) -> Optional[list]:
    """Merged records, or None (after a clear stderr message) when any
    journal is missing; corrupt lines are counted and warned about."""
    missing = _missing_journals(paths)
    if missing:
        print(
            f"error: journal(s) {', '.join(repr(p) for p in missing)} do not exist",
            file=sys.stderr,
        )
        return None
    records, skipped = read_merged_ex(paths)
    if skipped:
        print(
            f"warning: skipped {skipped} corrupt/truncated journal line(s)",
            file=sys.stderr,
        )
    return records


def run_export(
    port: int,
    host: str = "127.0.0.1",
    snapshot_uri: Optional[str] = None,
    once: bool = False,
) -> int:
    """The ``export`` subcommand body (separated so tests drive it)."""
    from hpbandster_tpu.obs.export import (
        ExporterServer,
        render_registry,
        snapshot_fetcher,
    )

    if snapshot_uri is not None:
        from hpbandster_tpu.parallel.rpc import parse_uri

        try:
            # a malformed URI can never succeed: fail fast as usage error
            parse_uri(snapshot_uri)
        except ValueError as e:
            print(
                f"error: invalid --snapshot URI {snapshot_uri!r}: {e}",
                file=sys.stderr,
            )
            return 2
        fetch = snapshot_fetcher(snapshot_uri)
    else:
        fetch = render_registry
    if once:
        try:
            sys.stdout.write(fetch())
        except Exception as e:
            print(f"error: scrape failed: {e}", file=sys.stderr)
            return 1
        return 0
    try:
        # positional: the obs-reserved-fields rule reserves `host=` kwargs
        # on obs-resolving calls for the identity stamp; this is a bind
        # address
        server = ExporterServer(port, fetch, host)
    except OSError as e:
        # port in use / privileged port / bad bind address: the CLI
        # contract is a clear message + exit 2, never a raw traceback
        print(
            f"error: cannot bind exporter to {host}:{port}: {e}",
            file=sys.stderr,
        )
        return 2
    print(
        f"serving /metrics on http://{host}:{server.port} "
        + (f"(bridging obs_snapshot at {snapshot_uri})" if snapshot_uri
           else "(local registry)"),
        file=sys.stderr,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # graftlint: disable=swallowed-exception — ^C is the intended way to stop the exporter
        pass
    finally:
        server.close()
    return 0


# the payload half of a slo_alert record — everything the evaluator
# computed, nothing the bus envelope stamped (t_wall/host/pid differ
# between the live emit and the offline recomputation by design)
_SLO_PAYLOAD = (
    "slo", "severity", "state", "burn_short", "burn_long",
    "budget_remaining", "key",
)

_STATE_NAMES = {0: "ok", 1: "pending", 2: "firing"}


def _slo_payload(rec: dict) -> dict:
    return {k: rec.get(k) for k in _SLO_PAYLOAD}


def run_slo(
    journals: List[str],
    as_json: bool = False,
    stream: Optional[Any] = None,
) -> int:
    """The ``slo`` subcommand body (separated so tests drive it):
    re-evaluate the SLO pack offline over journal records, check the
    journaled ``slo_alert`` stream against the recomputation, and print
    the per-SLO table + machine-readable verdict."""
    from hpbandster_tpu.obs.alerts import scan_slo_records

    out = stream if stream is not None else sys.stdout
    records = _read_checked(journals)
    if records is None:
        return 2
    mgr = scan_slo_records(records)
    snap = mgr.snapshot()
    recomputed = [_slo_payload(t) for t in mgr.transitions]
    recorded = [
        _slo_payload(r) for r in records if r.get("event") == "slo_alert"
    ]
    replay = {
        "recorded_transitions": len(recorded),
        "recomputed_transitions": len(recomputed),
        # the byte-identical contract: a live-managed run's journaled
        # slo_alert records, envelope stripped, equal the offline
        # recomputation exactly; None = run had no live manager, so
        # there is nothing to compare (not a failure)
        "identical": (recorded == recomputed) if recorded else None,
    }
    budgets = [
        p["budget_remaining"]
        for p in snap["by_slo"].values()
        if p.get("budget_remaining") is not None
    ]
    worst_budget = min(budgets) if budgets else None
    verdict = {
        "firing": snap["firing"],
        "budget_remaining": worst_budget,
        "ok": bool(
            snap["firing"] == 0
            and (worst_budget is None or worst_budget > 0.0)
            and replay["identical"] is not False
        ),
    }
    doc = {"slo": snap, "replay": replay, "verdict": verdict}
    if as_json:
        print(json.dumps(doc, indent=1, sort_keys=True), file=out)
        return 0
    status = "OK" if verdict["ok"] else "FAIL"
    print(
        f"slo verdict: {status} — {snap['firing']} firing, worst burn "
        f"{snap['worst_burn_rate']}, worst budget {worst_budget}",
        file=out,
    )
    if not snap["by_slo"]:
        print("  (no SLO-relevant records in this journal)", file=out)
    for name, pub in snap["by_slo"].items():
        state = _STATE_NAMES.get(pub["state"], str(pub["state"]))
        print(
            f"  {name:<24} burn={pub['burn_rate']}  "
            f"budget={pub['budget_remaining']}  state={state}",
            file=out,
        )
    ident = replay["identical"]
    tag = ("n/a (no journaled slo_alert records)" if ident is None
           else "identical" if ident else "MISMATCH")
    print(
        f"  replay parity: {tag} "
        f"({replay['recorded_transitions']} recorded / "
        f"{replay['recomputed_transitions']} recomputed)",
        file=out,
    )
    return 0


def run_alerts(
    journals: List[str],
    as_json: bool = False,
    stream: Optional[Any] = None,
) -> int:
    """The ``alerts`` subcommand body (separated so tests drive it):
    list every slo_alert lifecycle transition — the journal's own
    records when the run was live-managed, an offline scan otherwise."""
    from hpbandster_tpu.obs.alerts import scan_slo_records

    out = stream if stream is not None else sys.stdout
    records = _read_checked(journals)
    if records is None:
        return 2
    recorded = [r for r in records if r.get("event") == "slo_alert"]
    if recorded:
        source, raw = "journal", recorded
    else:
        source, raw = "offline_scan", list(scan_slo_records(records).transitions)
    times = [
        r.get("t_wall") for r in records
        if isinstance(r.get("t_wall"), (int, float))
    ]
    t0 = min(times) if times else 0.0
    rows = []
    for r in raw:
        t = r.get("t_wall")
        at_s = round(float(t) - t0, 3) if isinstance(t, (int, float)) else None
        rows.append({"at_s": at_s, **_slo_payload(r)})
    doc = {"source": source, "count": len(rows), "transitions": rows}
    if as_json:
        print(json.dumps(doc, indent=1, sort_keys=True), file=out)
        return 0
    print(f"slo alert transitions ({source}): {len(rows)}", file=out)
    for r in rows:
        at = f"+{r['at_s']:.3f}s" if r["at_s"] is not None else "?"
        print(
            f"  {at:>12}  {str(r['slo']):<24} {str(r['severity']):<7} "
            f"-> {str(r['state']):<9} burn {r['burn_short']}/{r['burn_long']} "
            f"budget {r['budget_remaining']}",
            file=out,
        )
    return 0


def _top_wait_or_quit(interval: float) -> bool:
    """Sleep one refresh interval; True = keep running. Keybindings:
    ``q`` (+Enter) or ^C quits — stdin is only consulted when it is a
    real TTY, so piped/scripted runs never block on it."""
    try:
        if sys.stdin is not None and sys.stdin.isatty():
            import select

            ready, _, _ = select.select([sys.stdin], [], [], interval)
            if ready:
                line = sys.stdin.readline()
                if line.strip().lower().startswith("q"):
                    return False
        else:
            time.sleep(interval)
    except KeyboardInterrupt:  # graftlint: disable=swallowed-exception — ^C is the intended way to leave top
        return False
    except (OSError, ValueError):  # closed/odd stdin: plain sleep instead
        time.sleep(interval)
    return True


def run_top(
    uris: Optional[List[str]],
    series: Optional[str] = None,
    interval: float = 2.0,
    ticks: Optional[int] = None,
    clear: bool = True,
    stream: Optional[Any] = None,
    tenant: Optional[str] = None,
) -> int:
    """The ``top`` subcommand body (separated so tests drive it): a
    refreshing fleet table from live endpoint polling (``--snapshot``,
    repeatable) or from the newest sample of a collector series file
    (``--series``)."""
    from hpbandster_tpu.obs.collector import (
        format_fleet_table,
        read_series_tail,
    )
    from hpbandster_tpu.obs.summarize import make_viewer_collector

    out = stream if stream is not None else sys.stdout
    if bool(uris) == bool(series):
        print(
            "error: top needs --snapshot URI(s) or --series PATH (not both)",
            file=sys.stderr,
        )
        return 2
    collector = None
    if uris:
        try:
            collector = make_viewer_collector(uris, interval)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    tick = 0
    sample = None
    series_stat: Optional[Tuple[int, int]] = None
    while True:
        if collector is not None:
            sample = collector.poll_once()
        else:
            if not os.path.exists(series):
                print(f"error: series file {series!r} does not exist",
                      file=sys.stderr)
                return 2
            st = os.stat(series)
            stat_now = (st.st_mtime_ns, st.st_size)
            # re-read only when the live file actually changed; even
            # then only its tail — a tick renders one frame, not the
            # fleet's whole history
            if stat_now != series_stat:
                series_stat = stat_now
                sample = read_series_tail(series)
        if clear:
            print("\x1b[2J\x1b[H", end="", file=out)
        stamp = time.strftime("%H:%M:%S")
        source = "live" if collector is not None else series
        print(f"hpbandster fleet top — {stamp} ({source})  [q quits]",
              file=out)
        if sample is not None:
            print(format_fleet_table(sample, tenant=tenant), file=out,
                  flush=True)
        else:
            print("(no fleet samples yet)", file=out, flush=True)
        tick += 1
        if ticks is not None and tick >= ticks:
            return 0
        if not _top_wait_or_quit(interval):
            return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hpbandster_tpu.obs",
        description="observability tooling (see docs/observability.md)",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    p_sum = sub.add_parser(
        "summarize",
        help="per-stage latency percentiles, worker utilization, failures, "
        "and merged per-trace timelines",
    )
    p_sum.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — e.g. the master's and each worker's",
    )
    p_sum.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the summary as JSON instead of text",
    )
    p_rep = sub.add_parser(
        "report",
        help="optimizer decision report: incumbent trajectory, "
        "model-vs-random win rate, promotion regret, alert digest",
    )
    p_rep.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — merged before analysis",
    )
    p_rep.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the report as JSON instead of text",
    )
    p_rep.add_argument(
        "--tenant", metavar="TENANT", default=None,
        help="report one tenant's slice of a multi-tenant journal "
        "(records without tenant_id belong to 'default')",
    )
    p_tl = sub.add_parser(
        "timeline",
        help="export the unified sweep timeline as Chrome trace-event "
        "JSON (open in Perfetto or chrome://tracing); see "
        "docs/observability.md 'Timeline & critical path'",
    )
    p_tl.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — merged and clock-aligned first",
    )
    p_tl.add_argument(
        "--out", metavar="PATH", default=None,
        help="write the trace JSON here (default: stdout)",
    )
    p_cp = sub.add_parser(
        "critical-path",
        help="attribute end-to-end wall-clock to named phases (admission "
        "wait, compile, transfer, rung compute, promotion, KDE refit, "
        "RPC) with a machine-readable verdict",
    )
    p_cp.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — merged and clock-aligned first",
    )
    p_cp.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the attribution (and verdict) as JSON instead of text",
    )
    p_cp.add_argument(
        "--threshold", type=float, default=0.95,
        help="attributed-share bar for the verdict (default 0.95)",
    )
    p_rpl = sub.add_parser(
        "replay",
        help="re-score recorded promotion_decision records under another "
        "promotion rule: rank-inversion and incumbent-regret deltas "
        "(deterministic; see docs/promotion.md)",
    )
    p_rpl.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — merged before analysis",
    )
    p_rpl.add_argument(
        "--rule", required=True, metavar="RULE",
        help="promotion rule to replay under (e.g. asha, pareto, "
        "lc_earlystop, successive_halving)",
    )
    p_rpl.add_argument(
        "--eta", type=float, default=None,
        help="eta for the asha replay (default: derived from each "
        "record's budget ratio)",
    )
    p_rpl.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the replay report as JSON instead of text",
    )
    p_slo = sub.add_parser(
        "slo",
        help="re-evaluate the SLO pack over a journaled run: per-SLO "
        "burn/budget/state table, replay-parity check, machine-readable "
        "verdict (see docs/observability.md 'SLOs & alerting')",
    )
    p_slo.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — merged before evaluation",
    )
    p_slo.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the table, replay parity, and verdict as JSON",
    )
    p_al = sub.add_parser(
        "alerts",
        help="list every slo_alert lifecycle transition (pending -> "
        "firing -> resolved) with burn rates and budget",
    )
    p_al.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — merged before evaluation",
    )
    p_al.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the transition list as JSON",
    )
    p_watch = sub.add_parser(
        "watch", help="tail a live journal (or poll a health RPC), "
        "one status line per tick"
    )
    p_watch.add_argument(
        "journal", nargs="?", default=None,
        help="path to a (possibly future) journal",
    )
    p_watch.add_argument(
        "--snapshot", metavar="URI", action="append", default=None,
        help="poll obs_snapshot on this RPC endpoint (host:port) instead "
        "of tailing a journal — latency quantiles without a journal; "
        "repeat for several endpoints (one merged row each per tick)",
    )
    p_watch.add_argument(
        "--interval", type=float, default=2.0, help="seconds between ticks"
    )
    p_watch.add_argument(
        "--ticks", type=int, default=None,
        help="stop after N ticks (default: run until ^C)",
    )
    p_watch.add_argument(
        "--tenant", metavar="TENANT", default=None,
        help="with --snapshot: show this tenant's serving counters on "
        "each row instead of the tenant census",
    )
    p_top = sub.add_parser(
        "top",
        help="live fleet dashboard: refreshing table of endpoints, device "
        "balance, alerts, top recompilers (see docs/observability.md "
        "'Fleet observatory')",
    )
    p_top.add_argument(
        "--snapshot", metavar="URI", action="append", default=None,
        help="poll obs_snapshot on this endpoint (host:port); repeat for "
        "the whole fleet (master + dispatcher + workers)",
    )
    p_top.add_argument(
        "--series", metavar="PATH", default=None,
        help="render the newest sample of a collector series file instead "
        "of polling live endpoints",
    )
    p_top.add_argument(
        "--interval", type=float, default=2.0, help="seconds between refreshes"
    )
    p_top.add_argument(
        "--ticks", type=int, default=None,
        help="stop after N refreshes (default: run until q/^C)",
    )
    p_top.add_argument(
        "--no-clear", action="store_true", dest="no_clear",
        help="append frames instead of clearing the screen (pipelines/tests)",
    )
    p_top.add_argument(
        "--tenant", metavar="TENANT", default=None,
        help="narrow the table to endpoints serving this tenant; the "
        "tenants column then shows the tenant's configs_done",
    )
    p_exp = sub.add_parser(
        "export",
        help="Prometheus exporter: serve GET /metrics in the strict text "
        "exposition format (see docs/observability.md 'Scraping the fleet')",
    )
    p_exp.add_argument(
        "--port", type=int, default=9090,
        help="HTTP port to serve /metrics on (default 9090)",
    )
    p_exp.add_argument(
        "--host", default="127.0.0.1",
        help="bind address (default 127.0.0.1; use 0.0.0.0 to expose)",
    )
    p_exp.add_argument(
        "--snapshot", metavar="URI", default=None,
        help="bridge mode: per scrape, poll obs_snapshot on this RPC "
        "endpoint (host:port) and export ITS metrics instead of this "
        "process's registry",
    )
    p_exp.add_argument(
        "--once", action="store_true",
        help="print one exposition to stdout and exit (no HTTP server)",
    )
    args = parser.parse_args(argv)

    if args.command == "top":
        return run_top(
            uris=args.snapshot, series=args.series, interval=args.interval,
            ticks=args.ticks, clear=not args.no_clear, tenant=args.tenant,
        )

    if args.command == "slo":
        return run_slo(args.journals, as_json=args.as_json)

    if args.command == "alerts":
        return run_alerts(args.journals, as_json=args.as_json)

    if args.command == "export":
        return run_export(
            port=args.port, host=args.host, snapshot_uri=args.snapshot,
            once=args.once,
        )

    if args.command == "watch":
        if args.snapshot is not None:
            if args.journal is not None:
                print(
                    "error: watch takes a journal path OR --snapshot, "
                    "not both",
                    file=sys.stderr,
                )
                return 2
            return watch_snapshot(
                args.snapshot, interval=args.interval, ticks=args.ticks,
                tenant=args.tenant,
            )
        if args.journal is None:
            print(
                "error: watch needs a journal path or --snapshot URI",
                file=sys.stderr,
            )
            return 2
        if args.tenant is not None:
            # refusing beats silently watching every tenant's records
            print(
                "error: watch --tenant requires --snapshot (journal mode "
                "has no tenant filter; use 'report --tenant' for a "
                "per-tenant journal replay)",
                file=sys.stderr,
            )
            return 2
        return watch_journal(args.journal, interval=args.interval, ticks=args.ticks)

    records = _read_checked(args.journals)
    if records is None:
        return 2
    if args.command == "timeline":
        from hpbandster_tpu.obs.timeline import to_chrome_trace

        doc = to_chrome_trace(records)
        payload = json.dumps(doc, indent=1, sort_keys=True)
        stats = doc["otherData"]
        if args.out is not None:
            with open(args.out, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
            print(
                f"wrote {args.out}: {stats['slices']} slices, "
                f"{stats['flows']} flow arrows, {stats['processes']} "
                f"process row(s) over {stats['span_s']}s — open in "
                "https://ui.perfetto.dev",
                file=sys.stderr,
            )
        else:
            print(payload)
        return 0
    if args.command == "critical-path":
        from hpbandster_tpu.obs.timeline import (
            critical_path,
            format_critical_path,
        )

        cp = critical_path(records, threshold=args.threshold)
        if args.as_json:
            print(json.dumps(cp, indent=1, sort_keys=True))
        else:
            print(format_critical_path(cp))
        return 0
    if args.command == "replay":
        # CLI-only import: the replay harness pulls in the promotion
        # kernels (numpy/jax); the substrate commands stay stdlib-only
        from hpbandster_tpu.promote.replay import (
            format_replay,
            replay_records,
        )

        try:
            rep = replay_records(records, args.rule, eta=args.eta)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.as_json:
            print(json.dumps(rep, indent=1, sort_keys=True))
        else:
            print(format_replay(rep))
        return 0
    if args.command == "report":
        if args.tenant is not None:
            records = filter_tenant(records, args.tenant)
        rep = build_report(records)
        if args.as_json:
            print(json.dumps(rep, indent=1, sort_keys=True))
        else:
            print(format_report(rep))
        return 0
    summary = summarize_records(records)
    if args.as_json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
