"""CLI: ``python -m hpbandster_tpu.obs <command>``.

* ``summarize <journal> [<journal> ...] [--json]`` — merge one or many
  (possibly rotated) journals by wall clock; print per-stage latency
  percentiles, worker utilization, failure tallies, and the merged
  per-trace timelines (queue wait -> dispatch -> compute -> delivery).
* ``watch <journal> [--interval S] [--ticks N]`` — tail a live journal,
  one status line per tick; runs until ^C unless ``--ticks`` bounds it.

Exit codes: 0 success, 2 usage error / unreadable journal.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from hpbandster_tpu.obs.journal import journal_paths
from hpbandster_tpu.obs.summarize import (
    format_summary,
    read_merged,
    summarize_records,
    watch_journal,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hpbandster_tpu.obs",
        description="observability tooling (see docs/observability.md)",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    p_sum = sub.add_parser(
        "summarize",
        help="per-stage latency percentiles, worker utilization, failures, "
        "and merged per-trace timelines",
    )
    p_sum.add_argument(
        "journals", nargs="+", metavar="journal",
        help="JSONL run journal(s) — e.g. the master's and each worker's",
    )
    p_sum.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the summary as JSON instead of text",
    )
    p_watch = sub.add_parser(
        "watch", help="tail a live journal, one status line per tick"
    )
    p_watch.add_argument("journal", help="path to a (possibly future) journal")
    p_watch.add_argument(
        "--interval", type=float, default=2.0, help="seconds between ticks"
    )
    p_watch.add_argument(
        "--ticks", type=int, default=None,
        help="stop after N ticks (default: run until ^C)",
    )
    args = parser.parse_args(argv)

    if args.command == "watch":
        return watch_journal(args.journal, interval=args.interval, ticks=args.ticks)

    missing = [
        p for p in args.journals
        if not os.path.exists(p) and not journal_paths(p)
    ]
    if missing:
        print(
            f"error: journal(s) {', '.join(repr(p) for p in missing)} do not exist",
            file=sys.stderr,
        )
        return 2
    summary = summarize_records(read_merged(args.journals))
    if args.as_json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
