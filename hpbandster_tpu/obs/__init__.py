"""``hpbandster_tpu.obs`` — structured events, metrics, and run journal.

The telemetry substrate the master/dispatcher/worker/optimizer layers
emit into (see docs/observability.md):

* :mod:`~hpbandster_tpu.obs.metrics` — thread-safe counters / gauges /
  fixed-bucket histograms with an atomic :meth:`MetricsRegistry.snapshot`;
* :mod:`~hpbandster_tpu.obs.events` — the typed event bus
  (``job_submitted`` ... ``unknown_result``) plus monotonic-clock
  :func:`span` regions, with ``utils/profiling.py`` as the optional
  ``jax.profiler`` span backend;
* :mod:`~hpbandster_tpu.obs.journal` — rotating JSONL run journal +
  in-memory ring buffer for post-mortems, identity-stamped via
  ``static_fields`` / ``configure(identity=...)``;
* :mod:`~hpbandster_tpu.obs.trace` — per-job trace context propagated
  across RPC hops (the ``_obs`` envelope in ``parallel/rpc.py``), stamped
  onto every event as ``trace_id``;
* :mod:`~hpbandster_tpu.obs.health` — the ``obs_snapshot`` fleet-health
  RPC endpoint (+ latency quantiles) + :func:`install_crash_dump`
  forensics;
* :mod:`~hpbandster_tpu.obs.audit` — the optimizer decision audit:
  ``config_sampled`` / ``promotion_decision`` records (why BOHB sampled
  a config, what a rung promotion decided) + :func:`config_lineage`;
* :mod:`~hpbandster_tpu.obs.anomaly` — streaming anomaly detection
  (stragglers, flapping workers, NaN bursts, KDE-refit stalls,
  recompile storms) emitting ``alert`` events + counters;
* :mod:`~hpbandster_tpu.obs.slo` / :mod:`~hpbandster_tpu.obs.alerts` —
  declarative SLOs with multi-window multi-burn-rate evaluation
  (page 5m/1h, ticket 6h/3d) and the pending → firing → resolved alert
  lifecycle: journaled ``slo_alert`` transitions,
  ``slo.<name>.{burn_rate,budget_remaining,state}`` gauges, and a
  byte-identical offline replay (``obs slo <journal>``);
* :mod:`~hpbandster_tpu.obs.runtime` — XLA runtime telemetry: the
  :func:`tracked_jit` compile ledger (``xla_compile`` events, per-fn
  recompile counters), the periodic :class:`DeviceSampler` memory /
  live-buffer gauges, and :func:`note_transfer` host<->device counters;
* :mod:`~hpbandster_tpu.obs.export` — the Prometheus-compatible
  exporter: strict text exposition rendering of any registry snapshot,
  a round-trip parser, the ``metrics_text`` health-RPC mount, and the
  ``python -m hpbandster_tpu.obs export`` HTTP bridge;
* :mod:`~hpbandster_tpu.obs.collector` — the fleet observatory:
  :class:`FleetCollector` polls every ``obs_snapshot`` endpoint into a
  rotating series file + derived fleet gauges (device balance, worker
  churn, queue trend, compile rate) feeding the ``fleet_imbalance`` /
  ``worker_churn`` anomaly rules and the ``obs top`` dashboard;
* :mod:`~hpbandster_tpu.obs.profile` — on-demand deep profiling:
  :class:`ProfileSession` behind the ``start_profile``/``stop_profile``
  health RPCs, plus :func:`roofline_report` over the AOT compile
  ledger's cost analysis (FLOPs/bytes per bucketed program);
* ``python -m hpbandster_tpu.obs summarize <journal> [<journal> ...]`` —
  per-stage latency percentiles, worker utilization, failure tallies, and
  merged cross-host per-trace timelines; ``report`` renders the
  deterministic optimizer story (incumbent trajectory, model-vs-random
  win rate, promotion regret, alert digest); ``watch <journal>`` tails a
  live run (``watch --snapshot host:port`` polls a health RPC instead).

Everything here is stdlib-only and costs ~nothing when no sink is
attached (the bench's ``obs_overhead`` tier measures exactly that), so
the instrumentation stays on permanently — attach sinks to look.

Quick start::

    from hpbandster_tpu import obs

    handle = obs.configure(journal_path="run/journal.jsonl")
    try:
        ...  # any optimizer run; events stream into the journal
    finally:
        handle.close()
    # then: python -m hpbandster_tpu.obs summarize run/journal.jsonl
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

from hpbandster_tpu.obs import events as _events
from hpbandster_tpu.obs import metrics as _metrics
from hpbandster_tpu.obs.anomaly import (  # noqa: F401
    AnomalyDetector,
    AnomalyRules,
    scan_records,
)
from hpbandster_tpu.obs.alerts import (  # noqa: F401
    AlertManager,
    scan_slo_records,
)
from hpbandster_tpu.obs.slo import (  # noqa: F401
    BurnWindow,
    DEFAULT_WINDOWS,
    Selector,
    SLOEvaluator,
    SLOSpec,
    default_slo_pack,
)
from hpbandster_tpu.obs.collector import (  # noqa: F401
    FleetCollector,
    derive_fleet,
    format_fleet_table,
    read_series,
)
from hpbandster_tpu.obs.device_metrics import (  # noqa: F401
    budget_cost_from_obs,
    decode_device_metrics,
    device_metrics_default,
    emit_device_telemetry,
    publish_device_metrics,
)
from hpbandster_tpu.obs.audit import (  # noqa: F401
    AUDIT_EVENTS,
    AUDIT_RULE_FIELDS,
    config_lineage,
    drain_stragglers,
    emit_bracket_created,
    emit_bracket_promotion,
    emit_config_sampled,
    emit_promotion_decision,
    emit_sweep_incumbent,
    note_straggler,
)
from hpbandster_tpu.obs.events import (  # noqa: F401
    ALERT,
    BRACKET_PROMOTION,
    CHAOS_FAULT,
    CHECKPOINT_WRITTEN,
    CONFIG_SAMPLED,
    DEVICE_TELEMETRY,
    DUPLICATE_RESULT,
    EVENT_TYPES,
    FLEET_SAMPLE,
    JOB_FAILED,
    JOB_FINISHED,
    JOB_REQUEUED,
    JOB_STARTED,
    JOB_SUBMITTED,
    KDE_REFIT,
    PROMOTION_DECISION,
    RESULT_DELIVERED,
    RESULT_REPLAYED,
    RPC_CLIENT_CALL,
    RPC_RETRY,
    SLO_ALERT,
    SWEEP_INCUMBENT,
    UNKNOWN_RESULT,
    WORKER_DISCOVERED,
    WORKER_DROPPED,
    WORKER_QUARANTINED,
    XLA_COMPILE,
    Event,
    EventBus,
    emit,
    get_bus,
    make_event,
    span,
    use_jax_annotations,
)
from hpbandster_tpu.obs.export import (  # noqa: F401
    parse_prometheus_text,
    render_registry,
    render_snapshot,
)
from hpbandster_tpu.obs.health import (  # noqa: F401
    HealthEndpoint,
    install_crash_dump,
)
from hpbandster_tpu.obs.journal import (  # noqa: F401
    JsonlJournal,
    RingBuffer,
    process_identity,
    read_journal,
)
from hpbandster_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_metrics,
)
from hpbandster_tpu.obs.profile import (  # noqa: F401
    ProfileSession,
    device_peaks,
    format_roofline,
    get_profile_session,
    roofline_report,
)
from hpbandster_tpu.obs.runtime import (  # noqa: F401
    CompileTracker,
    DeviceSampler,
    get_compile_tracker,
    note_transfer,
    publish_sweep_transfers,
    runtime_snapshot,
    start_device_sampler,
    tracked_jit,
    transfer_counters,
)
# KDE_REFIT deliberately not re-imported: the phase constant shares its
# value with the already-exported event name (both "kde_refit")
from hpbandster_tpu.obs.timeline import (  # noqa: F401
    ADMISSION,
    COMPILE,
    PHASES,
    PROMOTION,
    RPC,
    RUNG_COMPUTE,
    TRANSFER,
    TimelineRecorder,
    align_clocks,
    build_timeline,
    critical_path,
    format_critical_path,
    mark,
    phase_span,
    to_chrome_trace,
)
from hpbandster_tpu.obs.trace import (  # noqa: F401
    DEFAULT_TENANT,
    TraceContext,
    current_run,
    current_tenant,
    current_trace,
    current_wire,
    extract_tenant,
    extract_wire,
    new_trace,
    use_run,
    use_tenant,
    use_trace,
)

__all__ = [
    "Event", "EventBus", "emit", "make_event", "get_bus", "span",
    "use_jax_annotations",
    "JsonlJournal", "RingBuffer", "read_journal", "process_identity",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_metrics",
    "TraceContext", "new_trace", "current_trace", "use_trace",
    "current_wire", "extract_wire",
    "DEFAULT_TENANT", "current_tenant", "use_tenant", "extract_tenant",
    "current_run", "use_run",
    "HealthEndpoint", "install_crash_dump",
    "AnomalyDetector", "AnomalyRules", "scan_records",
    "AlertManager", "scan_slo_records", "SLOSpec", "SLOEvaluator",
    "Selector", "BurnWindow", "DEFAULT_WINDOWS", "default_slo_pack",
    "SLO_ALERT",
    "AUDIT_EVENTS", "AUDIT_RULE_FIELDS", "config_lineage",
    "emit_bracket_created", "emit_bracket_promotion",
    "emit_config_sampled", "emit_promotion_decision",
    "emit_sweep_incumbent",
    "note_straggler", "drain_stragglers",
    "decode_device_metrics", "publish_device_metrics",
    "emit_device_telemetry", "budget_cost_from_obs",
    "device_metrics_default",
    "CompileTracker", "DeviceSampler", "get_compile_tracker",
    "note_transfer", "publish_sweep_transfers", "transfer_counters",
    "runtime_snapshot", "start_device_sampler",
    "tracked_jit",
    "FleetCollector", "derive_fleet", "format_fleet_table", "read_series",
    "ProfileSession", "get_profile_session", "device_peaks",
    "roofline_report", "format_roofline",
    "render_snapshot", "render_registry", "parse_prometheus_text",
    "configure", "set_enabled", "enabled",
    "EVENT_TYPES", "JOB_SUBMITTED", "JOB_STARTED", "JOB_FINISHED",
    "JOB_FAILED", "WORKER_DISCOVERED", "WORKER_DROPPED",
    "BRACKET_PROMOTION", "KDE_REFIT", "RPC_RETRY", "RESULT_DELIVERED",
    "CHECKPOINT_WRITTEN", "UNKNOWN_RESULT",
    "CONFIG_SAMPLED", "PROMOTION_DECISION", "ALERT", "XLA_COMPILE",
    "FLEET_SAMPLE",
    "JOB_REQUEUED", "RESULT_REPLAYED", "DUPLICATE_RESULT",
    "WORKER_QUARANTINED", "CHAOS_FAULT", "SWEEP_INCUMBENT",
    "DEVICE_TELEMETRY", "RPC_CLIENT_CALL",
    "PHASES", "ADMISSION", "COMPILE", "TRANSFER", "RUNG_COMPUTE",
    "PROMOTION", "RPC",
    "phase_span", "mark", "TimelineRecorder", "align_clocks",
    "build_timeline", "to_chrome_trace", "critical_path",
    "format_critical_path",
]


def set_enabled(flag: bool) -> None:
    """Process-wide kill switch: ``False`` turns every emit / counter /
    span into a single-boolean-check no-op (the bench's A/B lever)."""
    _events._set_enabled(flag)
    _metrics._set_enabled(flag)


def enabled() -> bool:
    return _events._ENABLED


class ObsHandle:
    """What :func:`configure` returns: the attached sinks + one close()."""

    def __init__(self, detachers: List[Callable[[], None]],
                 journal: Optional[JsonlJournal], ring: Optional[RingBuffer],
                 anomaly: Optional[AnomalyDetector] = None,
                 sampler: Optional[DeviceSampler] = None,
                 slo: Optional[AlertManager] = None):
        self._detachers = detachers
        self.journal = journal
        self.ring = ring
        self.anomaly = anomaly
        self.sampler = sampler
        self.slo = slo

    def close(self) -> None:
        """Detach every sink and close the journal file (idempotent)."""
        for detach in self._detachers:
            detach()
        self._detachers = []
        if self.sampler is not None:
            from hpbandster_tpu.obs.runtime import _clear_device_sampler

            self.sampler.stop()
            _clear_device_sampler(self.sampler)
            self.sampler = None
        if self.journal is not None:
            self.journal.close()

    def __enter__(self) -> "ObsHandle":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


def configure(
    journal_path: Optional[str] = None,
    journal_max_bytes: int = 16 * 1024 * 1024,
    journal_max_files: int = 3,
    ring_capacity: int = 0,
    identity: Union[bool, Dict[str, Any], None] = None,
    bus: Optional[EventBus] = None,
    anomaly: Union[bool, AnomalyRules, None] = None,
    device_sampler: Union[bool, float, None] = None,
    slo: Union[bool, List["SLOSpec"], None] = None,
) -> ObsHandle:
    """Attach the standard sinks to ``bus`` (default: the process bus).

    ``journal_path`` enables the rotating JSONL journal; ``ring_capacity
    > 0`` additionally keeps the newest events in memory for post-mortems.
    ``identity`` stamps every journal record with this process's identity:
    ``True`` for the automatic ``{host, pid}`` pair, or a dict of extra
    fields (``{"worker_id": ...}``) merged over it — the stamp that lets
    ``summarize a.jsonl b.jsonl`` attribute merged cross-host records.
    ``anomaly`` attaches a streaming :class:`AnomalyDetector` (``True``
    for default :class:`AnomalyRules`, or pass tuned rules); its ``alert``
    events land in the same journal and its tally is on the handle as
    ``handle.anomaly``. ``slo`` attaches an :class:`AlertManager`
    (``True`` for :func:`default_slo_pack`, or pass a list of
    :class:`SLOSpec`); its ``slo_alert`` transitions land in the same
    journal (replayable via ``obs slo``) and the manager is on the
    handle as ``handle.slo``. ``device_sampler`` starts the periodic per-device
    memory / live-buffer gauge sampler (``True`` for the default 10 s
    cadence, or a number of seconds) — only in processes that run device
    work, since the first sample initializes the jax backend. Returns an
    :class:`ObsHandle` — close it to detach (tests and multi-run
    processes must, or sinks accumulate)."""
    bus = bus if bus is not None else get_bus()
    detachers: List[Callable[[], None]] = []
    journal = None
    ring = None
    detector = None
    if journal_path is not None:
        static = None
        if identity:
            static = process_identity(
                **(identity if isinstance(identity, dict) else {})
            )
        journal = JsonlJournal(
            journal_path, max_bytes=journal_max_bytes,
            max_files=journal_max_files, static_fields=static,
        )
        detachers.append(bus.subscribe(journal))
    if ring_capacity > 0:
        ring = RingBuffer(ring_capacity)
        detachers.append(bus.subscribe(ring))
    if anomaly:
        detector = AnomalyDetector(
            rules=anomaly if isinstance(anomaly, AnomalyRules) else None,
            bus=bus,
        )
        detachers.append(bus.subscribe(detector))
    manager = None
    if slo:
        manager = AlertManager(
            specs=slo if isinstance(slo, (list, tuple)) else None,
            bus=bus,
        )
        detachers.append(bus.subscribe(manager))
    sampler = None
    if device_sampler:
        sampler = start_device_sampler(
            interval_s=10.0 if device_sampler is True else float(device_sampler)
        )
    return ObsHandle(detachers, journal, ring, detector, sampler, manager)
