"""``hpbandster_tpu.obs`` — structured events, metrics, and run journal.

The telemetry substrate the master/dispatcher/worker/optimizer layers
emit into (see docs/observability.md):

* :mod:`~hpbandster_tpu.obs.metrics` — thread-safe counters / gauges /
  fixed-bucket histograms with an atomic :meth:`MetricsRegistry.snapshot`;
* :mod:`~hpbandster_tpu.obs.events` — the typed event bus
  (``job_submitted`` ... ``unknown_result``) plus monotonic-clock
  :func:`span` regions, with ``utils/profiling.py`` as the optional
  ``jax.profiler`` span backend;
* :mod:`~hpbandster_tpu.obs.journal` — rotating JSONL run journal +
  in-memory ring buffer for post-mortems;
* ``python -m hpbandster_tpu.obs summarize <journal>`` — per-stage
  latency percentiles, worker utilization, failure tallies.

Everything here is stdlib-only and costs ~nothing when no sink is
attached (the bench's ``obs_overhead`` tier measures exactly that), so
the instrumentation stays on permanently — attach sinks to look.

Quick start::

    from hpbandster_tpu import obs

    handle = obs.configure(journal_path="run/journal.jsonl")
    try:
        ...  # any optimizer run; events stream into the journal
    finally:
        handle.close()
    # then: python -m hpbandster_tpu.obs summarize run/journal.jsonl
"""

from __future__ import annotations

from typing import Callable, List, Optional

from hpbandster_tpu.obs import events as _events
from hpbandster_tpu.obs import metrics as _metrics
from hpbandster_tpu.obs.events import (  # noqa: F401
    BRACKET_PROMOTION,
    CHECKPOINT_WRITTEN,
    EVENT_TYPES,
    JOB_FAILED,
    JOB_FINISHED,
    JOB_STARTED,
    JOB_SUBMITTED,
    KDE_REFIT,
    RPC_RETRY,
    UNKNOWN_RESULT,
    WORKER_DISCOVERED,
    WORKER_DROPPED,
    Event,
    EventBus,
    emit,
    get_bus,
    span,
    use_jax_annotations,
)
from hpbandster_tpu.obs.journal import (  # noqa: F401
    JsonlJournal,
    RingBuffer,
    read_journal,
)
from hpbandster_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_metrics,
)

__all__ = [
    "Event", "EventBus", "emit", "get_bus", "span", "use_jax_annotations",
    "JsonlJournal", "RingBuffer", "read_journal",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_metrics",
    "configure", "set_enabled", "enabled",
    "EVENT_TYPES", "JOB_SUBMITTED", "JOB_STARTED", "JOB_FINISHED",
    "JOB_FAILED", "WORKER_DISCOVERED", "WORKER_DROPPED",
    "BRACKET_PROMOTION", "KDE_REFIT", "RPC_RETRY", "CHECKPOINT_WRITTEN",
    "UNKNOWN_RESULT",
]


def set_enabled(flag: bool) -> None:
    """Process-wide kill switch: ``False`` turns every emit / counter /
    span into a single-boolean-check no-op (the bench's A/B lever)."""
    _events._set_enabled(flag)
    _metrics._set_enabled(flag)


def enabled() -> bool:
    return _events._ENABLED


class ObsHandle:
    """What :func:`configure` returns: the attached sinks + one close()."""

    def __init__(self, detachers: List[Callable[[], None]],
                 journal: Optional[JsonlJournal], ring: Optional[RingBuffer]):
        self._detachers = detachers
        self.journal = journal
        self.ring = ring

    def close(self) -> None:
        """Detach every sink and close the journal file (idempotent)."""
        for detach in self._detachers:
            detach()
        self._detachers = []
        if self.journal is not None:
            self.journal.close()

    def __enter__(self) -> "ObsHandle":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


def configure(
    journal_path: Optional[str] = None,
    journal_max_bytes: int = 16 * 1024 * 1024,
    journal_max_files: int = 3,
    ring_capacity: int = 0,
    bus: Optional[EventBus] = None,
) -> ObsHandle:
    """Attach the standard sinks to ``bus`` (default: the process bus).

    ``journal_path`` enables the rotating JSONL journal; ``ring_capacity
    > 0`` additionally keeps the newest events in memory for post-mortems.
    Returns an :class:`ObsHandle` — close it to detach (tests and
    multi-run processes must, or sinks accumulate)."""
    bus = bus if bus is not None else get_bus()
    detachers: List[Callable[[], None]] = []
    journal = None
    ring = None
    if journal_path is not None:
        journal = JsonlJournal(
            journal_path, max_bytes=journal_max_bytes,
            max_files=journal_max_files,
        )
        detachers.append(bus.subscribe(journal))
    if ring_capacity > 0:
        ring = RingBuffer(ring_capacity)
        detachers.append(bus.subscribe(ring))
    return ObsHandle(detachers, journal, ring)
