"""Device metrics plane: decode in-trace sweep telemetry into the obs
pipeline.

PR 12 fused the HyperBand outer loop in-trace: bracket rotation, KDE
refits and promotions never surface to host, which left the
observability stack (events, audit histograms, anomaly rules, Prometheus
families) blind for exactly the sweeps that matter at 100k-1M configs.
This module is the host half of the fix. The device half is a
fixed-shape metrics pytree (``ops.sweep.DeviceMetrics``) threaded
through ``run_bracket`` and the resident ``lax.scan`` carry:

* per-(bracket, rung) loss **histograms** over :data:`N_BINS` log-spaced
  bins (schema below — ONE definition shared by the jittable accumulator
  ``ops.fused.stage_telemetry`` and the host twins here);
* per-(bracket, rung) **crash counts** (NaN losses), **evaluation
  counts** and **promotion counts**;
* per-bracket **KDE-refit** flags (was the model gate open) and
  **best-final losses** (the incumbent-improvement trail).

Every leaf is sized by the *schedule* (brackets x rungs x bins), never
by the config count, so the whole telemetry bill rides the sweep's
existing final d2h and the resident tier's flat-host-link assertion is
preserved by construction (``bench.py`` ``resident_100k`` measures it
with telemetry ON).

Host-side, :func:`decode_device_metrics` folds the fetched pytree into
one deterministic JSON-safe record; :func:`publish_device_metrics`
republishes it as registry gauges (``sweep.device_metrics.*`` plus the
``sweep.rung.<budget>.*`` label family ``obs/export.py`` renders for
Prometheus); :func:`emit_device_telemetry` journals it as a
``device_telemetry`` event consumed by ``summarize``/``report``/``obs
top`` and by the anomaly rules (``nan_burst`` / ``bracket_skew`` fed
from device crash counters instead of host job events).

:func:`budget_cost_from_obs` is the cost feed multi-objective promotion
reads (``promote/pareto.py``): the per-budget evaluation-cost estimate
from the obs histograms — the master's budget-keyed ``job_run_s``
histograms, else the ``sweep.budget_cost_s.<budget>`` gauges this
decoder derives from device telemetry — so Pareto ranks by the
pipeline's aggregate measurement and falls back to per-job wall spans
only when no histogram feed exists.

Bin schema (``schema`` version 1): bin 0 holds every loss at or below
``10**LOG10_LO`` (zeros and negatives included); bins ``1..N_BINS-2``
are log-spaced up to ``10**LOG10_HI``; bin ``N_BINS-1`` is the +inf
overflow. A loss equal to a bin's upper bound lands IN that bin
(``bisect_left`` — the same convention as ``obs.metrics.Histogram``).
NaN (crashed) losses are never histogrammed; they are counted in the
crash counters. Quantiles decode as bucket upper bounds (conservative,
like the registry histograms); a quantile landing in the overflow bin
decodes as None.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.metrics import MetricsRegistry, get_metrics

__all__ = [
    "N_BINS",
    "LOG10_LO",
    "LOG10_HI",
    "SCHEMA_VERSION",
    "bin_edges",
    "bin_index_np",
    "hist_quantile",
    "device_metrics_default",
    "decode_device_metrics",
    "merge_rungs",
    "publish_device_metrics",
    "emit_device_telemetry",
    "budget_cost_from_obs",
    "device_section_from_records",
    "format_device_section",
    "device_metric_fields",
    "finite_or_none",
]

#: total bin count, underflow (bin 0) and overflow (bin N_BINS-1) included
N_BINS = 32
#: log10 of bin 0's upper bound / of the last finite upper bound
LOG10_LO = -6.0
LOG10_HI = 6.0
#: decoded-record schema version (bump on any layout change so journal
#: readers can tell records apart)
SCHEMA_VERSION = 1

#: minimum observation count before a registry histogram is trusted as a
#: cost feed (below it, one noisy span would masquerade as an aggregate)
COST_FEED_MIN_COUNT = 8


def device_metrics_default() -> bool:
    """Process default for the drivers' ``device_metrics=None`` knob:
    ``HPB_DEVICE_METRICS=1`` turns in-trace telemetry on everywhere, any
    other value (or unset) leaves it off — telemetry changes the compiled
    program, so the default must be explicit and stable, never inferred
    from ambient bus state."""
    import os

    return os.environ.get("HPB_DEVICE_METRICS", "") == "1"


def bin_edges():
    """Ascending upper bounds of bins ``0..N_BINS-2`` (f64[N_BINS-1]) —
    THE schema definition. The jittable accumulator
    (``ops.fused.stage_telemetry``) and the host twin
    (:func:`bin_index_np`) both bin against exactly this array; anything
    else and the device/host parity tests break."""
    import numpy as np

    return np.logspace(LOG10_LO, LOG10_HI, N_BINS - 1)


def bin_index_np(losses) -> "Any":
    """Host twin of the in-trace binning: ``i64[n]`` bin index per loss
    (``searchsorted`` left, matching ``obs.metrics.Histogram``'s
    ``bisect_left``). NaN rows index the overflow bin — callers mask
    them out exactly like the device accumulator does."""
    import numpy as np

    losses = np.asarray(losses, np.float32)
    return np.minimum(
        np.searchsorted(bin_edges().astype(np.float32), losses, side="left"),
        N_BINS - 1,
    )


def hist_quantile(hist: Sequence[int], q: float) -> Optional[float]:
    """Conservative quantile from one bin-count vector: the upper bound
    of the bucket holding the q-quantile observation (the
    ``obs.metrics.Histogram`` convention). None when the histogram is
    empty or the quantile lands in the +inf overflow bin (no honest
    upper bound exists there)."""
    total = sum(int(c) for c in hist)
    if total <= 0:
        return None
    edges = bin_edges()
    rank = max(float(q), 0.0) * total
    acc = 0
    for i, c in enumerate(hist):
        acc += int(c)
        if acc >= rank and c:
            return float(edges[i]) if i < len(edges) else None
    return None


def finite_or_none(v: Any) -> Optional[float]:
    """Finite numeric or None; bools (a corrupt record's `true` loss)
    are not numbers. THE one finite-coercion helper of the obs decode
    layer — report.py delegates to it."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        v = float(v)
        if v == v and v not in (float("inf"), float("-inf")):
            return v
    return None


#: the gauge namespace publish_device_metrics mints totals under —
#: device_metric_fields is its ONE parser
GAUGE_PREFIX = "sweep.device_metrics."


def device_metric_fields(gauges) -> Dict[str, float]:
    """``{field: value}`` for every ``sweep.device_metrics.*`` gauge in
    a metrics/gauges mapping — THE one parser of the gauge names
    :func:`publish_device_metrics` mints. The collector's endpoint rows
    and ``watch --snapshot``'s device part both read through it, so a
    renamed or added field cannot make the two surfaces disagree."""
    out: Dict[str, float] = {}
    for name, value in (gauges or {}).items():
        if isinstance(name, str) and name.startswith(GAUGE_PREFIX):
            v = finite_or_none(value)
            if v is not None:
                out[name[len(GAUGE_PREFIX):]] = v
    return out


def _plan_shapes(plans) -> List[Tuple[Tuple[int, ...], Tuple[float, ...]]]:
    """Normalize a plan sequence (BracketPlan or raw pairs) to hashable
    ``(num_configs, budgets)`` tuples — what decode keys rungs by."""
    out = []
    for p in plans:
        if hasattr(p, "num_configs"):
            out.append((
                tuple(int(n) for n in p.num_configs),
                tuple(float(b) for b in p.budgets),
            ))
        else:
            nc, bd = p
            out.append((
                tuple(int(n) for n in nc), tuple(float(b) for b in bd)
            ))
    return out


def merge_rungs(rung_lists: Sequence[Sequence[Dict[str, Any]]]) -> List[Dict[str, Any]]:
    """Fold several decoded records' ``rungs`` sections (same schema)
    into one per-budget aggregate — histograms sum bin-wise, quantiles
    recompute from the merged histogram. The one merge implementation
    ``summarize``/``report`` share so the two views of a journal agree."""
    by_budget: Dict[float, Dict[str, Any]] = {}
    for rungs in rung_lists:
        for r in rungs or []:
            b = finite_or_none(r.get("budget"))
            if b is None:
                continue
            slot = by_budget.setdefault(b, {
                "budget": b, "evals": 0, "crashes": 0, "promotions": 0,
                "hist": [0] * N_BINS,
            })
            for k in ("evals", "crashes", "promotions"):
                v = r.get(k)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    slot[k] += int(v)
            h = r.get("hist")
            if isinstance(h, (list, tuple)) and len(h) == N_BINS:
                slot["hist"] = [
                    a + int(c) for a, c in zip(slot["hist"], h)
                ]
    out = []
    for b in sorted(by_budget):
        slot = by_budget[b]
        slot["crash_rate"] = (
            round(slot["crashes"] / slot["evals"], 6)
            if slot["evals"] else None
        )
        slot["loss_p50"] = hist_quantile(slot["hist"], 0.50)
        slot["loss_p95"] = hist_quantile(slot["hist"], 0.95)
        out.append(slot)
    return out


def decode_device_metrics(
    parts,
    plans=None,
    execute_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Fold fetched :class:`~hpbandster_tpu.ops.sweep.DeviceMetrics`
    pytree(s) into ONE deterministic, JSON-safe record.

    ``parts`` is either a single metrics pytree (then ``plans`` names its
    bracket schedule) or a sequence of ``(metrics, plans)`` pairs — the
    chunked driver decodes all chunks at once. Determinism is a hard
    contract (pinned by tests): the record derives only from the pytree
    values and plan shapes — two decodes of the same inputs are
    byte-identical.

    ``execute_s`` (the sweep's measured device seconds) additionally
    derives a per-budget evaluation-cost estimate (``est_cost_s`` per
    rung): device seconds split across rungs proportionally to
    ``evals x budget`` (the HyperBand cost model — budget IS the unit of
    evaluation work), divided by the rung's evaluations. That estimate
    feeds the ``sweep.budget_cost_s.<b>`` gauges
    :func:`publish_device_metrics` exports and the Pareto cost feed.
    """
    import numpy as np

    if plans is not None:
        parts = [(parts, plans)]
    parts = [
        (m, _plan_shapes(p)) for m, p in parts
    ]

    n_brackets = 0
    total = {"evals": 0, "crashes": 0, "promotions": 0, "model_fits": 0}
    by_budget: Dict[float, Dict[str, Any]] = {}
    per_bracket_best: List[Optional[float]] = []
    per_bracket_crashes: List[int] = []
    #: per-rung execution-order entries (the ``rung_seq`` stamp the
    #: device accumulator writes), assembled into the flat ``rung_order``
    #: list the flight recorder (obs/timeline.py) lays device rows from
    rung_order: List[Dict[str, Any]] = []
    seq_offset = 0

    def budget_slot(b: float) -> Dict[str, Any]:
        return by_budget.setdefault(float(b), {
            "budget": float(b), "evals": 0, "crashes": 0, "promotions": 0,
            "hist": [0] * N_BINS,
        })

    for part_i, (metrics, shapes) in enumerate(parts):
        hist = np.asarray(metrics.loss_hist)
        evals = np.asarray(metrics.evals)
        crashes = np.asarray(metrics.crashes)
        promos = np.asarray(metrics.promotions)
        fits = np.asarray(metrics.model_fits)
        best = np.asarray(metrics.best_final)
        # older pytrees (pre-rung_seq journals replayed through decode)
        # carry no stamp: synthesize bracket-major order, which is what
        # the unrolled sweep executes anyway
        seq = getattr(metrics, "rung_seq", None)
        seq = np.asarray(seq) if seq is not None else None
        if hist.shape[0] != len(shapes):
            raise ValueError(
                f"metrics carry {hist.shape[0]} brackets but the plan "
                f"schedule names {len(shapes)} — decode needs the exact "
                "schedule the sweep ran"
            )
        part_rungs = 0
        part_entries: List[Dict[str, Any]] = []
        for b_i, (num_configs, budgets) in enumerate(shapes):
            n_brackets += 1
            total["model_fits"] += int(fits[b_i])
            bracket_crashes = 0
            for s, budget in enumerate(budgets):
                slot = budget_slot(budget)
                slot["evals"] += int(evals[b_i, s])
                slot["crashes"] += int(crashes[b_i, s])
                slot["promotions"] += int(promos[b_i, s])
                slot["hist"] = [
                    a + int(c) for a, c in zip(slot["hist"], hist[b_i, s])
                ]
                total["evals"] += int(evals[b_i, s])
                total["crashes"] += int(crashes[b_i, s])
                total["promotions"] += int(promos[b_i, s])
                bracket_crashes += int(crashes[b_i, s])
                s_raw = int(seq[b_i, s]) if seq is not None else part_rungs
                if s_raw >= 0:
                    part_entries.append({
                        "seq": s_raw,
                        "bracket": n_brackets - 1,
                        "stage": s,
                        "budget": float(budget),
                        "evals": int(evals[b_i, s]),
                    })
                part_rungs += 1
            per_bracket_crashes.append(bracket_crashes)
            bf = float(best[b_i])
            per_bracket_best.append(
                round(bf, 6) if bf == bf and finite_or_none(bf) is not None
                else None
            )
        # stack parts in execution order: rebase each part's stamps to
        # its own minimum (a pytree SLICED out of a larger sweep keeps
        # the sweep-global stamps; a fresh chunk starts at 0 — both land
        # in the same place after the rebase), then offset by the rungs
        # already decoded so chunked decodes order globally
        if part_entries:
            part_min = min(e["seq"] for e in part_entries)
            for e in part_entries:
                e["seq"] = e["seq"] - part_min + seq_offset
            rung_order.extend(part_entries)
        seq_offset += part_rungs

    # running incumbent after each bracket (crashed/NaN bests never
    # improve it) — the per-round improvement trail the ISSUE asks for
    incumbent_after: List[Optional[float]] = []
    improvements = 0
    running: Optional[float] = None
    for bf in per_bracket_best:
        if bf is not None and (running is None or bf < running):
            running = bf
            improvements += 1
        incumbent_after.append(running)

    rungs = []
    # work split for the cost estimate: evals x budget per rung
    work_total = sum(
        slot["evals"] * b for b, slot in by_budget.items()
    )
    for b in sorted(by_budget):
        slot = by_budget[b]
        slot["crash_rate"] = (
            round(slot["crashes"] / slot["evals"], 6)
            if slot["evals"] else None
        )
        slot["loss_p50"] = hist_quantile(slot["hist"], 0.50)
        slot["loss_p95"] = hist_quantile(slot["hist"], 0.95)
        if (
            execute_s is not None and work_total > 0 and slot["evals"] > 0
        ):
            slot["est_cost_s"] = round(
                float(execute_s) * (slot["evals"] * b / work_total)
                / slot["evals"],
                9,
            )
        rungs.append(slot)

    # execution-order section: rungs sorted by the device stamp, each
    # carrying its estimated device-seconds slice (same evals x budget
    # work model as est_cost_s) so the timeline can lay the device row
    # out to scale without any per-rung host timing existing
    rung_order.sort(key=lambda r: (r["seq"], r["bracket"], r["stage"]))
    if execute_s is not None and work_total > 0:
        for r in rung_order:
            r["est_s"] = round(
                float(execute_s) * (r["evals"] * r["budget"] / work_total),
                9,
            )

    rec: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "n_bins": N_BINS,
        "brackets": n_brackets,
        "rounds_completed": n_brackets,
        "evaluations": total["evals"],
        "crashes": total["crashes"],
        "promotions": total["promotions"],
        "model_fits": total["model_fits"],
        "crash_rate": (
            round(total["crashes"] / total["evals"], 6)
            if total["evals"] else None
        ),
        "rungs": rungs,
        "rung_order": rung_order,
        "per_bracket_best": per_bracket_best,
        "per_bracket_crashes": per_bracket_crashes,
        "incumbent_after": incumbent_after,
        "improvements": improvements,
    }
    if execute_s is not None:
        rec["execute_s"] = round(float(execute_s), 6)
    return rec


def publish_device_metrics(
    decoded: Dict[str, Any],
    registry: Optional[MetricsRegistry] = None,
) -> None:
    """Republish one decoded record as registry gauges.

    * ``sweep.device_metrics.{evaluations,crashes,promotions,model_fits,
      rounds,crash_rate}`` — sweep-level totals (dotted names flatten in
      the Prometheus rendering);
    * ``sweep.rung.<budget>.{evals,crashes,promotions,loss_p50,
      loss_p95}`` — per-rung families, re-expressed by ``obs/export.py``
      as ``sweep_rung_<field>{budget=...}``;
    * ``sweep.budget_cost_s.<budget>`` — the per-evaluation device-cost
      estimate (present when the decoder was given ``execute_s``), the
      gauge half of :func:`budget_cost_from_obs`'s feed.

    Like the per-sweep transfer gauges these describe the LAST sweep;
    scraping mid-run sees the previous sweep's values.
    """
    reg = registry if registry is not None else get_metrics()
    for field, key in (
        ("evaluations", "evaluations"), ("crashes", "crashes"),
        ("promotions", "promotions"), ("model_fits", "model_fits"),
        ("rounds", "rounds_completed"),
    ):
        v = decoded.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            reg.gauge(f"sweep.device_metrics.{field}").set(float(v))
    rate = finite_or_none(decoded.get("crash_rate"))
    if rate is not None:
        reg.gauge("sweep.device_metrics.crash_rate").set(rate)
    for rung in decoded.get("rungs") or []:
        b = finite_or_none(rung.get("budget"))
        if b is None:
            continue
        for field in ("evals", "crashes", "promotions"):
            v = rung.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                reg.gauge(f"sweep.rung.{b:g}.{field}").set(float(v))
        for field in ("loss_p50", "loss_p95"):
            v = finite_or_none(rung.get(field))
            if v is not None:
                reg.gauge(f"sweep.rung.{b:g}.{field}").set(v)
        cost = finite_or_none(rung.get("est_cost_s"))
        if cost is not None:
            reg.gauge(f"sweep.budget_cost_s.{b:g}").set(cost)


def emit_device_telemetry(decoded: Dict[str, Any]) -> None:
    """Journal one decoded record as a ``device_telemetry`` event — the
    record ``summarize``/``report``/``obs top`` consume and the anomaly
    rules (``nan_burst``, ``bracket_skew``) read device crash counters
    from. A no-op with no sink attached, like every emit."""
    if not E.get_bus().active:
        return
    E.emit(E.DEVICE_TELEMETRY, **decoded)


def device_section_from_records(
    records: Sequence[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Fold a journal's ``device_telemetry`` records into the section
    ``summarize`` and ``report`` both render — ONE aggregation so the
    two views of a journal cannot drift. Deterministic in record
    content; None when the journal carries no device telemetry."""
    recs = [
        r for r in records
        if isinstance(r, dict) and r.get("event") == E.DEVICE_TELEMETRY
    ]
    if not recs:
        return None
    totals = {
        "sweeps": len(recs), "evaluations": 0, "crashes": 0,
        "promotions": 0, "model_fits": 0, "rounds_completed": 0,
    }
    for r in recs:
        for key in (
            "evaluations", "crashes", "promotions", "model_fits",
            "rounds_completed",
        ):
            v = r.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                totals[key] += int(v)
    totals["crash_rate"] = (
        round(totals["crashes"] / totals["evaluations"], 6)
        if totals["evaluations"] else None
    )
    totals["rungs"] = merge_rungs([r.get("rungs") for r in recs])
    # each record's running-best tail is that sweep's final incumbent
    bests = [
        finite_or_none((r.get("incumbent_after") or [None])[-1]) for r in recs
    ]
    bests = [b for b in bests if b is not None]
    totals["best_loss"] = round(min(bests), 6) if bests else None
    return totals


def format_device_section(section: Dict[str, Any]) -> List[str]:
    """Text lines for one :func:`device_section_from_records` section —
    shared by the summarize and report renderers."""
    lines = [
        "device telemetry: %d sweep(s), %d evals, %d crashed%s, "
        "%d model fits, %d rounds"
        % (
            section["sweeps"], section["evaluations"], section["crashes"],
            (
                " (%.2f%%)" % (100.0 * section["crash_rate"])
                if isinstance(section.get("crash_rate"), (int, float))
                else ""
            ),
            section["model_fits"], section["rounds_completed"],
        )
    ]
    for rung in section.get("rungs") or []:
        p50 = rung.get("loss_p50")
        p95 = rung.get("loss_p95")
        lines.append(
            "  rung budget=%g: %d evals, %d crashed, %d promoted, "
            "loss p50<=%s p95<=%s"
            % (
                rung.get("budget"), rung.get("evals", 0),
                rung.get("crashes", 0), rung.get("promotions", 0),
                "%.4g" % p50 if isinstance(p50, (int, float)) else "?",
                "%.4g" % p95 if isinstance(p95, (int, float)) else "?",
            )
        )
    if section.get("best_loss") is not None:
        lines.append("  best final loss (device): %.6g" % section["best_loss"])
    return lines


def budget_cost_from_obs(
    budget: float,
    registry: Optional[MetricsRegistry] = None,
    min_count: int = COST_FEED_MIN_COUNT,
) -> Optional[float]:
    """The obs-histogram cost feed for one budget, or None when no feed
    exists.

    Priority: the master's budget-keyed evaluation-time histogram
    (``master.job_run_s.b<budget>`` p50, trusted once it holds
    ``min_count`` observations — the aggregate measurement, immune to
    one straggling span), then the ``sweep.budget_cost_s.<budget>``
    gauge the device-telemetry decoder publishes (fused/resident sweeps,
    where per-job host timing is fiction). ``promote/pareto.py`` ranks
    its cost objective from this feed and falls back to per-job wall
    spans only when it returns None.
    """
    b = finite_or_none(budget)
    if b is None:
        return None
    reg = registry if registry is not None else get_metrics()
    snap = reg.snapshot()
    hist = (snap.get("histograms") or {}).get(f"master.job_run_s.b{b:g}")
    if isinstance(hist, dict):
        count = hist.get("count")
        p50 = finite_or_none(hist.get("p50"))
        if (
            isinstance(count, (int, float)) and count >= max(int(min_count), 1)
            and p50 is not None
        ):
            return p50
    gauge = finite_or_none(
        (snap.get("gauges") or {}).get(f"sweep.budget_cost_s.{b:g}")
    )
    return gauge
