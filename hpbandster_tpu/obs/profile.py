"""On-demand deep profiling: remote trace capture + roofline attribution.

Before this module, capturing a ``jax.profiler`` trace of a fleet
process meant deciding at *construction* time (``FusedBOHB(profile_dir=
...)`` wraps the whole sweep) — there was no way to ask an already-hot
worker "show me the next thirty seconds". Two pieces fix that:

* :class:`ProfileSession` — a thread-safe wrapper over
  ``jax.profiler.start_trace`` / ``stop_trace`` with a process-wide
  default instance. Every :class:`~hpbandster_tpu.obs.health
  .HealthEndpoint` registers it as ``start_profile`` / ``stop_profile``
  / ``profile_status`` RPCs, so any fleet peer can be told to capture a
  trace *now*, remotely, and report where the files landed. Errors come
  back as ``{"ok": False, "error": ...}`` dicts, never as exceptions —
  a profiling request must not be able to take a serving process down.

* :func:`roofline_report` — walks the AOT compile ledger
  (:class:`~hpbandster_tpu.obs.runtime.CompileTracker`), whose
  ``_TrackedLowered`` proxy now records each compiled program's
  ``cost_analysis()`` (FLOPs + bytes accessed), and attributes
  arithmetic intensity per bucketed program: which programs are
  compute-bound vs memory-bound on this chip, and — given measured
  execution seconds — achieved-vs-peak utilization. Peak FLOP/s comes
  from ``workloads/flops.py``'s per-chip table; HBM bandwidth from the
  table below. **CPU caveat** (docs/observability.md): XLA's CPU
  backend still reports FLOPs/bytes, but there are no peak numbers for
  arbitrary host CPUs, so ``bound``/``utilization`` are None there —
  the intensities themselves remain exact and portable.

jax loads lazily inside the functions that need it (the standard obs
rule); importing this module costs nothing.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from hpbandster_tpu.obs.metrics import get_metrics

__all__ = [
    "ProfileSession",
    "get_profile_session",
    "device_peaks",
    "roofline_report",
    "format_roofline",
    "transfer_summary",
]

#: per-chip HBM bandwidth (bytes/s) by ``device.device_kind`` prefix —
#: the memory edge of the roofline (peak FLOP/s lives in
#: workloads/flops.py). v5e: 819 GB/s; v5p: 2765; v4: 1228; v3: 900;
#: v6e: 1640. Unknown kinds (CPU included) return None.
_PEAK_HBM_BYTES_S = {
    "TPU v6 lite": 1640e9,
    "TPU v5 lite": 819e9,
    "TPU v5p": 2765e9,
    "TPU v5": 819e9,  # bare "v5" reported by some stacks is v5e
    "TPU v4": 1228e9,
    "TPU v3": 900e9,
}


class ProfileSession:
    """One process's on-demand ``jax.profiler`` capture state.

    At most one trace is live at a time (jax's own constraint); a second
    ``start`` reports the active capture instead of raising. All methods
    return JSON-serializable dicts — this is an RPC surface first.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._log_dir: Optional[str] = None
        self._t0_mono: Optional[float] = None
        self._captures = 0

    def start(self, log_dir: Optional[str] = None) -> Dict[str, Any]:
        """Begin capturing a trace into ``log_dir`` (a fresh temp dir by
        default, reported back so the caller can fetch/inspect it)."""
        with self._lock:
            if self._log_dir is not None:
                return {
                    "ok": False,
                    "error": "profile already active",
                    "log_dir": self._log_dir,
                }
            if log_dir is None:
                log_dir = tempfile.mkdtemp(prefix="hpb_profile_")
            try:
                import jax

                os.makedirs(log_dir, exist_ok=True)
                jax.profiler.start_trace(log_dir)
            except Exception as e:
                # the profiler failing must never look like the process
                # failing — report and keep serving
                return {"ok": False, "error": f"{type(e).__name__}: {e}"}
            self._log_dir = log_dir
            self._t0_mono = time.monotonic()
            get_metrics().counter("profile.captures_started").inc()
            return {"ok": True, "log_dir": log_dir}

    def stop(self) -> Dict[str, Any]:
        """End the live capture; reports the trace dir and duration."""
        with self._lock:
            if self._log_dir is None:
                return {"ok": False, "error": "no profile active"}
            log_dir = self._log_dir
            t0 = self._t0_mono
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                # keep the session marked active: jax's profiler may
                # still hold its trace open, and clearing our state here
                # would wedge profiling for the life of the process (no
                # later start can succeed, no later stop would retry)
                return {
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                    "log_dir": log_dir,
                }
            self._log_dir = None
            self._t0_mono = None
            self._captures += 1
            get_metrics().counter("profile.captures_completed").inc()
            return {
                "ok": True,
                "log_dir": log_dir,
                "duration_s": (
                    round(time.monotonic() - t0, 3) if t0 is not None else None
                ),
                "files": _count_trace_files(log_dir),
            }

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active": self._log_dir is not None,
                "log_dir": self._log_dir,
                "elapsed_s": (
                    round(time.monotonic() - self._t0_mono, 3)
                    if self._t0_mono is not None else None
                ),
                "captures_completed": self._captures,
            }


def _count_trace_files(log_dir: str) -> int:
    n = 0
    for _dirpath, _dirnames, filenames in os.walk(log_dir):
        n += len(filenames)
    return n


_SESSION = ProfileSession()


def get_profile_session() -> ProfileSession:
    """The process-wide session every health endpoint exposes."""
    return _SESSION


# ------------------------------------------------------------------ roofline
def device_peaks(device: Any = None) -> Dict[str, Optional[float]]:
    """``{"flops_per_s", "bytes_per_s", "ridge_flops_per_byte", "kind"}``
    for one device (default: ``jax.devices()[0]``); values are None for
    chips without table entries — CPU most prominently."""
    if device is None:
        import jax

        device = jax.devices()[0]
    from hpbandster_tpu.workloads.flops import peak_bf16_flops

    kind = str(getattr(device, "device_kind", ""))
    flops = peak_bf16_flops(device)
    bw = None
    for prefix, v in _PEAK_HBM_BYTES_S.items():
        if kind.startswith(prefix):
            bw = v
            break
    return {
        "kind": kind,
        "flops_per_s": flops,
        "bytes_per_s": bw,
        "ridge_flops_per_byte": (flops / bw) if flops and bw else None,
    }


def transfer_summary(
    registry: Any = None,
) -> Optional[Dict[str, Any]]:
    """Host-link transfer view for the roofline report: process-lifetime
    byte/buffer counters (``obs.runtime.note_transfer``) plus the
    last-sweep gauges (``obs.runtime.publish_sweep_transfers``), or None
    when the process never counted a transfer. Registry-read only —
    never initializes jax."""
    from hpbandster_tpu.obs.metrics import get_metrics

    reg = registry if registry is not None else get_metrics()
    snap = reg.snapshot()
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    total = {
        k: int(counters.get(f"runtime.{k}", 0) or 0)
        for k in ("transfer_bytes_h2d", "transfer_bytes_d2h",
                  "transfers_h2d", "transfers_d2h")
    }
    if not any(total.values()) and "sweep.transfer_bytes.d2h" not in gauges:
        return None
    out: Dict[str, Any] = {"process_total": total}
    last_sweep = {
        "h2d_bytes": gauges.get("sweep.transfer_bytes.h2d"),
        "d2h_bytes": gauges.get("sweep.transfer_bytes.d2h"),
        "host_syncs": gauges.get("sweep.host_syncs"),
    }
    if any(v is not None for v in last_sweep.values()):
        out["last_sweep"] = last_sweep
    return out


def roofline_report(
    tracker: Any = None,
    peaks: Optional[Dict[str, Optional[float]]] = None,
    seconds_by_program: Optional[Dict[str, float]] = None,
    transfers: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Attribute FLOPs/bytes per compiled program in the compile ledger.

    Covers every ledger program that recorded a ``cost_analysis`` (the
    AOT path: ``fn.lower(...).compile()`` through ``_TrackedLowered`` —
    exactly the bucket ledger's programs). ``peaks`` defaults to
    :func:`device_peaks` of the first local device, but never initializes
    jax when the ledger is empty. ``seconds_by_program`` maps
    ``"fn"`` or ``"fn@signature"`` to measured execution seconds — when
    given, the program's achieved FLOP/s and utilization-vs-peak are
    estimated (the *measured* half of the roofline; without it only the
    analytic half renders).

    Deterministic: programs sort by (fn, signature); content-only.
    """
    from hpbandster_tpu.obs.runtime import get_compile_tracker

    trk = tracker if tracker is not None else get_compile_tracker()
    costed = trk.program_costs()
    if peaks is None and costed:
        try:
            peaks = device_peaks()
        except Exception:  # graftlint: disable=swallowed-exception — no usable device is an expected state (CPU CI, no backend); the report renders with a caveat instead
            peaks = None
    peaks = peaks or {
        "kind": None, "flops_per_s": None, "bytes_per_s": None,
        "ridge_flops_per_byte": None,
    }
    peak_f = peaks.get("flops_per_s")
    peak_b = peaks.get("bytes_per_s")
    ridge = peaks.get("ridge_flops_per_byte")
    programs: List[Dict[str, Any]] = []
    for entry in costed:
        flops = entry.get("flops")
        nbytes = entry.get("bytes_accessed")
        intensity = (
            round(flops / nbytes, 4) if flops and nbytes else None
        )
        bound = None
        if intensity is not None and ridge:
            bound = "compute" if intensity >= ridge else "memory"
        # the floor execution time the chip's rooflines allow — what the
        # measured seconds are judged against
        floor_s = None
        if flops is not None and peak_f:
            floor_s = flops / peak_f
        if nbytes is not None and peak_b:
            mem_s = nbytes / peak_b
            floor_s = mem_s if floor_s is None else max(floor_s, mem_s)
        row = {
            "fn": entry["fn"],
            "signature": entry.get("signature"),
            "compiles": entry.get("compiles"),
            "compile_s": entry.get("compile_s"),
            "flops": flops,
            "bytes_accessed": nbytes,
            "intensity_flops_per_byte": intensity,
            "bound": bound,
            "roofline_floor_s": (
                round(floor_s, 9) if floor_s is not None else None
            ),
        }
        seconds = None
        if seconds_by_program:
            key = f"{entry['fn']}@{entry.get('signature')}"
            seconds = seconds_by_program.get(key)
            if seconds is None:
                seconds = seconds_by_program.get(entry["fn"])
        if seconds and flops:
            achieved = flops / seconds
            row["measured_s"] = round(float(seconds), 6)
            row["achieved_flops_per_s"] = round(achieved, 2)
            if peak_f:
                row["utilization_vs_peak"] = round(achieved / peak_f, 4)
        programs.append(row)
    programs.sort(key=lambda r: (r["fn"], str(r["signature"])))
    if transfers is None:
        transfers = transfer_summary()
    return {
        "peak": peaks,
        "programs": programs,
        "program_count": len(programs),
        # the host-link half of the roofline story: FLOPs/bytes above are
        # what the device did; this is what crossed the host link doing it
        "transfers": transfers,
        "caveats": [] if peak_f else [
            "no peak FLOP/s table entry for this device kind "
            "(CPU backends especially): intensities are exact, but "
            "bound/utilization columns cannot be computed"
        ],
    }


def format_roofline(report: Dict[str, Any]) -> str:
    """Human rendering of :func:`roofline_report` (the ``obs roofline``
    CLI body)."""
    peak = report.get("peak") or {}
    lines = [
        "roofline — device: {} (peak {} FLOP/s, {} B/s, ridge {} FLOP/B)".format(
            peak.get("kind") or "?",
            _si(peak.get("flops_per_s")), _si(peak.get("bytes_per_s")),
            _fmtnum(peak.get("ridge_flops_per_byte")),
        )
    ]
    header = (
        f"{'program':<38} {'flops':>10} {'bytes':>10} {'FLOP/B':>8} "
        f"{'bound':<8} {'floor_s':>11} {'util':>6}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for row in report.get("programs") or []:
        name = row["fn"]
        sig = row.get("signature")
        if sig:
            name = f"{name}[{sig}]"
        util = row.get("utilization_vs_peak")
        lines.append(
            f"{name[:38]:<38} {_si(row.get('flops')):>10} "
            f"{_si(row.get('bytes_accessed')):>10} "
            f"{_fmtnum(row.get('intensity_flops_per_byte')):>8} "
            f"{str(row.get('bound') or '-'):<8} "
            f"{_fmtnum(row.get('roofline_floor_s')):>11} "
            f"{(f'{100 * util:.1f}%' if util is not None else '-'):>6}"
        )
    if not report.get("programs"):
        lines.append("(no costed programs in the compile ledger — run an "
                     "AOT-compiled path first, e.g. a bucketed schedule)")
    transfers = report.get("transfers")
    if transfers:
        total = transfers.get("process_total") or {}
        lines.append(
            "host link (process): h2d {} / {} buffers, d2h {} / {} buffers".format(
                _si(total.get("transfer_bytes_h2d")),
                _si(total.get("transfers_h2d")),
                _si(total.get("transfer_bytes_d2h")),
                _si(total.get("transfers_d2h")),
            )
        )
        last = transfers.get("last_sweep")
        if last:
            lines.append(
                "host link (last sweep): h2d {}, d2h {}, {} host sync(s)".format(
                    _si(last.get("h2d_bytes")), _si(last.get("d2h_bytes")),
                    _si(last.get("host_syncs")),
                )
            )
    for c in report.get("caveats") or []:
        lines.append(f"note: {c}")
    return "\n".join(lines)


def _si(v: Any) -> str:
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return "-"
    v = float(v)
    for div, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= div:
            return f"{v / div:.2f}{suffix}"
    return f"{v:.0f}"


def _fmtnum(v: Any) -> str:
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return "-"
    return f"{float(v):.3g}"
