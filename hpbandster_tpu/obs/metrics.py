"""Thread-safe metrics registry: counters, gauges, fixed-bucket histograms.

Stdlib-only and hot-path-cheap by design (the same rule ``analysis/``
follows): an increment is one lock acquire + one integer add, and the
whole registry shares a single lock so :meth:`MetricsRegistry.snapshot`
is ATOMIC — the returned dict is a consistent cut across every
instrument, which is what makes "snapshot equals the sum of what the
threads did" a testable property rather than a race.

Instruments are created through the registry (``counter(name)`` /
``gauge(name)`` / ``histogram(name, buckets=...)``); asking for an
existing name returns the existing instrument, asking for it as a
different kind raises. The process-wide default registry is
:func:`get_metrics`; code under test can build private registries.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_metrics",
    "DEFAULT_BUCKETS",
]

#: default histogram bucket upper bounds (seconds-flavored: micro-RPCs to
#: multi-minute fused compiles), +inf implicit as the last bucket
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 300.0
)

#: process-wide kill switch, toggled via hpbandster_tpu.obs.set_enabled();
#: disabled instruments drop updates at one boolean check
_ENABLED = True


def _set_enabled(flag: bool) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


class Counter:
    """Monotonically increasing count (events seen, failures, retries)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock  # the owning registry's lock: snapshots stay atomic
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """A value that goes up and down (queue depth, pool size)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram (bucket upper bounds + implicit +inf).

    ``observe(v)`` is O(log n_buckets) (bisect) under the registry lock.
    ``quantile(q)`` returns the upper bound of the bucket holding the
    q-quantile observation — a conservative estimate whose error is
    bounded by the bucket width, the classic fixed-bucket trade."""

    __slots__ = ("name", "_lock", "bounds", "_counts", "_sum", "_count", "_min", "_max")

    def __init__(
        self, name: str, lock: threading.Lock,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.name = name
        self._lock = lock
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot = (+inf overflow)
        self._sum = 0.0
        self._count = 0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float) -> None:
        if not _ENABLED:
            return
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self.bounds, v)] += 1
            self._sum += v
            self._count += 1
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)

    def quantile(self, q: float) -> Optional[float]:
        """Upper bucket bound covering the q-quantile; None when empty,
        the observed max for the overflow bucket."""
        with self._lock:
            return self._quantile_locked(q)

    def _quantile_locked(self, q: float) -> Optional[float]:
        # both callers (quantile(), the registry snapshot) hold self._lock
        if self._count == 0:
            return None
        rank = max(q, 0.0) * self._count
        acc = 0
        for i, c in enumerate(self._counts):
            acc += c
            if acc >= rank and c:
                return self.bounds[i] if i < len(self.bounds) else self._max
        return self._max

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


class MetricsRegistry:
    """Name -> instrument, all sharing ONE lock for atomic snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, Any] = {}

    def _get(self, name: str, kind: type, *args: Any) -> Any:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = kind(name, self._lock, *args)
                self._instruments[name] = inst
            elif not isinstance(inst, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {kind.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(
        self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get(name, Histogram, buckets)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """One consistent cut across every instrument (single lock hold)."""
        out: Dict[str, Dict[str, Any]] = {
            "counters": {}, "gauges": {}, "histograms": {}
        }
        with self._lock:
            for name, inst in sorted(self._instruments.items()):
                if isinstance(inst, Counter):
                    out["counters"][name] = inst._value
                elif isinstance(inst, Gauge):
                    out["gauges"][name] = inst._value
                else:
                    out["histograms"][name] = {
                        "count": inst._count,
                        "sum": inst._sum,
                        "min": inst._min,
                        "max": inst._max,
                        "buckets": dict(zip(
                            [str(b) for b in inst.bounds] + ["+inf"],
                            list(inst._counts),
                        )),
                        "p50": inst._quantile_locked(0.50),
                        "p95": inst._quantile_locked(0.95),
                    }
        return out

    def remove(self, name: str) -> bool:
        """Drop one instrument (e.g. a per-worker gauge when the worker
        leaves the pool — without this, elastic churn leaks stale frozen
        metrics without bound). Returns whether it existed."""
        with self._lock:
            return self._instruments.pop(name, None) is not None

    def reset(self) -> None:
        """Drop every instrument (test isolation)."""
        with self._lock:
            self._instruments.clear()


_DEFAULT_REGISTRY = MetricsRegistry()


def get_metrics() -> MetricsRegistry:
    """The process-wide default registry."""
    return _DEFAULT_REGISTRY
