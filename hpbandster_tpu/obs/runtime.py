"""XLA runtime telemetry: compilation tracking + device sampling.

PRs 2–4 made the *job* tier observable; this module does the same for the
*runtime* tier — the XLA substrate whose silent failure modes (a
recompile storm in the fused sweep, a device quietly filling its memory)
erase exactly the wall-clock wins the fused paths exist to deliver.

Three instruments, all stdlib-only at import (jax loads lazily inside
the functions that need it, same rule as the rest of ``obs``):

* :func:`tracked_jit` — a thin ``jax.jit`` wrapper adopted by the repo's
  jit sites (``ops/fused.py``, ``ops/sweep.py``, ``ops/kde.py``,
  ``ops/bracket.py``, ``parallel/backends.py``). Every call whose
  abstract shape signature (shapes + dtypes + static values) has not
  been seen by that wrapper times the dispatch and journals one
  ``xla_compile`` event: function name, signature, compile seconds, and
  the per-function recompile counter. The measured seconds are the
  first-call wall time (trace + compile + first execution — compile
  dominates for anything XLA spends real time on); steady-state calls
  pay one signature hash + set lookup, measured by the bench's
  ``runtime_overhead`` tier against the <2% obs bar.
* :class:`DeviceSampler` — a periodic daemon thread publishing
  per-device gauges: ``memory_stats()`` bytes in use / limit where the
  backend reports them (TPU/GPU; CPU reports nothing), plus live-buffer
  counts and bytes from ``jax.live_arrays()``.
* :func:`note_transfer` — host<->device transfer counters incremented at
  the repo's own transfer choke points (``ops/fused.py`` dispatch and
  unpack, ``parallel/backends.py`` evaluate, the batched executor's
  wave assembly): buffer counts and byte totals per direction.

Everything lands in the shared :mod:`~hpbandster_tpu.obs.metrics`
registry (so the Prometheus exporter in :mod:`~hpbandster_tpu.obs.export`
scrapes it for free) and on the event bus (so the journal, the
``recompile_storm`` anomaly rule, and the summarize/report CLIs see it).

The wrapper itself never emits from inside a traced region: when a
tracked function is being traced INTO an enclosing computation (e.g.
``ops.kde.propose`` vmapped inside the fused sweep), the wrapper detects
the live trace and passes straight through — the outer tracked boundary
owns that compile.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.metrics import MetricsRegistry, get_metrics

__all__ = [
    "CompileTracker",
    "DeviceSampler",
    "compile_stats_from_records",
    "get_compile_tracker",
    "note_transfer",
    "publish_sweep_transfers",
    "runtime_snapshot",
    "start_device_sampler",
    "tracked_jit",
    "transfer_counters",
]

logger = logging.getLogger("hpbandster_tpu.obs")


# ------------------------------------------------------------ compile tracking
class CompileTracker:
    """Per-function compile ledger shared by every :func:`tracked_jit`.

    Aggregation is by function *label* (not wrapper instance) on purpose:
    a loop that keeps constructing fresh jitted closures of the same
    function — the exact storm the ``recompile_storm`` rule and the
    ``jit-in-loop`` lint exist for — shows up as one label compiling over
    and over, which is the true cost XLA pays.
    """

    #: per-ledger bound on retained costed programs — a shape-churning
    #: pathology must not grow the roofline table without limit (the
    #: storm is the recompile counters' job to surface)
    MAX_COSTED_PROGRAMS = 256

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: label -> {"compiles": int, "compile_s": float,
        #:           "last_signature": str, "last_compile_s": float}
        self._fns: Dict[str, Dict[str, Any]] = {}
        #: (label, signature) -> {"compiles", "compile_s", "flops",
        #: "bytes_accessed"} for programs whose compile reported a
        #: cost_analysis (the AOT path) — what roofline_report walks
        self._programs: "collections.OrderedDict" = collections.OrderedDict()

    def record(
        self,
        label: str,
        signature: str,
        seconds: float,
        registry: Optional[MetricsRegistry] = None,
        bus: Optional[E.EventBus] = None,
        cost: Optional[Dict[str, float]] = None,
    ) -> int:
        """Count one fresh compilation of ``label``; returns the
        function's cumulative compile count. Updates the shared metrics
        (``runtime.compiles``, per-fn counters, ``runtime.compile_seconds``)
        and emits one ``xla_compile`` event. ``cost`` is the compiled
        program's XLA cost analysis (``{"flops", "bytes_accessed"}``
        where reported): retained per (label, signature) for the
        roofline report and republished as ``runtime.flops.<fn>`` /
        ``runtime.bytes_accessed.<fn>`` counters."""
        with self._lock:
            slot = self._fns.get(label)
            if slot is None:
                slot = self._fns[label] = {"compiles": 0, "compile_s": 0.0}
            slot["compiles"] += 1
            slot["compile_s"] += float(seconds)
            slot["last_signature"] = signature
            slot["last_compile_s"] = float(seconds)
            n = slot["compiles"]
            if cost:
                key = (label, signature)
                prog = self._programs.pop(key, None)
                if prog is None:
                    prog = {"compiles": 0, "compile_s": 0.0}
                prog["compiles"] += 1
                prog["compile_s"] = round(prog["compile_s"] + float(seconds), 6)
                prog.update({k: float(v) for k, v in cost.items()})
                self._programs[key] = prog  # re-insert: LRU-newest
                while len(self._programs) > self.MAX_COSTED_PROGRAMS:
                    self._programs.popitem(last=False)
        reg = registry if registry is not None else get_metrics()
        reg.counter("runtime.compiles").inc()
        reg.counter(f"runtime.compiles.{label}").inc()
        reg.gauge("runtime.compile_seconds").inc(float(seconds))
        extra: Dict[str, Any] = {}
        if cost:
            for field in ("flops", "bytes_accessed"):
                v = cost.get(field)
                if v is not None:
                    reg.counter(f"runtime.{field}.{label}").inc(int(v))
                    extra[field] = float(v)
        target = bus if bus is not None else E.get_bus()
        target.emit(
            E.XLA_COMPILE,
            fn=label,
            signature=signature,
            compile_s=round(float(seconds), 6),
            compiles=n,
            recompiles=n - 1,
            **extra,
        )
        return n

    def program_costs(self) -> List[Dict[str, Any]]:
        """Every costed program in the ledger, insertion order: the
        roofline report's input rows."""
        with self._lock:
            return [
                {"fn": label, "signature": signature, **dict(prog)}
                for (label, signature), prog in self._programs.items()
            ]

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable ledger: totals + per-function breakdown."""
        with self._lock:
            functions = {
                label: {
                    "compiles": slot["compiles"],
                    "compile_s": round(slot["compile_s"], 6),
                    "recompiles": slot["compiles"] - 1,
                    "last_signature": slot.get("last_signature"),
                }
                for label, slot in sorted(self._fns.items())
            }
        return {
            "total_compiles": sum(f["compiles"] for f in functions.values()),
            "total_compile_s": round(
                sum(f["compile_s"] for f in functions.values()), 6
            ),
            "functions": functions,
        }

    def reset(self) -> None:
        """Drop the ledger (test isolation)."""
        with self._lock:
            self._fns.clear()
            self._programs.clear()


_TRACKER = CompileTracker()


def get_compile_tracker() -> CompileTracker:
    """The process-wide compile ledger every :func:`tracked_jit` feeds."""
    return _TRACKER


def _leaf_key(leaf: Any) -> Any:
    """Hashable identity of one TRACED argument leaf: abstract
    (shape, dtype) for anything array-like, the python type (not value)
    for bare scalars — jax traces those as weak-typed values whose value
    never keys the dispatch cache — and the value for anything else."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    if isinstance(leaf, (bool, int, float, complex)):
        return ("weak", type(leaf).__name__)
    try:
        hash(leaf)
        return leaf
    except TypeError:
        return repr(leaf)


def _value_key(leaf: Any) -> Any:
    """Hashable identity of one STATIC argument: by value (jax bakes
    static values into the compiled program)."""
    try:
        hash(leaf)
        return leaf
    except TypeError:
        return repr(leaf)


#: jax.tree_util.tree_flatten, bound once on first use — the wrapper sits
#: on the hot dispatch path, so per-call `import jax` + attribute chains
#: are real money (measured ~14µs of a ~18µs signature)
_TREE_FLATTEN: Optional[Callable] = None


def _flatten(x: Any):
    global _TREE_FLATTEN
    if _TREE_FLATTEN is None:
        import jax

        _TREE_FLATTEN = jax.tree_util.tree_flatten
    return _TREE_FLATTEN(x)


def _abstract_signature(
    args: Tuple,
    kwargs: Dict,
    static_nums: frozenset = frozenset(),
    static_names: frozenset = frozenset(),
) -> Tuple:
    """Hashable abstract signature of a call, in the same terms jax's own
    dispatch cache keys on: tree structure + per-leaf shape/dtype for
    traced leaves (python scalars by type only — weak-typed), static args
    by value. Weak-type-vs-strong-type distinctions inside arrays are
    deliberately ignored — a documented trade for a wrapper cheap enough
    to sit on the hot dispatch path."""
    if not static_nums and not static_names:
        leaves, treedef = _flatten((args, kwargs))
        return (treedef, tuple(map(_leaf_key, leaves)), (), ())
    t_args = tuple(a for i, a in enumerate(args) if i not in static_nums)
    s_args = tuple(
        (i, _value_key(a)) for i, a in enumerate(args) if i in static_nums
    )
    t_kwargs = {k: v for k, v in kwargs.items() if k not in static_names}
    s_kwargs = tuple(sorted(
        (k, _value_key(v)) for k, v in kwargs.items() if k in static_names
    ))
    leaves, treedef = _flatten((t_args, t_kwargs))
    return (treedef, tuple(map(_leaf_key, leaves)), s_args, s_kwargs)


def _format_signature(sig: Tuple) -> str:
    """Human/journal form of :func:`_abstract_signature`:
    ``f32[8,2], f32[8], n=64``-style, truncated to a sane length."""
    parts: List[str] = []
    for key in sig[1]:
        if (
            isinstance(key, tuple)
            and len(key) == 2
            and isinstance(key[0], tuple)
            and isinstance(key[1], str)
        ):
            shape, dtype = key
            parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
        elif isinstance(key, tuple) and len(key) == 2 and key[0] == "weak":
            parts.append(f"weak_{key[1]}")
        else:
            parts.append(repr(key))
    for i, v in sig[2] if len(sig) > 2 else ():
        parts.append(f"static{i}={v!r}")
    for k, v in sig[3] if len(sig) > 3 else ():
        parts.append(f"{k}={v!r}")
    out = ", ".join(parts)
    return out if len(out) <= 200 else out[:197] + "..."


def tracked_jit(
    fn: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    tracker: Optional[CompileTracker] = None,
    registry: Optional[MetricsRegistry] = None,
    bus: Optional[E.EventBus] = None,
    **jit_kwargs: Any,
) -> Callable:
    """``jax.jit`` with compile telemetry: a drop-in wrapper that journals
    one ``xla_compile`` event per fresh abstract-shape signature.

    Usable bare (``tracked_jit(fn)``), with jit kwargs
    (``tracked_jit(fn, static_argnames="n")``), or as a decorator factory
    (``@partial(tracked_jit, static_argnames="n")``). ``name`` overrides
    the journal label (default: the function's ``__name__``).

    Signature tracking is per wrapper (each wrapper owns its own jit
    cache) while compile counts aggregate per label in the process-wide
    :class:`CompileTracker`. Calls made while an enclosing trace is live
    pass straight through untracked — the wrapper must never emit from
    inside a traced region (the ``obs-emit-in-jit`` contract).
    """
    if fn is None:
        return partial(
            tracked_jit, name=name, tracker=tracker, registry=registry,
            bus=bus, **jit_kwargs,
        )
    import inspect

    import jax

    jitted = jax.jit(fn, **jit_kwargs)
    label = name or getattr(fn, "__name__", None) or "<anonymous>"
    trk = tracker if tracker is not None else _TRACKER
    seen: set = set()

    # mirror jax's static/traced split so the signature keys statics by
    # VALUE and traced leaves abstractly (static_argnames resolve to
    # positions too — jax accepts them positionally)
    names = jit_kwargs.get("static_argnames") or ()
    names = (names,) if isinstance(names, str) else tuple(names)
    nums = jit_kwargs.get("static_argnums")
    nums = (nums,) if isinstance(nums, int) else tuple(nums or ())
    static_nums = set(nums)
    try:
        params = list(inspect.signature(fn).parameters)
        for nm in names:
            if nm in params:
                static_nums.add(params.index(nm))
    except (TypeError, ValueError):
        pass  # builtins/exotic callables: keyword statics still resolve
    static_nums = frozenset(static_nums)
    static_names = frozenset(names)
    # bound once: jax.core's module __getattr__ costs ~1µs per access
    trace_state_clean = jax.core.trace_state_clean

    def wrapper(*args: Any, **kwargs: Any):
        if not E._ENABLED or not trace_state_clean():
            # disabled, or being traced into an enclosing computation:
            # the outer tracked boundary owns any compile that results
            return jitted(*args, **kwargs)
        reg = registry if registry is not None else get_metrics()
        reg.counter("runtime.tracked_calls").inc()
        try:
            sig = _abstract_signature(args, kwargs, static_nums, static_names)
        except Exception:
            # an exotic pytree must degrade to an untracked call, never
            # block the dispatch it was only supposed to observe
            logger.exception("tracked_jit signature for %r failed", label)
            return jitted(*args, **kwargs)
        if sig in seen:
            return jitted(*args, **kwargs)
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        seconds = time.perf_counter() - t0
        seen.add(sig)
        trk.record(
            label, _format_signature(sig), seconds,
            registry=reg, bus=bus,
        )
        return out

    def lower(*args: Any, **kwargs: Any):
        """AOT path (``fn.lower(...).compile()``): the returned proxy
        times ``compile()`` and feeds the same ledger, so ahead-of-time
        compiles (FusedBOHB's executable cache) journal like JIT ones."""
        lowered = jitted.lower(*args, **kwargs)
        try:
            sig_str = _format_signature(_abstract_signature(args, kwargs))
        # best-effort label: an exotic pytree only costs the signature
        # string, never the lowering it annotates
        except Exception:  # graftlint: disable=swallowed-exception — signature is cosmetic here; the compile proceeds either way
            sig_str = "<unhashable>"
        return _TrackedLowered(
            lowered, label, sig_str, trk,
            registry if registry is not None else None, bus,
        )

    wrapper.__name__ = getattr(fn, "__name__", "tracked_jit")
    wrapper.__doc__ = getattr(fn, "__doc__", None)
    wrapper.__wrapped__ = fn
    #: the underlying jitted callable (AOT lowering, cache introspection)
    wrapper.jitted = jitted
    wrapper.label = label
    wrapper.lower = lower
    return wrapper


class _TrackedLowered:
    """Proxy over ``jax.stages.Lowered`` that records ``compile()`` time
    into the compile ledger; every other attribute forwards verbatim."""

    def __init__(self, lowered, label, signature, tracker, registry, bus):
        self._lowered = lowered
        self._label = label
        self._signature = signature
        self._tracker = tracker
        self._registry = registry
        self._bus = bus

    def compile(self, *args: Any, **kwargs: Any):
        if not E._ENABLED:
            return self._lowered.compile(*args, **kwargs)
        t0 = time.perf_counter()
        exe = self._lowered.compile(*args, **kwargs)
        self._tracker.record(
            self._label, self._signature, time.perf_counter() - t0,
            registry=self._registry, bus=self._bus,
            cost=_extract_cost(exe),
        )
        return exe

    def __getattr__(self, name: str) -> Any:
        return getattr(self._lowered, name)


def _extract_cost(compiled: Any) -> Optional[Dict[str, float]]:
    """The compiled program's XLA cost analysis, normalized to the ledger
    schema (``flops`` / ``bytes_accessed``). Best-effort: a backend
    without cost analysis returns None and the compile is still tracked
    — the roofline table just has no row for it."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # graftlint: disable=swallowed-exception — backends without cost analysis are expected; absence of a roofline row is the answer, the compile is still ledgered
        return None
    if isinstance(ca, (list, tuple)):  # older jax returns [dict] per device
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    for src, dst in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
        v = ca.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v == v and v >= 0:
            out[dst] = float(v)
    return out or None


# ---------------------------------------------------------- transfer counters
def note_transfer(
    direction: str,
    nbytes: int,
    buffers: int = 1,
    registry: Optional[MetricsRegistry] = None,
) -> None:
    """Count one host<->device transfer at a repo choke point.

    ``direction`` is ``"h2d"`` or ``"d2h"``. jax exposes no portable
    transfer counters, so the repo counts its OWN transfer sites — the
    fused dispatch/unpack pair and the batched backend's upload/fetch —
    which is exactly the set whose round-trips dominate on high-latency
    links (see ops/fused.py's packing rationale).
    """
    if direction not in ("h2d", "d2h"):
        raise ValueError(f"direction must be 'h2d' or 'd2h', not {direction!r}")
    reg = registry if registry is not None else get_metrics()
    reg.counter(f"runtime.transfers_{direction}").inc(int(buffers))
    reg.counter(f"runtime.transfer_bytes_{direction}").inc(max(int(nbytes), 0))


#: the four process-lifetime host-link counters :func:`note_transfer`
#: advances — the ONE name list shared by the per-sweep snapshot/diff
#: below and anything else that wants to read the link bill
_TRANSFER_COUNTER_KEYS = (
    "transfers_h2d", "transfers_d2h",
    "transfer_bytes_h2d", "transfer_bytes_d2h",
)


def transfer_counters(
    registry: Optional[MetricsRegistry] = None,
) -> Dict[str, int]:
    """Current values of the host-link transfer counters (0 where never
    advanced) — snapshot one before a sweep, diff with
    :func:`publish_sweep_transfers` after."""
    reg = registry if registry is not None else get_metrics()
    counters = reg.snapshot().get("counters") or {}
    return {
        k: int(counters.get(f"runtime.{k}", 0) or 0)
        for k in _TRANSFER_COUNTER_KEYS
    }


def publish_sweep_transfers(
    before: Dict[str, int],
    registry: Optional[MetricsRegistry] = None,
) -> Dict[str, int]:
    """Per-sweep device<->host byte accounting: diff the transfer
    counters against a :func:`transfer_counters` snapshot taken at sweep
    start, publish the result as gauges, and return the deltas.

    Gauges (exported to Prometheus as ``sweep_transfer_bytes{direction=}``
    and ``hpbandster_sweep_host_syncs`` via ``obs/export.py``):

    * ``sweep.transfer_bytes.h2d`` / ``sweep.transfer_bytes.d2h`` — bytes
      the host link carried for the LAST sweep. The resident sweep's
      flatness claim lives here: in incumbent-only mode d2h must not
      scale with config count (one vector + one scalar per sweep);
    * ``sweep.host_syncs`` — transferred-BUFFER count (both directions,
      the unit every ``note_transfer`` site counts in: a fetch of one
      4-leaf payload counts 4): the sweep's host-surface bill, which the
      resident-loop bench tier pins constant in config count.

    Counts only the repo's own :func:`note_transfer` choke points — the
    set whose round-trips dominate on high-latency links.
    """
    reg = registry if registry is not None else get_metrics()
    now = transfer_counters(reg)
    delta = {k: now[k] - int(before.get(k, 0)) for k in now}
    reg.gauge("sweep.transfer_bytes.h2d").set(
        float(delta["transfer_bytes_h2d"])
    )
    reg.gauge("sweep.transfer_bytes.d2h").set(
        float(delta["transfer_bytes_d2h"])
    )
    reg.gauge("sweep.host_syncs").set(
        float(delta["transfers_h2d"] + delta["transfers_d2h"])
    )
    return delta


# ------------------------------------------------------------- device sampler
class DeviceSampler:
    """Periodic per-device memory / live-buffer census -> gauges.

    ``sample()`` runs one census (tests call it directly); ``start()``
    spawns a daemon thread sampling every ``interval_s`` until ``stop()``.
    Gauges published per device index ``i``:

    * ``runtime.device.<i>.bytes_in_use`` / ``.bytes_limit`` — from
      ``Device.memory_stats()`` where the backend provides it;
    * ``runtime.device.<i>.live_buffers`` / ``.live_bytes`` — from
      ``jax.live_arrays()``, a sharded array contributing one buffer and
      its per-shard byte share to each device it lives on;

    plus ``runtime.device_count``. Sampling initializes the jax backend
    on first use, so only start a sampler in processes that run device
    work anyway (the health endpoint reads the LAST census, it never
    samples on demand).
    """

    def __init__(
        self,
        interval_s: float = 10.0,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.interval_s = max(float(interval_s), 0.05)
        self._registry = registry
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------- sampling
    def sample(self) -> Dict[str, Any]:
        """One census; returns (and retains) the JSON-serializable result."""
        import jax

        reg = self._registry if self._registry is not None else get_metrics()
        devices = jax.devices()
        per_dev: Dict[int, Dict[str, Any]] = {
            int(d.id): {"kind": str(d.device_kind), "platform": str(d.platform)}
            for d in devices
        }
        live_buffers: Dict[int, int] = {i: 0 for i in per_dev}
        live_bytes: Dict[int, int] = {i: 0 for i in per_dev}
        try:
            for arr in jax.live_arrays():
                devs = list(getattr(arr, "devices", lambda: [])())
                if not devs:
                    continue
                share = int(getattr(arr, "nbytes", 0)) // len(devs)
                for d in devs:
                    i = int(d.id)
                    if i in live_buffers:
                        live_buffers[i] += 1
                        live_bytes[i] += share
        except Exception:
            # live_arrays is best-effort introspection; a backend that
            # cannot enumerate must not kill the sampler thread
            logger.exception("device sampler live_arrays census failed")
        for d in devices:
            i = int(d.id)
            slot = per_dev[i]
            slot["live_buffers"] = live_buffers[i]
            slot["live_bytes"] = live_bytes[i]
            reg.gauge(f"runtime.device.{i}.live_buffers").set(live_buffers[i])
            reg.gauge(f"runtime.device.{i}.live_bytes").set(live_bytes[i])
            try:
                stats = d.memory_stats()
            # best-effort: CPU and older backends raise (or return None)
            # here — absent memory stats are the answer, not an error
            except Exception:  # graftlint: disable=swallowed-exception — backend without memory introspection; absence is the answer
                stats = None
            if stats:
                in_use = stats.get("bytes_in_use")
                limit = stats.get("bytes_limit")
                if isinstance(in_use, (int, float)):
                    slot["bytes_in_use"] = int(in_use)
                    reg.gauge(f"runtime.device.{i}.bytes_in_use").set(in_use)
                if isinstance(limit, (int, float)):
                    slot["bytes_limit"] = int(limit)
                    reg.gauge(f"runtime.device.{i}.bytes_limit").set(limit)
        reg.gauge("runtime.device_count").set(len(devices))
        census = {
            "t_wall": time.time(),
            "device_count": len(devices),
            "devices": {str(i): per_dev[i] for i in sorted(per_dev)},
        }
        with self._lock:
            self._last = census
        return census

    def last_sample(self) -> Optional[Dict[str, Any]]:
        """The newest census, or None before the first sample."""
        with self._lock:
            return dict(self._last) if self._last is not None else None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "DeviceSampler":
        """Spawn the daemon sampling thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="obs-device-sampler"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception:
                # telemetry must never kill its host process's thread pool
                logger.exception("device sampler pass failed")
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        """Stop the sampling thread (idempotent; safe if never started)."""
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)


#: the sampler started via start_device_sampler, for runtime_snapshot()
_SAMPLER: Optional[DeviceSampler] = None
_SAMPLER_LOCK = threading.Lock()


def start_device_sampler(
    interval_s: float = 10.0,
    registry: Optional[MetricsRegistry] = None,
) -> DeviceSampler:
    """Start (or return) the process-wide device sampler. The returned
    sampler's ``stop()`` halts it; ``obs.configure(device_sampler=...)``
    wires this into the standard sink lifecycle."""
    global _SAMPLER
    with _SAMPLER_LOCK:
        if _SAMPLER is None:
            _SAMPLER = DeviceSampler(interval_s=interval_s, registry=registry)
            _SAMPLER.start()
        return _SAMPLER


def _clear_device_sampler(sampler: DeviceSampler) -> None:
    """Forget the process-wide sampler if it is ``sampler`` (close path)."""
    global _SAMPLER
    with _SAMPLER_LOCK:
        if _SAMPLER is sampler:
            _SAMPLER = None


def compile_stats_from_records(
    records: List[Dict[str, Any]],
    window_s: float,
    top_k: int = 5,
) -> Dict[str, Any]:
    """Offline aggregation of ``xla_compile`` journal records — the ONE
    definition behind both the summarize CLI's "xla runtime" block and
    the report CLI's runtime section (they must agree or the two views
    of the same journal drift): per-fn compile counts/seconds, the
    compile-time share of the journal's wall-clock window, and the
    ``top_k`` recompilers. Deterministic: content-only, stable sort."""
    import math

    per_fn: Dict[str, Dict[str, float]] = {}
    for rec in records:
        if rec.get("event") != E.XLA_COMPILE:
            continue
        fn = str(rec.get("fn") or "?")
        slot = per_fn.setdefault(fn, {"compiles": 0, "compile_s": 0.0})
        slot["compiles"] += 1
        cs = rec.get("compile_s")
        if (
            isinstance(cs, (int, float))
            and not isinstance(cs, bool)
            and math.isfinite(cs)
        ):
            slot["compile_s"] += float(cs)
    total = sum(s["compiles"] for s in per_fn.values())
    total_s = sum(s["compile_s"] for s in per_fn.values())
    return {
        "compiles": int(total),
        "compile_s": round(total_s, 6),
        # compile-time share of the journal's wall-clock window: the
        # number that says whether XLA ate the sweep (a recompile storm
        # pushes this toward 1 even when every job "succeeded")
        "compile_share_of_wall": (
            round(min(total_s / window_s, 1.0), 4)
            if window_s > 0 and total else None
        ),
        "top_recompilers": [
            {
                "fn": fn,
                "compiles": int(slot["compiles"]),
                "compile_s": round(slot["compile_s"], 6),
                "recompiles": int(slot["compiles"]) - 1,
            }
            for fn, slot in sorted(
                per_fn.items(),
                key=lambda kv: (-kv[1]["compiles"], -kv[1]["compile_s"], kv[0]),
            )[:top_k]
        ],
    }


def runtime_snapshot() -> Dict[str, Any]:
    """The ``runtime`` section of ``obs_snapshot`` (health.py): the
    compile ledger plus the newest device census (None until a
    :class:`DeviceSampler` has run — this never touches jax itself, so a
    health RPC cannot initialize a backend as a side effect)."""
    with _SAMPLER_LOCK:
        sampler = _SAMPLER
    return {
        "compile": _TRACKER.snapshot(),
        "devices": sampler.last_sample() if sampler is not None else None,
    }
