"""Fleet observatory: poll every health endpoint into a time series.

PRs 2–5 made each fleet process individually legible — ``obs_snapshot``
answers *what is this process doing right now*, the Prometheus exporter
answers *what do its counters read this instant*. Nothing answered the
fleet-level questions the scale-out arc needs: is device memory balanced
across the mesh, are workers churning, is the queue draining, is XLA
compiling more than it should — *over time*.

:class:`FleetCollector` is that aggregation tier. It polls a set of
``obs_snapshot`` endpoints (master, dispatcher, every worker) on an
interval, folds each round into

* an on-disk **series file** — one strict-JSON line per poll round
  (``fleet_sample`` schema), written through the same rotating
  :class:`~hpbandster_tpu.obs.journal.JsonlJournal` machinery as run
  journals, so disk stays bounded however long the fleet runs;
* a bounded **in-memory window** (the newest ``window`` samples) that
  the ``obs top`` dashboard and trend math read;
* **derived fleet gauges** republished through the shared metrics
  registry (``fleet.*``), so the Prometheus exporter and the anomaly
  detector's ``fleet_imbalance`` / ``worker_churn`` rules see them with
  zero extra wiring:

  - ``fleet.endpoints`` / ``fleet.endpoints_ok`` / ``fleet.endpoints_stale``
  - ``fleet.workers_alive`` / ``fleet.queue_depth`` / ``fleet.jobs_in_flight``
  - ``fleet.device_mem_utilization`` — bytes in use / limit, fleet-wide
  - ``fleet.device_mem_skew`` — (max - min)/max over per-device busy
    bytes: the balance number the mesh-sharding arc reads
  - ``fleet.device_compute_skew`` — worst per-endpoint (max - min)/max
    over per-device sharded-sweep config counts
    (``sweep.device.<i>.configs``, published by
    ``parallel.multihost.publish_device_balance``; counts are only
    comparable within one sweep, so endpoints are judged separately and
    the fleet gauge is the worst of them): the compute-balance sibling
    of the memory skew — on an SPMD mesh all devices step in lockstep,
    so row-count imbalance IS step-time imbalance. The gauge describes
    each endpoint's MOST RECENT sharded sweep
  - ``fleet.worker_churn_per_min`` — worker drops + endpoint losses
  - ``fleet.queue_depth_trend_per_min`` — signed queue drain/growth rate
  - ``fleet.compile_rate_per_min`` — fresh XLA compiles across the fleet

Failure containment is the design center: every endpoint is polled with
its own socket timeout, so a dead or *hung* peer costs one bounded
timeout, never a stalled loop; the failed endpoint's row records the
gap (``ok=False`` + ``stale_s``) and its disappearance counts into the
churn rate. The collector never raises out of its poll loop.

One poll round also emits one ``fleet_sample`` event onto the bus, so a
configured journal retains the fleet story and the streaming anomaly
detector sees the derived gauges the moment they are computed —
:func:`~hpbandster_tpu.obs.anomaly.scan_records` over a series file
replays the same rules offline (tested parity).

Like ``health.py``, this module is transport-lazy: ``parallel/rpc.py``
imports only inside the default fetcher, so the obs substrate stays
stdlib-only at import.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.device_metrics import device_metric_fields
from hpbandster_tpu.obs.journal import JsonlJournal
from hpbandster_tpu.obs.metrics import MetricsRegistry, get_metrics

__all__ = [
    "EndpointState",
    "FleetCollector",
    "derive_fleet",
    "format_fleet_table",
    "read_series",
    "read_series_tail",
    "tenant_counters",
]

logger = E.logger


def _rpc_fetch(uri: str, timeout: float) -> Dict[str, Any]:
    """Default snapshot fetcher: one ``obs_snapshot`` RPC with its own
    socket timeout (connect and read both bounded — a hung peer costs
    ``timeout`` seconds, not a stalled collector)."""
    # lazy: the obs substrate never pulls in the RPC transport at import
    from hpbandster_tpu.parallel.rpc import RPCProxy

    snap = RPCProxy(uri, timeout=timeout).call("obs_snapshot")
    if not isinstance(snap, dict):
        raise ValueError(f"obs_snapshot from {uri} returned {type(snap).__name__}")
    return snap


class EndpointState:
    """Per-endpoint staleness bookkeeping (one instance per known URI)."""

    __slots__ = (
        "name", "uri", "ok", "ever_ok", "last_ok_mono", "last_error",
        "consecutive_failures", "last_snapshot", "last_counters",
    )

    def __init__(self, name: str, uri: str):
        self.name = name
        self.uri = uri
        self.ok = False
        self.ever_ok = False
        self.last_ok_mono: Optional[float] = None
        self.last_error: Optional[str] = None
        self.consecutive_failures = 0
        self.last_snapshot: Optional[Dict[str, Any]] = None
        #: counters cut at the last successful poll (rate math)
        self.last_counters: Dict[str, float] = {}

    def stale_s(self, now_mono: float) -> Optional[float]:
        """Seconds since the last successful poll; None if never polled
        successfully (a peer that has not come up yet is not *stale*)."""
        if self.last_ok_mono is None:
            return None
        return max(now_mono - self.last_ok_mono, 0.0)


def _num(x: Any) -> Optional[float]:
    if isinstance(x, bool) or not isinstance(x, (int, float)):
        return None
    return float(x) if math.isfinite(x) else None


def tenant_counters(
    counters: Mapping[str, Any], field: str = "configs_done"
) -> Dict[str, Any]:
    """``{tenant: value}`` for every ``serve.tenant.<t>.<field>`` counter
    — the ONE parser of the serving tier's per-tenant metric names
    (serve/pool.py emits them; this module and summarize's watch line
    both read them)."""
    prefix, suffix = "serve.tenant.", f".{field}"
    out: Dict[str, Any] = {}
    for name, value in counters.items():
        if name.startswith(prefix) and name.endswith(suffix):
            tenant = name[len(prefix):-len(suffix)]
            if tenant:
                out[tenant] = value
    return out


def lane_gauges(gauges: Mapping[str, Any]) -> Dict[str, Any]:
    """``{field: value}`` for the continuous-batching lane gauges
    (``serve/continuous.py``): ``total``/``occupied``/``starved`` from
    ``serve.lanes.*``, ``occupancy`` from ``serve.lane_occupancy``, and
    ``warm_age_s`` = the OLDEST family's program-warm age (plus
    ``families``, the resident-program count) from the
    ``serve.family.<f>.warm_age_s`` family. THE one parser of these
    names — the ``top`` lane line and ``watch --snapshot``'s lanes part
    both read through it."""
    out: Dict[str, Any] = {}
    ages = []
    for name, value in (gauges or {}).items():
        if not isinstance(name, str):
            continue
        v = _num(value)
        if v is None:
            continue
        if name == "serve.lane_occupancy":
            out["occupancy"] = v
        elif name.startswith("serve.lanes."):
            out[name[len("serve.lanes."):]] = v
        elif name.startswith("serve.family.") and name.endswith(
            ".warm_age_s"
        ):
            ages.append(v)
    if ages:
        out["warm_age_s"] = max(ages)
        out["families"] = len(ages)
    return out


def slo_gauges(gauges: Mapping[str, Any]) -> Dict[str, Any]:
    """``{worst_burn_rate, firing, slos}`` from the SLO gauge plane
    (``obs/alerts.py`` publishes ``slo.<name>.{burn_rate,
    budget_remaining,state}``): worst burn rate across specs, count of
    specs currently firing (state >= 2 per ``alerts.STATE_CODES``), and
    the spec census. THE one parser of these names — the ``top`` fleet
    SLO line, ``watch --snapshot``'s slo column, and the endpoint row
    all read through it. Empty dict when the endpoint runs no
    AlertManager, so SLO-free fleets render exactly as before."""
    worst: Optional[float] = None
    firing = 0
    slos = 0
    for name, value in (gauges or {}).items():
        if not isinstance(name, str) or not name.startswith("slo."):
            continue
        v = _num(value)
        if v is None:
            continue
        if name.endswith(".state"):
            slos += 1
            if v >= 2:
                firing += 1
        elif name.endswith(".burn_rate"):
            if worst is None or v > worst:
                worst = v
    if slos == 0 and worst is None:
        return {}
    return {"worst_burn_rate": worst, "firing": firing, "slos": slos}


def _endpoint_row(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Distill one ``obs_snapshot`` into the per-endpoint series row: the
    handful of fields fleet aggregation and ``top`` actually read."""
    metrics = snap.get("metrics") or {}
    gauges = metrics.get("gauges") or {}
    counters = metrics.get("counters") or {}
    runtime = snap.get("runtime") or {}
    compile_led = runtime.get("compile") or {}
    devices = (runtime.get("devices") or {}).get("devices") or {}
    dev_rows = {}
    for i, d in devices.items():
        if isinstance(d, dict):
            dev_rows[str(i)] = {
                k: d.get(k)
                for k in ("bytes_in_use", "bytes_limit", "live_bytes")
                if d.get(k) is not None
            }
    alerts = snap.get("alerts") or {}
    # serving-tier census (serve/pool.py): per-tenant configs_done
    # counters fold into one {tenant: done} map per endpoint — what the
    # fleet fairness ratio and the `top` tenant column aggregate
    tenants: Dict[str, float] = {}
    for tenant, value in tenant_counters(counters).items():
        v = _num(value)
        if v is not None:
            tenants[tenant] = v
    # sharded-sweep balance census (parallel/multihost.py
    # publish_device_balance): per-device config counts fold into
    # {device: {configs, pad_rows}} — what fleet.device_compute_skew
    # aggregates across endpoints
    sweep_devices: Dict[str, Dict[str, float]] = {}
    for name, value in gauges.items():
        if not name.startswith("sweep.device."):
            continue
        dev, _, field = name[len("sweep.device."):].partition(".")
        v = _num(value)
        if dev and field and v is not None:
            sweep_devices.setdefault(dev, {})[field] = v
    # device metrics plane (obs/device_metrics.py): the last sweep's
    # decoded in-trace telemetry totals — what `top` renders as the
    # device-telemetry line and watch --snapshot appends per row (ONE
    # gauge-name parser, shared with the watch renderer)
    device_metrics = device_metric_fields(gauges)
    # continuous-batching lane census (serve/continuous.py): occupancy,
    # starved lanes and program-warm age — the `top` lane line and the
    # watch lanes part (ONE parser, lane_gauges)
    lanes = lane_gauges(gauges)
    # SLO plane (obs/alerts.py): worst burn rate + firing count per
    # endpoint — what the fleet verdict rolls up (ONE parser, slo_gauges)
    slo = slo_gauges(gauges)
    return {
        "component": snap.get("component"),
        "uptime_s": _num(snap.get("uptime_s")),
        "in_flight": snap.get("in_flight"),
        "workers_alive": _num(gauges.get("dispatcher.workers_alive")),
        "queue_depth": _num(gauges.get("dispatcher.queue_depth")),
        "jobs_in_flight": _num(gauges.get("dispatcher.jobs_in_flight")),
        "workers_dropped": _num(counters.get("dispatcher.workers_dropped")),
        "compiles": _num(counters.get("runtime.compiles"))
        or _num(compile_led.get("total_compiles")),
        "top_recompilers": _top_recompilers(compile_led),
        "devices": dev_rows,
        "sweep_devices": sweep_devices,
        "device_metrics": device_metrics,
        "lanes": lanes,
        "slo": slo,
        "alerts_total": _num(alerts.get("total")),
        "tenants": tenants,
    }


def _top_recompilers(compile_led: Dict[str, Any], k: int = 3) -> List[Dict[str, Any]]:
    fns = compile_led.get("functions") or {}
    rows = [
        {"fn": fn, "compiles": int(slot.get("compiles") or 0)}
        for fn, slot in fns.items()
        if isinstance(slot, dict)
    ]
    rows.sort(key=lambda r: (-r["compiles"], r["fn"]))
    return rows[:k]


def _device_balance(
    rows: Mapping[str, Dict[str, Any]]
) -> Tuple[Optional[float], Optional[float]]:
    """(utilization, skew) over every device of every polled endpoint.

    Utilization is fleet bytes-in-use / bytes-limit where the backend
    reports memory stats (TPU/GPU). Skew is (max-min)/max over each
    device's *busy* bytes — ``bytes_in_use`` when available, else the
    ``live_bytes`` census (the CPU-visible signal) — the imbalance
    number a config-sharded mesh must hold near zero.
    """
    in_use_total = 0.0
    limit_total = 0.0
    busy: List[float] = []
    for row in rows.values():
        for d in (row.get("devices") or {}).values():
            iu = _num(d.get("bytes_in_use"))
            lim = _num(d.get("bytes_limit"))
            lv = _num(d.get("live_bytes"))
            if iu is not None and lim:
                in_use_total += iu
                limit_total += lim
            b = iu if iu is not None else lv
            if b is not None:
                busy.append(b)
    utilization = (in_use_total / limit_total) if limit_total else None
    skew = None
    if busy:
        hi = max(busy)
        skew = 0.0 if hi <= 0 else (hi - min(busy)) / hi
    return utilization, skew


def _compute_balance(rows: Mapping[str, Dict[str, Any]]) -> Optional[float]:
    """Worst PER-ENDPOINT (max-min)/max over per-device sharded-sweep
    config counts — the compute-balance sibling of
    :func:`_device_balance`'s memory skew.

    Config counts are only comparable WITHIN one sweep: pooling absolute
    counts across endpoints would read two perfectly balanced sweeps of
    different sizes (a 1M run next to a 10k run) as severe imbalance. So
    the skew is computed per endpoint (each endpoint's gauges describe
    its own most recent sharded sweep) and the fleet gauge is the worst
    of them. SPMD meshes step in lockstep, so row-count imbalance is
    step-time imbalance; None when no endpoint has published sweep
    balance gauges."""
    worst: Optional[float] = None
    for row in rows.values():
        configs = [
            c
            for dv in (row.get("sweep_devices") or {}).values()
            if (c := _num(dv.get("configs"))) is not None
        ]
        if not configs:
            continue
        hi = max(configs)
        skew = 0.0 if hi <= 0 else (hi - min(configs)) / hi
        worst = skew if worst is None else max(worst, skew)
    return worst


def derive_fleet(
    rows: Mapping[str, Dict[str, Any]],
    ok: int,
    stale: int,
    lost: int,
    churn_events: int,
) -> Dict[str, Any]:
    """Fold per-endpoint rows into the derived fleet gauges of one round.

    Pure function of its inputs (no clocks, no registry) so the offline
    scan and the tests compute exactly what the live collector publishes;
    rate/trend fields are filled in by the collector, which owns the
    window."""
    utilization, skew = _device_balance(rows)
    compute_skew = _compute_balance(rows)

    def _sum(field: str) -> Optional[float]:
        vals = [_num(r.get(field)) for r in rows.values()]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    # the dispatcher's workers_alive gauge is ping-loop-paced (can lag a
    # whole ping_interval behind); the endpoint census itself is the
    # fallback truth — every ok endpoint announcing component=worker
    workers_alive = _sum("workers_alive")
    if workers_alive is None and rows:
        workers_alive = float(sum(
            1 for r in rows.values()
            if r.get("ok") and r.get("component") == "worker"
        ))

    # multi-tenant fairness (serve/pool.py): cumulative configs_done per
    # tenant summed over every endpoint; the max/min ratio is the fleet's
    # one-number fairness gauge (1.0 = perfectly even service; None with
    # <2 tenants or before the slowest tenant's first delivery — a ratio
    # over a zero denominator would read as infinite unfairness during
    # warmup, which is noise, not signal)
    tenant_done: Dict[str, float] = {}
    for r in rows.values():
        for tenant, done in (r.get("tenants") or {}).items():
            v = _num(done)
            if v is not None:
                tenant_done[tenant] = tenant_done.get(tenant, 0.0) + v
    ratio = None
    if len(tenant_done) >= 2 and min(tenant_done.values()) > 0:
        ratio = round(
            max(tenant_done.values()) / min(tenant_done.values()), 4
        )
    # the ratio's None-during-warmup blind spot must not hide PERMANENT
    # starvation: tenants stuck at zero while others progress get their
    # own count, so an alert can fire on exactly the case the ratio
    # cannot express
    starved = None
    if tenant_done:
        starved = (
            sum(1 for v in tenant_done.values() if v == 0)
            if any(v > 0 for v in tenant_done.values()) else 0
        )

    # fleet SLO verdict: worst burn rate across every endpoint's specs,
    # total firing count — one number pair that says whether the fleet
    # is inside its objectives (None when no endpoint runs SLOs)
    slo_worst: Optional[float] = None
    slo_firing: Optional[float] = None
    for r in rows.values():
        s = r.get("slo") or {}
        w = _num(s.get("worst_burn_rate"))
        if w is not None and (slo_worst is None or w > slo_worst):
            slo_worst = w
        f = _num(s.get("firing"))
        if f is not None:
            slo_firing = (slo_firing or 0.0) + f

    return {
        "endpoints": len(rows),
        "ok": ok,
        "stale": stale,
        "lost": lost,
        "churn_events": churn_events,
        "workers_alive": workers_alive,
        "queue_depth": _sum("queue_depth"),
        "jobs_in_flight": _sum("jobs_in_flight"),
        "compiles": _sum("compiles"),
        "device_mem_utilization": (
            round(utilization, 4) if utilization is not None else None
        ),
        "device_mem_skew": round(skew, 4) if skew is not None else None,
        "device_compute_skew": (
            round(compute_skew, 4) if compute_skew is not None else None
        ),
        "tenants": len(tenant_done) if tenant_done else None,
        "tenants_starved": starved,
        "tenant_throughput_ratio": ratio,
        "slo_worst_burn_rate": (
            round(slo_worst, 4) if slo_worst is not None else None
        ),
        "slo_firing": int(slo_firing) if slo_firing is not None else None,
    }


EndpointSpec = Union[
    Sequence[str],
    Mapping[str, str],
    Callable[[], Mapping[str, str]],
]


class FleetCollector:
    """Poll ``obs_snapshot`` endpoints into a windowed fleet time series.

    ``endpoints`` is a list of URIs, a ``{name: uri}`` mapping, or a
    zero-arg callable returning one — the callable form is how the
    master tracks an *elastic* fleet (workers join and leave between
    rounds; the collector re-reads the listing every round and keeps
    staleness state per URI).

    ``poll_once()`` runs one round synchronously (tests and the ``top``
    CLI drive it directly); ``start()`` spawns the daemon poll thread.
    Every round is bounded: each endpoint gets its own ``timeout_s``
    socket timeout, failures are recorded as the gap they are, and
    nothing propagates out of the loop.
    """

    def __init__(
        self,
        endpoints: EndpointSpec,
        interval_s: float = 2.0,
        series_path: Optional[str] = None,
        timeout_s: Optional[float] = None,
        window: int = 256,
        stale_after_s: Optional[float] = None,
        churn_window_s: float = 600.0,
        lost_after_failures: int = 2,
        registry: Optional[MetricsRegistry] = None,
        bus: Optional[E.EventBus] = None,
        series_max_bytes: int = 16 * 1024 * 1024,
        series_max_files: int = 3,
        fetch: Optional[Callable[[str, float], Dict[str, Any]]] = None,
    ):
        self.interval_s = max(float(interval_s), 0.05)
        #: per-endpoint socket timeout; defaults to the poll interval
        #: (capped at 5 s) so one hung peer cannot eat multiple rounds
        self.timeout_s = (
            float(timeout_s) if timeout_s is not None
            else min(max(self.interval_s, 0.5), 5.0)
        )
        #: an endpoint unpolled this long is *stale* even if the last
        #: attempt nominally succeeded (default: 3 poll intervals)
        self.stale_after_s = (
            float(stale_after_s) if stale_after_s is not None
            else 3.0 * self.interval_s
        )
        self.churn_window_s = float(churn_window_s)
        #: consecutive failed polls before a once-ok endpoint counts as a
        #: churn event — one missed round is routinely a GIL stall (the
        #: peer's reply thread blocked behind an XLA compile), not a death
        self.lost_after_failures = max(int(lost_after_failures), 1)
        self._endpoints_spec = endpoints
        self._registry = registry
        self._bus = bus
        self._fetch = fetch if fetch is not None else _rpc_fetch
        self._lock = threading.Lock()
        self._states: Dict[str, EndpointState] = {}
        self._window: collections.deque = collections.deque(
            maxlen=max(int(window), 2)
        )
        #: monotonic stamps of churn events (drops + endpoint losses)
        self._churn_times: collections.deque = collections.deque(maxlen=1024)
        self._seq = 0
        self._journal: Optional[JsonlJournal] = None
        if series_path is not None:
            self._journal = JsonlJournal(
                series_path, max_bytes=series_max_bytes,
                max_files=series_max_files,
            )
        self.series_path = series_path
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool: Optional[Any] = None

    # ------------------------------------------------------------- endpoints
    def _resolve_endpoints(self) -> Dict[str, str]:
        spec = self._endpoints_spec
        try:
            listing = spec() if callable(spec) else spec
        except Exception:
            # an elastic listing source mid-teardown must not kill the loop
            logger.exception("fleet collector endpoint listing failed")
            with self._lock:
                return {name: st.uri for name, st in self._states.items()}
        if isinstance(listing, Mapping):
            return {str(k): str(v) for k, v in listing.items()}
        return {str(u): str(u) for u in (listing or ())}

    # ----------------------------------------------------------------- round
    def poll_once(self) -> Dict[str, Any]:
        """One poll round; returns (and retains) the ``fleet_sample``
        record that was written/emitted."""
        now_mono = time.monotonic()
        listing = self._resolve_endpoints()
        unlisted_lost = 0
        with self._lock:
            for name, uri in listing.items():
                st = self._states.get(name)
                if st is None or st.uri != uri:
                    # a same-name listing whose URI moved is a restart:
                    # the old endpoint is gone, which is churn exactly
                    # like an unlisted one (a worker flapping onto a
                    # fresh port each cycle must not read as zero churn)
                    if st is not None and st.ever_ok:
                        self._churn_times.append(now_mono)
                        unlisted_lost += 1
                    self._states[name] = EndpointState(name, uri)
            # unlisted endpoints are forgotten — an elastic fleet shrinks;
            # a vanished-but-once-ok endpoint counts as churn (tallied
            # into this round's lost/churn_events below, so the sample's
            # fields agree with the rate they feed)
            for name in list(self._states):
                if name not in listing:
                    st = self._states.pop(name)
                    if st.ever_ok:
                        self._churn_times.append(now_mono)
                        unlisted_lost += 1
            targets = list(self._states.values())

        rows: Dict[str, Dict[str, Any]] = {}
        ok = stale = streak_lost = 0
        drops_delta = 0.0
        # endpoints poll CONCURRENTLY: N unreachable peers cost one
        # socket timeout of wall clock, not N serial ones — the round
        # stays bounded however much of the fleet is down (each endpoint
        # has exactly one poller thread; state writes don't race)
        if len(targets) > 1:
            results = list(self._ensure_pool().map(
                self._poll_endpoint, targets))
        else:
            results = [self._poll_endpoint(st) for st in targets]
        for st, (row, churned, drop_delta) in zip(targets, results):
            rows[st.name] = row
            if row["ok"]:
                ok += 1
            if churned:
                streak_lost += 1
            drops_delta += drop_delta
            stale_s = row.get("stale_s")
            if stale_s is not None and stale_s > self.stale_after_s:
                stale += 1
        # losses this round = endpoints unlisted (stamped above) +
        # failure streaks; worker drops observed by the dispatcher count
        # into the same churn stream (one monotonic stamp per event —
        # unlisted stamps were already appended in the listing block)
        lost = unlisted_lost + streak_lost
        churn_events = lost + int(drops_delta)
        now_mono = time.monotonic()
        with self._lock:
            for _ in range(int(drops_delta) + streak_lost):
                self._churn_times.append(now_mono)
            churn_per_min = self._churn_per_min_locked(now_mono)

        fleet = derive_fleet(rows, ok=ok, stale=stale, lost=lost,
                             churn_events=churn_events)
        fleet["worker_churn_per_min"] = churn_per_min
        sample = {
            "event": "fleet_sample",
            "t_wall": time.time(),
            "t_mono": now_mono,
            "seq": self._seq,
            "fleet": fleet,
            "endpoints": {name: rows[name] for name in sorted(rows)},
        }
        self._seq += 1
        with self._lock:
            self._window.append(sample)
            trend, compile_rate = self._trends_locked()
        fleet["queue_depth_trend_per_min"] = trend
        fleet["compile_rate_per_min"] = compile_rate
        self._publish(fleet)
        if self._journal is not None:
            try:
                # sort_keys: two collectors over the same fleet state
                # produce byte-identical lines (modulo clocks) — the
                # determinism bar the series readers rely on
                self._journal.write_record(_sorted_record(sample))
            except Exception:
                logger.exception("fleet series write failed")
        bus = self._bus if self._bus is not None else E.get_bus()
        bus.emit(E.FLEET_SAMPLE, **_flat_fields(sample))
        return sample

    def _poll_endpoint(
        self, st: EndpointState
    ) -> Tuple[Dict[str, Any], bool, float]:
        """Poll one endpoint; returns (series row, lost-this-round,
        dispatcher worker-drop delta). Never raises."""
        snap: Optional[Dict[str, Any]] = None
        distilled: Optional[Dict[str, Any]] = None
        t0 = time.monotonic()
        try:
            snap = self._fetch(st.uri, self.timeout_s)
            # distilling INSIDE the try: a version-skewed peer answering
            # with an unexpected structure is a gap, not a collector
            # crash — the 'never raises' contract covers both steps
            distilled = _endpoint_row(snap)
        except Exception as e:
            st.ok = False
            st.consecutive_failures += 1
            st.last_error = f"{type(e).__name__}: {e}"
        drop_delta = 0.0
        if distilled is not None:
            st.ok = True
            st.ever_ok = True
            st.consecutive_failures = 0
            st.last_error = None
            st.last_ok_mono = time.monotonic()
            st.last_snapshot = snap
        now_mono = time.monotonic()
        row: Dict[str, Any] = {
            "uri": st.uri,
            "ok": st.ok,
            "poll_s": round(now_mono - t0, 6),
            "stale_s": (
                round(st.stale_s(now_mono), 3)
                if st.stale_s(now_mono) is not None else None
            ),
            "consecutive_failures": st.consecutive_failures,
            "error": st.last_error,
        }
        if distilled is not None:
            dropped = _num(distilled.get("workers_dropped"))
            if dropped is not None:
                prev = st.last_counters.get("workers_dropped")
                if prev is not None and dropped > prev:
                    drop_delta = dropped - prev
                st.last_counters["workers_dropped"] = dropped
            row.update(distilled)
        # a churn event fires once per failure STREAK, and only after
        # lost_after_failures consecutive misses of a once-ok endpoint
        # (one missed round is routinely a peer GIL-stalled in a compile)
        lost = (
            st.ever_ok
            and st.consecutive_failures == self.lost_after_failures
        )
        return row, lost, drop_delta

    # ------------------------------------------------------------- windows
    def _churn_per_min_locked(self, now_mono: float) -> float:
        while self._churn_times and now_mono - self._churn_times[0] > self.churn_window_s:
            self._churn_times.popleft()
        # fixed-window denominator: events / churn_window_s — a freshly
        # started collector must not report one early drop as a storm
        return round(len(self._churn_times) * 60.0 / self.churn_window_s, 4)

    def _trends_locked(self) -> Tuple[Optional[float], Optional[float]]:
        """(queue-depth slope per minute, compile rate per minute) over
        the in-memory window — newest minus oldest over elapsed time,
        which is robust to missed rounds in a way per-round deltas are
        not."""
        if len(self._window) < 2:
            return None, None
        first, last = self._window[0], self._window[-1]
        dt = last["t_mono"] - first["t_mono"]
        if dt <= 0:
            return None, None

        def rate(field: str, monotone: bool) -> Optional[float]:
            a = _num(first["fleet"].get(field))
            b = _num(last["fleet"].get(field))
            if a is None or b is None:
                return None
            delta = b - a
            if monotone and delta < 0:
                # a counter went backwards: an endpoint restarted — treat
                # the window as unmeasurable rather than report negative
                return None
            return round(delta * 60.0 / dt, 4)

        return rate("queue_depth", False), rate("compiles", True)

    def _publish(self, fleet: Dict[str, Any]) -> None:
        reg = self._registry if self._registry is not None else get_metrics()
        for field, gauge in (
            ("endpoints", "fleet.endpoints"),
            ("ok", "fleet.endpoints_ok"),
            ("stale", "fleet.endpoints_stale"),
            ("workers_alive", "fleet.workers_alive"),
            ("queue_depth", "fleet.queue_depth"),
            ("jobs_in_flight", "fleet.jobs_in_flight"),
            ("device_mem_utilization", "fleet.device_mem_utilization"),
            ("device_mem_skew", "fleet.device_mem_skew"),
            ("device_compute_skew", "fleet.device_compute_skew"),
            ("worker_churn_per_min", "fleet.worker_churn_per_min"),
            ("queue_depth_trend_per_min", "fleet.queue_depth_trend_per_min"),
            ("compile_rate_per_min", "fleet.compile_rate_per_min"),
            ("tenants", "fleet.tenants"),
            ("tenants_starved", "fleet.tenants_starved"),
            ("tenant_throughput_ratio", "fleet.tenant_throughput_ratio"),
            ("slo_worst_burn_rate", "fleet.slo_worst_burn_rate"),
            ("slo_firing", "fleet.slo_firing"),
        ):
            v = _num(fleet.get(field))
            if v is not None:
                reg.gauge(gauge).set(v)
            else:
                # a gauge whose source became unmeasurable must be
                # dropped, not frozen: a dead dispatcher's last
                # queue_depth would otherwise serve as live forever
                reg.remove(gauge)
        reg.counter("fleet.poll_rounds").inc()

    def _ensure_pool(self) -> Any:
        """The persistent poller pool: threads are reused across rounds
        (per-round executor spawn/join would tax every 2 s duty cycle
        for the life of the sweep). Capacity 16 caps the concurrent
        socket count; threads only materialize on demand."""
        with self._lock:
            if self._pool is None:
                import concurrent.futures

                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="obs-fleet-poll"
                )
            return self._pool

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetCollector":
        """Spawn the daemon poll thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="obs-fleet-collector"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.poll_once()
            except Exception:
                # the observatory must never take the fleet down
                logger.exception("fleet collector poll round failed")
            # drift-free cadence: the round's own cost comes out of the
            # wait, so the effective period stays ~interval_s even when
            # part of the fleet is timing out
            elapsed = time.monotonic() - t0
            if self._stop.wait(max(self.interval_s - elapsed, 0.05)):
                return

    def stop(self) -> None:
        """Stop the poll thread and close the series file (idempotent)."""
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
            pool, self._pool = self._pool, None
        if thread is not None:
            thread.join(timeout=max(2 * self.timeout_s, 5.0))
        if pool is not None:
            # don't block on a hung peer's in-flight poll; its thread is
            # daemon-irrelevant once the loop is down
            pool.shutdown(wait=False)
        if self._journal is not None:
            self._journal.close()

    def __enter__(self) -> "FleetCollector":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # ----------------------------------------------------------- inspection
    def window(self) -> List[Dict[str, Any]]:
        """Oldest-first copy of the in-memory sample window."""
        with self._lock:
            return list(self._window)

    def last_sample(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._window[-1] if self._window else None

    def last_snapshots(self) -> Dict[str, Optional[Dict[str, Any]]]:
        """Newest full ``obs_snapshot`` per endpoint (None before the
        first success) — what ``watch --snapshot`` renders per row."""
        with self._lock:
            return {
                name: st.last_snapshot
                for name, st in sorted(self._states.items())
            }

    def endpoint_states(self) -> Dict[str, Dict[str, Any]]:
        """Staleness view per endpoint (the ``watch --snapshot`` merge)."""
        now = time.monotonic()
        with self._lock:
            return {
                name: {
                    "uri": st.uri,
                    "ok": st.ok,
                    "stale_s": st.stale_s(now),
                    "consecutive_failures": st.consecutive_failures,
                    "error": st.last_error,
                }
                for name, st in sorted(self._states.items())
            }


def _sorted_record(x: Any) -> Any:
    """Recursively key-sort dicts so a series line's byte layout is a
    function of its content only."""
    if isinstance(x, dict):
        return {k: _sorted_record(x[k]) for k in sorted(x)}
    if isinstance(x, (list, tuple)):
        return [_sorted_record(v) for v in x]
    return x


def _flat_fields(sample: Dict[str, Any]) -> Dict[str, Any]:
    """The bus-event form of one sample: derived fleet gauges flattened
    to top-level fields (what the anomaly rules key on) plus the compact
    endpoint census."""
    fleet = sample["fleet"]
    return {
        "seq": sample["seq"],
        **{k: v for k, v in fleet.items()},
        "endpoint_names": sorted(sample["endpoints"]),
    }


def read_series(path: str) -> List[Dict[str, Any]]:
    """Read a (possibly rotated) series file, oldest first — the same
    reader contract as run journals (corrupt lines skipped)."""
    from hpbandster_tpu.obs.journal import read_journal

    return [r for r in read_journal(path) if r.get("event") == "fleet_sample"]


def read_series_tail(
    path: str, max_scan_bytes: int = 1 << 20
) -> Optional[Dict[str, Any]]:
    """Newest ``fleet_sample`` without parsing the whole rotated set —
    a refreshing dashboard needs one frame per tick, not the history.
    Scans only the live file's final bytes; falls back to the full
    :func:`read_series` when the live file holds no complete sample
    (e.g. freshly rotated)."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(size - max_scan_bytes, 0))
            chunk = f.read()
    except OSError:
        chunk = b""
    for line in reversed(chunk.splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            # a truncated first line of the scan window, or a corrupt
            # line — same skip contract as read_journal
            continue
        if isinstance(rec, dict) and rec.get("event") == "fleet_sample":
            return rec
    recs = read_series(path)
    return recs[-1] if recs else None


# ------------------------------------------------------------------ top view
def _fmt(v: Any, nd: int = 0, dash: str = "-") -> str:
    if v is None:
        return dash
    if isinstance(v, float):
        return f"{v:.{nd}f}" if nd else f"{v:g}"
    return str(v)


def format_fleet_table(
    sample: Dict[str, Any], tenant: Optional[str] = None
) -> str:
    """Render one ``fleet_sample`` as the ``obs top`` fleet table.

    ``tenant`` narrows the view to endpoints serving that tenant; the
    per-endpoint ``tenants`` column then shows the tenant's own
    ``configs_done`` instead of the serving tenant count.
    """
    fleet = sample.get("fleet") or {}
    lines = [
        "fleet: endpoints {}/{} ok ({} stale)  workers={}  queue={}  "
        "in_flight={}".format(
            _fmt(fleet.get("ok")), _fmt(fleet.get("endpoints")),
            _fmt(fleet.get("stale")), _fmt(fleet.get("workers_alive")),
            _fmt(fleet.get("queue_depth")), _fmt(fleet.get("jobs_in_flight")),
        ),
        "       mem_util={}  mem_skew={}  compute_skew={}  churn/min={}  "
        "queue_trend/min={}  compiles/min={}".format(
            _fmt(fleet.get("device_mem_utilization"), 3),
            _fmt(fleet.get("device_mem_skew"), 3),
            _fmt(fleet.get("device_compute_skew"), 3),
            _fmt(fleet.get("worker_churn_per_min"), 2),
            _fmt(fleet.get("queue_depth_trend_per_min"), 2),
            _fmt(fleet.get("compile_rate_per_min"), 2),
        ),
    ]
    if fleet.get("tenants") is not None or tenant is not None:
        lines.append(
            "       tenants={}  throughput_ratio={}{}".format(
                _fmt(fleet.get("tenants")),
                _fmt(fleet.get("tenant_throughput_ratio"), 2),
                f"  [filter: tenant={tenant}]" if tenant else "",
            )
        )
    # device-telemetry section: aggregate the per-endpoint last-sweep
    # in-trace counters (obs/device_metrics.py) — present only when at
    # least one endpoint published them, so telemetry-free fleets render
    # exactly as before
    dm_rows = [
        row.get("device_metrics")
        for row in (sample.get("endpoints") or {}).values()
        if row.get("device_metrics")
    ]
    if dm_rows:
        evals = sum(int(r.get("evaluations", 0)) for r in dm_rows)
        crashes = sum(int(r.get("crashes", 0)) for r in dm_rows)
        rounds = sum(int(r.get("rounds", 0)) for r in dm_rows)
        fits = sum(int(r.get("model_fits", 0)) for r in dm_rows)
        lines.append(
            "       device_telemetry: evals={}  crashed={}{}  rounds={}  "
            "model_fits={}".format(
                evals, crashes,
                " ({:.2f}%)".format(100.0 * crashes / evals)
                if evals else "",
                rounds, fits,
            )
        )
    # continuous-batching lane line (serve/continuous.py gauges):
    # present only when an endpoint serves resident lane programs, so
    # lane-free fleets render exactly as before
    lane_rows = [
        row.get("lanes")
        for row in (sample.get("endpoints") or {}).values()
        if row.get("lanes")
    ]
    if lane_rows:
        total = sum(int(r.get("total", 0)) for r in lane_rows)
        occupied = sum(int(r.get("occupied", 0)) for r in lane_rows)
        starved = sum(int(r.get("starved", 0)) for r in lane_rows)
        ages = [
            r.get("warm_age_s") for r in lane_rows
            if isinstance(r.get("warm_age_s"), (int, float))
        ]
        lines.append(
            "       lanes: occupied={}/{}  starved={}  warm_age_s={}"
            .format(
                occupied, total, starved,
                _fmt(max(ages), 1) if ages else "-",
            )
        )
    # SLO verdict line (obs/alerts.py gauges via slo_gauges): present
    # only when an endpoint runs an AlertManager, so SLO-free fleets
    # render exactly as before
    if (
        fleet.get("slo_worst_burn_rate") is not None
        or fleet.get("slo_firing") is not None
    ):
        lines.append(
            "       slo: worst_burn={}  firing={}".format(
                _fmt(fleet.get("slo_worst_burn_rate"), 2),
                _fmt(fleet.get("slo_firing")),
            )
        )
    lines.append("")
    header = (
        f"{'endpoint':<18} {'comp':<10} {'ok':<3} {'up_s':>8} "
        f"{'stale_s':>8} {'inflight':<14} {'alerts':>6} {'compiles':>8} "
        f"{'tenants':>8}  top_recompilers"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for name, row in sorted((sample.get("endpoints") or {}).items()):
        tenants = row.get("tenants") or {}
        if tenant is not None and tenant not in tenants:
            continue
        in_flight = row.get("in_flight")
        if isinstance(in_flight, dict):
            in_flight = ",".join(
                f"{k}={v}" for k, v in sorted(in_flight.items())
                if not isinstance(v, (list, dict))
            ) or "busy"
        recomp = " ".join(
            f"{r['fn']}x{r['compiles']}"
            for r in (row.get("top_recompilers") or [])
        )
        tenant_cell = (
            _fmt(tenants.get(tenant)) if tenant is not None
            else (_fmt(len(tenants)) if tenants else "-")
        )
        lines.append(
            f"{name[:18]:<18} {str(row.get('component') or '?')[:10]:<10} "
            f"{'y' if row.get('ok') else 'N':<3} "
            f"{_fmt(row.get('uptime_s'), 1):>8} "
            f"{_fmt(row.get('stale_s'), 1):>8} "
            f"{str(in_flight if in_flight is not None else '-')[:14]:<14} "
            f"{_fmt(row.get('alerts_total')):>6} "
            f"{_fmt(row.get('compiles')):>8} "
            f"{tenant_cell:>8}  {recomp}"
        )
    return "\n".join(lines)
