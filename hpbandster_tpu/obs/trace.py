"""Trace context — one identity per job, carried across every hop.

A distributed BOHB run is a relay: the master mints a job, the dispatcher
RPCs it to a worker on another host, the worker computes and RPCs the
result back. Each process journals its own half of the story; without a
shared identity those halves can never be re-joined. :class:`TraceContext`
is that identity: a ``run_id`` (the sweep), a ``trace_id`` (one job's
round-trip), and a ``hop`` counter (how many process boundaries the
context has crossed).

Plumbing rules:

* the *current* trace lives in a :mod:`contextvars` ContextVar — emitting
  sites never pass ``trace_id`` by hand (the ``obs-reserved-fields``
  graftlint rule forbids it); :func:`hpbandster_tpu.obs.events.make_event`
  stamps it onto every event automatically;
* across RPC it rides as an optional ``_obs`` envelope field beside
  ``method``/``params`` (``parallel/rpc.py`` injects via
  :func:`current_wire` and extracts via :func:`extract_wire`). Old peers
  ignore the unknown key, so the wire stays backward compatible in both
  directions;
* threads do NOT inherit contextvars — code that hands work to another
  thread (``Worker._rpc_start_computation`` -> compute thread) must
  capture :func:`current_trace` and re-enter it with :func:`use_trace`.

Tenant identity (the serving tier, ``hpbandster_tpu/serve``) follows the
same pattern in a SECOND ContextVar: :func:`use_tenant` makes a tenant id
current, :func:`make_event` stamps it as ``tenant_id`` on every event, and
:func:`current_wire` carries it in the same ``_obs`` envelope so the
dispatcher/worker side of a multi-tenant job journals under the right
tenant. No tenant context means no field anywhere — a single-tenant
journal stays byte-identical to the pre-serving format, and readers treat
a missing ``tenant_id`` as the ``"default"`` tenant (:data:`DEFAULT_TENANT`).


Stdlib-only, like the rest of ``obs``: importing this module pulls in no
jax/numpy and a no-trace :func:`current_wire` is one ContextVar read.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import uuid
from typing import Any, Dict, Iterator, Optional

__all__ = [
    "TraceContext",
    "WIRE_FIELD",
    "DEFAULT_TENANT",
    "new_trace",
    "current_trace",
    "set_trace",
    "reset_trace",
    "use_trace",
    "current_tenant",
    "use_tenant",
    "current_run",
    "use_run",
    "current_wire",
    "extract_wire",
    "extract_tenant",
]

#: the envelope key trace context travels under in RPC messages
WIRE_FIELD = "_obs"

#: what a missing ``tenant_id`` means to every reader (journal filters,
#: report --tenant): the pre-serving single-tenant world IS this tenant
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One job's identity: which run, which job, how many hops so far."""

    run_id: str
    trace_id: str
    hop: int = 0


_CURRENT: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "hpbandster_tpu_obs_trace", default=None
)


def new_trace(run_id: str = "") -> TraceContext:
    """Mint a fresh trace identity (the master does this per job)."""
    return TraceContext(run_id=str(run_id), trace_id=uuid.uuid4().hex[:16], hop=0)


def current_trace() -> Optional[TraceContext]:
    """The trace active in this thread/context, or None."""
    return _CURRENT.get()


def set_trace(ctx: Optional[TraceContext]) -> contextvars.Token:
    """Make ``ctx`` current; returns the token for :func:`reset_trace`."""
    return _CURRENT.set(ctx)


def reset_trace(token: contextvars.Token) -> None:
    _CURRENT.reset(token)


@contextlib.contextmanager
def use_trace(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Run the body under ``ctx``. ``use_trace(None)`` is a no-op passthrough
    (callers never need to branch on 'do I have a trace?')."""
    if ctx is None:
        yield None
        return
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


# ----------------------------------------------------------------- tenant
_TENANT: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "hpbandster_tpu_obs_tenant", default=None
)


def current_tenant() -> Optional[str]:
    """The tenant active in this thread/context, or None (single-tenant)."""
    return _TENANT.get()


@contextlib.contextmanager
def use_tenant(tenant: Optional[str]) -> Iterator[Optional[str]]:
    """Run the body under a tenant identity; events emitted inside carry
    ``tenant_id`` and outgoing RPC envelopes carry ``tenant``.
    ``use_tenant(None)`` is a no-op passthrough, exactly like
    :func:`use_trace` — single-tenant call sites never branch."""
    if tenant is None:
        yield None
        return
    token = _TENANT.set(str(tenant))
    try:
        yield tenant
    finally:
        _TENANT.reset(token)


# -------------------------------------------------------------------- run
#: the run (sweep) active in this thread/context. Unlike the trace (one
#: per JOB) this is one per MASTER drive loop: process-global state that
#: must not bleed between sequential or concurrent sweeps in one process
#: (the promotion-audit straggler ledger, obs/audit.py) keys on it. Not
#: stamped onto events — journal records already carry run identity
#: through their trace context where it matters.
_RUN: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "hpbandster_tpu_obs_run", default=None
)


def current_run() -> Optional[str]:
    """The run id active in this thread/context, or None."""
    run = _RUN.get()
    if run is not None:
        return run
    # inside a job's trace the run identity is already known — the
    # fallback that lets bus sinks (anomaly detector) attribute without
    # their emitter having entered use_run explicitly
    ctx = _CURRENT.get()
    return ctx.run_id if ctx is not None and ctx.run_id else None


@contextlib.contextmanager
def use_run(run_id: Optional[str]) -> Iterator[Optional[str]]:
    """Run the body under a run (sweep) identity. ``use_run(None)`` is a
    no-op passthrough like :func:`use_trace` / :func:`use_tenant`."""
    if run_id is None:
        yield None
        return
    token = _RUN.set(str(run_id))
    try:
        yield run_id
    finally:
        _RUN.reset(token)


# ------------------------------------------------------------------- wire
def current_wire() -> Optional[Dict[str, Any]]:
    """The ``_obs`` envelope for an outgoing RPC: the current trace with
    its hop count advanced (plus the current tenant when one is active),
    or None when neither is set (the common case — two ContextVar reads,
    no allocation)."""
    ctx = _CURRENT.get()
    tenant = _TENANT.get()
    if ctx is None and tenant is None:
        return None
    wire: Dict[str, Any] = {}
    if ctx is not None:
        wire.update(
            run_id=ctx.run_id, trace_id=ctx.trace_id, hop=ctx.hop + 1
        )
    if tenant is not None:
        wire["tenant"] = tenant
    return wire


def extract_wire(wire: Any) -> Optional[TraceContext]:
    """Parse an incoming ``_obs`` envelope into a :class:`TraceContext`.

    Tolerant by contract: a missing, malformed, or future-shaped envelope
    yields None — a telemetry field must never fail an RPC."""
    if not isinstance(wire, dict):
        return None
    trace_id = wire.get("trace_id")
    if not isinstance(trace_id, str) or not trace_id:
        return None
    run_id = wire.get("run_id")
    hop = wire.get("hop")
    return TraceContext(
        run_id=run_id if isinstance(run_id, str) else "",
        trace_id=trace_id,
        hop=hop if isinstance(hop, int) and hop >= 0 else 0,
    )


def extract_tenant(wire: Any) -> Optional[str]:
    """The tenant id of an incoming ``_obs`` envelope, or None.

    Same tolerance contract as :func:`extract_wire`: a missing, malformed,
    or tenant-less envelope (every pre-serving peer) is simply no tenant.
    """
    if not isinstance(wire, dict):
        return None
    tenant = wire.get("tenant")
    return tenant if isinstance(tenant, str) and tenant else None
