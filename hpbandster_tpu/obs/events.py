"""Structured event bus + monotonic spans — the tracing substrate.

Design constraints (the acceptance bar in docs/observability.md):

* **no-sink cost ~ zero**: ``emit()`` with no subscriber is one tuple
  read + one boolean check and returns before an :class:`Event` is even
  constructed — the instrumented hot paths (master submit loop, batched
  waves, RPC retries) pay nothing when nobody is listening;
* **thread-safe without emit-side locking**: the sink list is a
  copy-on-write tuple, so emitters read it with one atomic load while
  subscribe/unsubscribe swap whole tuples under the bus lock;
* **monotonic durations**: spans measure with ``time.monotonic()`` —
  immune to wall-clock jumps — and carry ``time.time()`` alongside only
  for human-readable journal ordering (the same wall/mono split
  ``core.job.Job`` records).

The span backend folds in ``utils/profiling.py``: after
:func:`use_jax_annotations` every span additionally opens a
``jax.profiler.TraceAnnotation`` so the named region shows up in
TensorBoard/Perfetto device traces. Never emit events from INSIDE jitted
code — that is host work in a traced body; the ``obs-emit-in-jit``
graftlint rule gates the repo on it.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

from hpbandster_tpu.obs.trace import current_tenant, current_trace

__all__ = [
    "Event",
    "EventBus",
    "get_bus",
    "emit",
    "make_event",
    "span",
    "use_jax_annotations",
    "EVENT_TYPES",
    "JOB_SUBMITTED",
    "JOB_STARTED",
    "JOB_FINISHED",
    "JOB_FAILED",
    "WORKER_DISCOVERED",
    "WORKER_DROPPED",
    "BRACKET_PROMOTION",
    "KDE_REFIT",
    "RPC_RETRY",
    "RESULT_DELIVERED",
    "CHECKPOINT_WRITTEN",
    "UNKNOWN_RESULT",
    "CONFIG_SAMPLED",
    "PROMOTION_DECISION",
    "ALERT",
    "XLA_COMPILE",
    "FLEET_SAMPLE",
    "JOB_REQUEUED",
    "RESULT_REPLAYED",
    "DUPLICATE_RESULT",
    "WORKER_QUARANTINED",
    "CHAOS_FAULT",
    "SWEEP_INCUMBENT",
    "DEVICE_TELEMETRY",
    "LANE_ASSIGNED",
    "LANE_RELEASED",
    "RPC_CLIENT_CALL",
    "SLO_ALERT",
]

logger = logging.getLogger("hpbandster_tpu.obs")

# ------------------------------------------------------------- typed events
JOB_SUBMITTED = "job_submitted"
JOB_STARTED = "job_started"
JOB_FINISHED = "job_finished"
JOB_FAILED = "job_failed"
WORKER_DISCOVERED = "worker_discovered"
WORKER_DROPPED = "worker_dropped"
BRACKET_PROMOTION = "bracket_promotion"
KDE_REFIT = "kde_refit"
RPC_RETRY = "rpc_retry"
RESULT_DELIVERED = "result_delivered"
CHECKPOINT_WRITTEN = "checkpoint_written"
UNKNOWN_RESULT = "unknown_result"
#: optimizer decision audit records (obs/audit.py): why a config was
#: sampled, and what a rung promotion decided — the journal's view of the
#: ALGORITHM, not the infrastructure
CONFIG_SAMPLED = "config_sampled"
PROMOTION_DECISION = "promotion_decision"
#: streaming anomaly detector verdicts (obs/anomaly.py)
ALERT = "alert"
#: XLA runtime telemetry (obs/runtime.py): one record per fresh
#: compilation a ``tracked_jit`` boundary observed — fn name, abstract
#: shape signature, compile seconds, per-function recompile count
XLA_COMPILE = "xla_compile"
#: one fleet-collector poll round (obs/collector.py): derived fleet
#: gauges — endpoint census, device balance, churn and trend rates
FLEET_SAMPLE = "fleet_sample"
#: recovery vocabulary (core/recovery.py, docs/fault_tolerance.md): a
#: dispatcher re-queued an orphaned job after its worker died ...
JOB_REQUEUED = "job_requeued"
#: ... a previously-stranded result (WAL record or dead letter) joined
#: back into a live run exactly once ...
RESULT_REPLAYED = "result_replayed"
#: ... a second delivery of an already-ingested result was recognized by
#: its idempotency key and dropped (the exactly-once gate) ...
DUPLICATE_RESULT = "duplicate_result"
#: ... and a flapping worker was quarantined: dropped AND banned from
#: rediscovery until the quarantine expires
WORKER_QUARANTINED = "worker_quarantined"
#: one injected fault from the chaos harness (parallel/chaos.py):
#: kind in {kill, delay, drop, duplicate}
CHAOS_FAULT = "chaos_fault"
#: the resident (incumbent-only) sweep's single device->host payload,
#: journaled: winning vector/loss/bracket plus each bracket's best final
#: loss — the ONLY decision record a sweep whose per-rung decisions
#: never left the device produces (obs/audit.py emit_sweep_incumbent;
#: `obs replay` re-scores it)
SWEEP_INCUMBENT = "sweep_incumbent"
#: one sweep's decoded device-metrics record (obs/device_metrics.py):
#: per-rung log-binned loss histograms, crash/evaluation/promotion
#: counts, KDE-refit tallies and the per-bracket incumbent trail — all
#: accumulated IN-TRACE (ops/sweep.py DeviceMetrics) and decoded on the
#: sweep's final d2h, so fused/resident sweeps feed the obs pipeline
#: without surfacing per-job events
DEVICE_TELEMETRY = "device_telemetry"
#: continuous-batching lane lifecycle (serve/continuous.py): a mesh lane
#: of a resident bucket-family program changed owner — ``lane_assigned``
#: when a lane takes a NEW owner at a chunk boundary (carries
#: ``lane``/``family``/``tenant``; warm re-boardings are silent —
#: ownership is sticky, so the journal records changes, not every
#: chunk), and ``lane_released`` when the owner departs and the lane
#: returns to the free pool
LANE_ASSIGNED = "lane_assigned"
LANE_RELEASED = "lane_released"
#: one client-side RPC round trip (parallel/rpc.py RPCProxy.call): a
#: span-shaped record (``duration_s`` + ``method``) the flight recorder
#: (obs/timeline.py) renders as an RPC-phase hop slice — emitted only
#: when a sink listens, so the no-recorder RPC path pays one
#: ``bus.active`` read and nothing else
RPC_CLIENT_CALL = "rpc_client_call"
#: one SLO alert lifecycle transition (obs/alerts.py AlertManager):
#: pending -> firing -> resolved, each journaled with the burn rates and
#: budget remaining that justified it — timestamps derive from the
#: records that drove the evaluator, so an offline replay of the same
#: journal reproduces the transitions byte-identically
SLO_ALERT = "slo_alert"

#: the core vocabulary (docs/observability.md "Event schema"). emit() also
#: accepts names outside this set — subsystems may add their own (span
#: names, ``bracket_created``, ``sweep_chunk``) without a registry edit.
EVENT_TYPES = frozenset({
    JOB_SUBMITTED, JOB_STARTED, JOB_FINISHED, JOB_FAILED,
    WORKER_DISCOVERED, WORKER_DROPPED, BRACKET_PROMOTION, KDE_REFIT,
    RPC_RETRY, RESULT_DELIVERED, CHECKPOINT_WRITTEN, UNKNOWN_RESULT,
    CONFIG_SAMPLED, PROMOTION_DECISION, ALERT, XLA_COMPILE, FLEET_SAMPLE,
    JOB_REQUEUED, RESULT_REPLAYED, DUPLICATE_RESULT, WORKER_QUARANTINED,
    CHAOS_FAULT, SWEEP_INCUMBENT, DEVICE_TELEMETRY, LANE_ASSIGNED,
    LANE_RELEASED, RPC_CLIENT_CALL, SLO_ALERT,
})

#: process-wide kill switch (hpbandster_tpu.obs.set_enabled)
_ENABLED = True


def _set_enabled(flag: bool) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


@dataclasses.dataclass(frozen=True)
class Event:
    """One structured record: name + wall/monotonic stamps + fields."""

    name: str
    t_wall: float
    t_mono: float
    fields: Dict[str, Any]


Sink = Callable[[Event], None]


def make_event(name: str, fields: Dict[str, Any]) -> Event:
    """Construct one stamped :class:`Event`: wall + monotonic clocks, the
    current trace's ``trace_id`` and the current tenant's ``tenant_id``
    (see :mod:`~hpbandster_tpu.obs.trace`) folded into the fields. The one
    place trace/tenant stamping happens — call sites never pass
    ``trace_id``/``tenant_id`` by hand (``obs-reserved-fields`` rule).
    With no tenant context the field is absent entirely, so single-tenant
    journals stay byte-compatible (readers default it to ``"default"``).
    """
    tc = current_trace()
    if tc is not None and "trace_id" not in fields:
        fields = dict(fields, trace_id=tc.trace_id)
    tenant = current_tenant()
    if tenant is not None and "tenant_id" not in fields:
        fields = dict(fields, tenant_id=tenant)
    return Event(name, time.time(), time.monotonic(), fields)


class EventBus:
    """Fan one emit out to every subscribed sink; sinks must not raise
    (if one does anyway, the error is logged and the other sinks still
    receive the event — telemetry must never kill the run)."""

    def __init__(self):
        self._lock = threading.Lock()
        # copy-on-write: emit() reads the tuple with one atomic load; the
        # lock only serializes subscribe/unsubscribe swaps
        self._sinks: Tuple[Sink, ...] = ()

    @property
    def active(self) -> bool:
        """True when an emit would actually reach a sink."""
        return _ENABLED and bool(self._sinks)  # graftlint: disable=lock-coverage — copy-on-write tuple: an unlocked read sees a complete old/new tuple

    def subscribe(self, sink: Sink) -> Callable[[], None]:
        """Attach ``sink``; returns a detach callable (idempotent)."""
        with self._lock:
            self._sinks = self._sinks + (sink,)

        def detach() -> None:
            with self._lock:
                self._sinks = tuple(s for s in self._sinks if s is not sink)

        return detach

    def emit(self, name: str, **fields: Any) -> Optional[Event]:
        """Deliver one event; returns it, or None when nobody listens.
        The Event (and its trace stamp) is only constructed when a sink
        will actually see it — the no-sink path stays ~free."""
        sinks = self._sinks  # graftlint: disable=lock-coverage — copy-on-write tuple: an unlocked read sees a complete old/new tuple
        if not sinks or not _ENABLED:
            return None
        ev = make_event(name, fields)
        for sink in sinks:
            try:
                sink(ev)
            except Exception:
                logger.exception("obs sink %r failed on %s", sink, name)
        return ev

    def publish(self, ev: Event) -> Optional[Event]:
        """Deliver a pre-built :class:`Event` (e.g. one a worker already
        wrote to its local journal) to the current sinks."""
        sinks = self._sinks  # graftlint: disable=lock-coverage — copy-on-write tuple: an unlocked read sees a complete old/new tuple
        if not sinks or not _ENABLED:
            return None
        for sink in sinks:
            try:
                sink(ev)
            except Exception:
                logger.exception("obs sink %r failed on %s", sink, ev.name)
        return ev


_DEFAULT_BUS = EventBus()


def get_bus() -> EventBus:
    """The process-wide default bus."""
    return _DEFAULT_BUS


def emit(name: str, **fields: Any) -> Optional[Event]:
    """``get_bus().emit(...)`` — the module-level convenience every
    instrumented call site uses."""
    return _DEFAULT_BUS.emit(name, **fields)


# ---------------------------------------------------------------- spans
#: optional jax.profiler annotation factory (utils.profiling.annotate),
#: installed by use_jax_annotations(); None = spans are host-only
_ANNOTATE: Optional[Callable[[str], Any]] = None


def use_jax_annotations(enable: bool = True) -> None:
    """Fold ``utils/profiling.py`` in as the span backend: every span
    additionally opens a ``jax.profiler.TraceAnnotation`` so named host
    regions line up with device traces. Off by default (importing jax
    from the obs layer must stay opt-in)."""
    global _ANNOTATE
    if enable:
        from hpbandster_tpu.utils.profiling import annotate

        _ANNOTATE = annotate
    else:
        _ANNOTATE = None


@contextlib.contextmanager
def span(name: str, bus: Optional[EventBus] = None, **fields: Any) -> Iterator[None]:
    """Monotonic-clock duration region: on exit, emits ``name`` with a
    ``duration_s`` field (plus ``error=<type>`` if the body raised).

    Near-zero when inactive: with no sinks and no jax annotation backend
    the body runs with no clock reads at all."""
    target = bus if bus is not None else _DEFAULT_BUS
    annotate = _ANNOTATE
    if not target.active and annotate is None:
        yield
        return
    ctx = annotate(name) if annotate is not None else contextlib.nullcontext()
    t0 = time.monotonic()
    error: Optional[str] = None
    try:
        with ctx:
            yield
    except BaseException as e:
        error = type(e).__name__
        raise
    finally:
        duration = time.monotonic() - t0
        if error is not None:
            fields = dict(fields, error=error)
        target.emit(name, duration_s=round(duration, 6), **fields)
