"""Declarative SLOs over the journal-record planes: specs + burn-rate math.

An :class:`SLOSpec` declares an objective (a good/total ratio target)
over the same journal-schema records ``summarize``/``replay`` already
consume, and :class:`SLOEvaluator` turns the record stream into
multi-window multi-burn-rate measurements — the SRE discipline (fast
5m/1h windows page, slow 6h/3d windows ticket) applied to an HPO fleet,
where HyperBand's budget framing already *is* an error-budget problem.

Four objective shapes, all reducing to per-record ``(good, bad)``
increments so one window engine serves them all:

* **ratio** — ``total`` selects the units of work; ``bad`` selects the
  failures from a *separate* record stream (``rpc_client_call`` total
  vs ``rpc_retry`` bad);
* **threshold** — ``total`` selects the units; each is good when
  ``good_when`` also matches it (``serve_admission`` records with
  ``wait_s <= 0.25``) — how a latency-percentile objective ("admission
  p95 <= 250 ms" == "95% of admissions under 250 ms") is declared;
* **counter** — one record carries the counts: ``total_field`` /
  ``bad_field`` read pre-aggregated tallies off it (a
  ``device_telemetry`` record's ``evaluations``/``crashes``, the only
  per-evaluation signal a fused sweep surfaces);
* **staleness** — ``fresh`` marks the signal being kept fresh
  (``kde_refit``), ``total`` probes it (every chunk record): a probe is
  good while the last fresh mark is at most ``max_age_s`` old.

Burn rate = (bad/total over a window) / (1 - objective): 1.0 burns the
error budget exactly at the objective's allowed rate; 14.4 exhausts a
3-day budget in 5 hours (the classic page threshold). A severity fires
only when BOTH its windows burn — the long window proves the problem is
real, the short window proves it is *still happening*.

Everything here is pure record math: no clocks (timestamps come from the
records' ``t_wall``), no locks, no bus, no registry — which is what lets
``obs slo --journal`` re-evaluate a journaled run **byte-identically**
offline (the discipline :mod:`hpbandster_tpu.obs.anomaly` set). The
lifecycle/journaling half lives in :mod:`hpbandster_tpu.obs.alerts`.
"""

from __future__ import annotations

import collections
import dataclasses
import math
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple, Union

__all__ = [
    "Selector",
    "BurnWindow",
    "SLOSpec",
    "SLOEvaluator",
    "DEFAULT_WINDOWS",
    "default_slo_pack",
]

#: hard cap per window deque: bounded memory regardless of record rate,
#: identical live and offline (a cap that only one side applied would
#: break replay parity)
_WINDOW_CAP = 65536


def _num(x: Any) -> Optional[float]:
    """Finite number or None; bools are not measurements."""
    if isinstance(x, bool) or not isinstance(x, (int, float)):
        return None
    v = float(x)
    return v if math.isfinite(v) else None


@dataclasses.dataclass(frozen=True)
class Selector:
    """Declarative record predicate: event name(s) + field constraints.

    ``event`` matches the record's ``event`` (a tuple means any-of);
    ``where`` is a tuple of ``(field, value)`` equality constraints;
    ``field`` + ``le``/``ge`` bound a numeric field (a non-numeric or
    missing value fails the bound — absence of evidence is not good
    service). All parts must hold.
    """

    event: Union[str, Tuple[str, ...], None] = None
    where: Tuple[Tuple[str, Any], ...] = ()
    field: Optional[str] = None
    le: Optional[float] = None
    ge: Optional[float] = None

    def matches(self, rec: Dict[str, Any]) -> bool:
        if self.event is not None:
            name = rec.get("event")
            if isinstance(self.event, tuple):
                if name not in self.event:
                    return False
            elif name != self.event:
                return False
        for key, want in self.where:
            if rec.get(key) != want:
                return False
        if self.field is not None:
            v = _num(rec.get(self.field))
            if v is None:
                return False
            if self.le is not None and v > self.le:
                return False
            if self.ge is not None and v < self.ge:
                return False
        return True


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One multi-window burn condition: fire at ``burn``× budget rate
    sustained over BOTH windows (long proves it, short confirms it is
    current)."""

    short_s: float
    long_s: float
    burn: float
    severity: str


#: the SRE standard pair: page on a fast burn (5m/1h at 14.4x — a 3-day
#: budget gone in 5 hours), ticket on a slow one (6h/3d at 1.0x — any
#: sustained burn that will exhaust the budget within its window)
DEFAULT_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow(short_s=300.0, long_s=3600.0, burn=14.4, severity="page"),
    BurnWindow(short_s=21600.0, long_s=259200.0, burn=1.0,
               severity="ticket"),
)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declared objective over the record stream.

    Exactly one objective shape applies (checked at construction):
    ``bad`` (ratio), ``good_when`` (threshold), ``total_field`` +
    ``bad_field`` (counter), or ``fresh`` + ``max_age_s`` (staleness);
    ``total`` always selects the units of work / probes.
    """

    name: str
    objective: float
    total: Selector
    description: str = ""
    bad: Optional[Selector] = None
    good_when: Optional[Selector] = None
    total_field: Optional[str] = None
    bad_field: Optional[str] = None
    fresh: Optional[Selector] = None
    max_age_s: Optional[float] = None
    windows: Tuple[BurnWindow, ...] = DEFAULT_WINDOWS
    #: the error-budget accounting window (budget_remaining's horizon);
    #: defaults to the longest declared burn window
    budget_window_s: Optional[float] = None
    #: hysteresis: a breach must hold this long before firing ...
    for_s: float = 0.0
    #: ... and must stay clear this long before resolving (flap damping)
    clear_for_s: float = 120.0

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"slo {self.name!r}: objective must be in (0, 1), "
                f"got {self.objective!r}"
            )
        shapes = [
            self.bad is not None,
            self.good_when is not None,
            self.total_field is not None or self.bad_field is not None,
            self.fresh is not None or self.max_age_s is not None,
        ]
        if sum(shapes) != 1:
            raise ValueError(
                f"slo {self.name!r}: declare exactly one objective shape "
                "(bad | good_when | total_field+bad_field | "
                "fresh+max_age_s)"
            )
        if shapes[2] and (self.total_field is None or self.bad_field is None):
            raise ValueError(
                f"slo {self.name!r}: counter form needs BOTH total_field "
                "and bad_field"
            )
        if shapes[3] and (self.fresh is None or self.max_age_s is None):
            raise ValueError(
                f"slo {self.name!r}: staleness form needs BOTH fresh "
                "and max_age_s"
            )
        if not self.windows:
            raise ValueError(f"slo {self.name!r}: at least one BurnWindow")

    @property
    def budget_horizon_s(self) -> float:
        if self.budget_window_s is not None:
            return float(self.budget_window_s)
        return max(w.long_s for w in self.windows)


class _Window:
    """One sliding window's running good/bad tallies.

    Increments append with their record time; pruning walks the deque
    head (amortized O(1)) against the newest time seen. The hard cap
    drops the oldest increment when full — same cap live and offline,
    so replay parity survives pathological rates.
    """

    __slots__ = ("span_s", "items", "good", "bad")

    def __init__(self, span_s: float):
        self.span_s = float(span_s)
        self.items: Deque[Tuple[float, float, float]] = collections.deque()
        self.good = 0.0
        self.bad = 0.0

    def add(self, t: float, good: float, bad: float) -> None:
        if len(self.items) >= _WINDOW_CAP:
            self._drop()
        self.items.append((t, good, bad))
        self.good += good
        self.bad += bad

    def _drop(self) -> None:
        _t, g, b = self.items.popleft()
        self.good -= g
        self.bad -= b

    def prune(self, now: float) -> None:
        cutoff = now - self.span_s
        items = self.items
        while items and items[0][0] < cutoff:
            self._drop()

    @property
    def total(self) -> float:
        return self.good + self.bad

    def error_rate(self) -> Optional[float]:
        total = self.good + self.bad
        if total <= 0:
            return None
        return self.bad / total


class _SpecState:
    """Per-spec window set + staleness bookkeeping."""

    __slots__ = ("spec", "windows", "budget", "last_fresh_t", "first_probe_t")

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        spans = []
        for w in spec.windows:
            for s in (w.short_s, w.long_s):
                if s not in spans:
                    spans.append(s)
        self.windows: Dict[float, _Window] = {s: _Window(s) for s in spans}
        self.budget = _Window(spec.budget_horizon_s)
        self.last_fresh_t: Optional[float] = None
        self.first_probe_t: Optional[float] = None

    # ------------------------------------------------------------ classify
    def classify(self, rec: Dict[str, Any]) -> Optional[Tuple[float, float]]:
        """``(good, bad)`` increments this record contributes, or None."""
        spec = self.spec
        if spec.bad is not None:
            if spec.bad.matches(rec):
                return (0.0, 1.0)
            if spec.total.matches(rec):
                return (1.0, 0.0)
            return None
        if spec.good_when is not None:
            if not spec.total.matches(rec):
                return None
            return (1.0, 0.0) if spec.good_when.matches(rec) else (0.0, 1.0)
        if spec.total_field is not None:
            if not spec.total.matches(rec):
                return None
            total = _num(rec.get(spec.total_field)) or 0.0
            bad = _num(rec.get(spec.bad_field)) or 0.0
            bad = min(max(bad, 0.0), max(total, 0.0))
            if total <= 0:
                return None
            return (total - bad, bad)
        # staleness: fresh marks reset the age clock; probes judge it
        t = _num(rec.get("t_wall"))
        if spec.fresh is not None and spec.fresh.matches(rec):
            if t is not None:
                self.last_fresh_t = t
            return None
        if not spec.total.matches(rec) or t is None:
            return None
        if self.first_probe_t is None:
            self.first_probe_t = t
        baseline = (
            self.last_fresh_t
            if self.last_fresh_t is not None else self.first_probe_t
        )
        age = t - baseline
        ok = age <= float(spec.max_age_s or 0.0)
        return (1.0, 0.0) if ok else (0.0, 1.0)

    # ------------------------------------------------------------- measure
    def add(self, t: float, good: float, bad: float) -> None:
        for win in self.windows.values():
            win.add(t, good, bad)
        self.budget.add(t, good, bad)

    def measure(self, now: float) -> Dict[str, Any]:
        """Burn rates / budget at ``now`` (a record's time, never a
        clock). All floats round to 6 places — the byte-stability
        contract the replay parity check rides on."""
        spec = self.spec
        allowed = 1.0 - spec.objective
        for win in self.windows.values():
            win.prune(now)
        self.budget.prune(now)

        def burn(span_s: float) -> Optional[float]:
            rate = self.windows[span_s].error_rate()
            if rate is None:
                return None
            return round(rate / allowed, 6)

        severities: Dict[str, Dict[str, Any]] = {}
        worst: Optional[float] = None
        for w in spec.windows:
            b_short, b_long = burn(w.short_s), burn(w.long_s)
            breached = (
                b_short is not None and b_long is not None
                and b_short >= w.burn and b_long >= w.burn
            )
            severities[w.severity] = {
                "burn_short": b_short,
                "burn_long": b_long,
                "threshold": w.burn,
                "breached": breached,
            }
            for b in (b_short, b_long):
                if b is not None and (worst is None or b > worst):
                    worst = b
        total = self.budget.total
        if total > 0:
            spent = self.budget.bad / (total * allowed)
            remaining = round(1.0 - spent, 6)
        else:
            remaining = 1.0
        return {
            "slo": spec.name,
            "objective": spec.objective,
            "burn_rate": worst,
            "budget_remaining": remaining,
            "severities": severities,
            "window_total": round(total, 6),
        }


class SLOEvaluator:
    """Pure record-stream evaluator for a pack of specs.

    ``update(rec)`` feeds one journal-schema record to every spec and
    returns the measurements of the specs the record touched. No clocks,
    no locks, no I/O: callers that need thread safety (the live bus
    sink) or side effects (gauges, journaled transitions) wrap it —
    :class:`hpbandster_tpu.obs.alerts.AlertManager` is that wrapper.
    """

    def __init__(self, specs: Sequence[SLOSpec]):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate slo names: {sorted(names)}")
        self.states: Dict[str, _SpecState] = {
            s.name: _SpecState(s) for s in specs
        }
        self.last_t: Optional[float] = None

    @property
    def specs(self) -> List[SLOSpec]:
        return [st.spec for st in self.states.values()]

    def update(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Process one record; returns measurements for touched specs."""
        t = _num(rec.get("t_wall"))
        if t is None:
            return []
        # merged multi-process journals can interleave slightly out of
        # order; the window engine needs a non-decreasing "now"
        if self.last_t is None or t > self.last_t:
            self.last_t = t
        now = self.last_t
        out: List[Dict[str, Any]] = []
        for state in self.states.values():
            inc = state.classify(rec)
            if inc is None:
                continue
            state.add(now, inc[0], inc[1])
            out.append(state.measure(now))
        return out

    def measure_all(self) -> List[Dict[str, Any]]:
        """Measurements for every spec at the last seen record time."""
        if self.last_t is None:
            return [
                st.measure(0.0) for st in self.states.values()
            ]
        return [st.measure(self.last_t) for st in self.states.values()]


def default_slo_pack() -> List[SLOSpec]:
    """The fleet's stock objectives, wired to signals the serve tier and
    sweep drivers already journal (docs/observability.md "SLOs &
    alerting" carries the same table):

    * ``serve_admission`` — 95% of admissions reach dispatch within
      250 ms (``serve_admission`` records, ``serve/pool.py``): the
      continuous-batching latency claim as an objective;
    * ``lane_starvation`` — 99% of serve chunks run with zero starved
      lanes (``serve_chunk`` records, ``serve/continuous.py``);
    * ``tenant_auth_rejects`` — 99% of authenticated frontend calls
      succeed (``tenant_auth`` records, ``serve/frontend.py``): a
      sustained reject rate is a brute-force probe or a rotated key;
    * ``device_crash_rate`` — 95% of device evaluations finish finite
      (``device_telemetry`` counter records — the fused tier's only
      per-evaluation feed, rung tallies included);
    * ``rpc_retry_rate`` — 99% of client RPCs land without a retry
      (``rpc_client_call`` total vs ``rpc_retry`` bad);
    * ``kde_refit_staleness`` — 95% of sweep/serve chunks run with a
      model refit at most 10 minutes old: the optimizer silently
      degrading to random search is an SLO breach, not a curiosity.
    """
    return [
        SLOSpec(
            name="serve_admission",
            description="admission -> dispatch within 250ms (p95)",
            objective=0.95,
            total=Selector(event="serve_admission"),
            good_when=Selector(field="wait_s", le=0.25),
        ),
        SLOSpec(
            name="lane_starvation",
            description="serve chunks with zero starved lanes",
            objective=0.99,
            total=Selector(event="serve_chunk"),
            good_when=Selector(field="starved", le=0.0),
        ),
        SLOSpec(
            name="tenant_auth_rejects",
            description="frontend calls passing tenant auth",
            objective=0.99,
            total=Selector(event="tenant_auth"),
            good_when=Selector(where=(("ok", True),)),
        ),
        SLOSpec(
            name="device_crash_rate",
            description="device evaluations finishing finite (per rung "
                        "tallies ride the same records)",
            objective=0.95,
            total=Selector(event="device_telemetry"),
            total_field="evaluations",
            bad_field="crashes",
        ),
        SLOSpec(
            name="rpc_retry_rate",
            description="client RPCs landing without a retry",
            objective=0.99,
            total=Selector(event="rpc_client_call"),
            bad=Selector(event="rpc_retry"),
        ),
        SLOSpec(
            name="kde_refit_staleness",
            description="chunks running with a fresh model fit "
                        "(<= 10 min old)",
            objective=0.95,
            total=Selector(event=("sweep_chunk", "serve_chunk")),
            fresh=Selector(event="kde_refit"),
            max_age_s=600.0,
        ),
    ]
