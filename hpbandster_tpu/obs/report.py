"""Deterministic run reports: ``python -m hpbandster_tpu.obs report``.

Where ``summarize`` answers "how did the *infrastructure* behave" (stage
latencies, utilization, failures), ``report`` answers "how did the
*optimizer* behave" — entirely from merged journals, no live run needed:

* **incumbent trajectory** — every time the best-seen loss improved:
  when, at what budget, by which config, and whether the improver was a
  model-based pick or a random draw (joined from ``config_sampled``
  audit records, see ``obs/audit.py``);
* **model vs random** — per budget, how model-based proposals compare to
  random draws: counts, best/mean losses, and the pairwise win rate
  P(model beats random) — the journal-side check of BOHB §4's claim that
  the model earns its keep once trained;
* **promotion regret** — per rung, was the promotion justified in
  hindsight: among the promoted configs, did the rung's top-ranked one
  stay best at the next budget (rank-1 carryover regret), and how many
  promoted pairs swapped order across the rung (inversions)? High regret
  at a rung means its fidelity is too noisy to cut there — HyperBand's
  ladder analysis (Li et al., JMLR 2017) made from the audit trail;
* **bracket utilization** — per iteration: planned vs sampled configs,
  model-based share, completed/crashed evaluations, promotions per rung;
* **runtime** — compile economics from ``xla_compile`` records
  (``obs/runtime.py``): total compiles, compile seconds, their share of
  the run's wall-clock window, and the top recompiling functions;
* **device telemetry** — the decoded in-trace metrics plane
  (``device_telemetry`` records, ``obs/device_metrics.py``): per-rung
  crash/promotion counts and loss quantiles for fused/resident sweeps
  whose decisions never surfaced to host (same aggregation as
  ``summarize`` — the two views cannot drift);
* **alert digest** — the anomaly detector's verdicts: recorded ``alert``
  events when a live detector ran, otherwise a deterministic offline
  replay of the same rules (``obs.anomaly.scan_records``).

Determinism is a hard contract (pinned by tests): the report derives
exclusively from record content — never from the wall clock, dict
iteration order, or file paths — so two invocations over the same
journal(s) are byte-identical.
"""

from __future__ import annotations

import bisect
import json
from typing import Any, Dict, List, Optional, Tuple

from hpbandster_tpu.obs import events as E
from hpbandster_tpu.obs.alerts import scan_slo_records
from hpbandster_tpu.obs.anomaly import scan_records
from hpbandster_tpu.obs.audit import config_key, config_lineage
from hpbandster_tpu.obs.device_metrics import (
    device_section_from_records,
    finite_or_none as _finite,
    format_device_section,
)
from hpbandster_tpu.obs.runtime import compile_stats_from_records
from hpbandster_tpu.obs.trace import DEFAULT_TENANT

__all__ = [
    "build_report",
    "format_report",
    "filter_tenant",
    "promotion_hindsight",
]


def filter_tenant(
    records: List[Dict[str, Any]], tenant: str
) -> List[Dict[str, Any]]:
    """One tenant's slice of a merged multi-tenant journal.

    A record without a ``tenant_id`` belongs to :data:`DEFAULT_TENANT` —
    that is the byte-compat contract (``obs/trace.py``): pre-serving
    journals, and the non-tenant infrastructure records of a serving
    process (collector samples, compile events from shared programs),
    all read as the default tenant. ``report --tenant acme`` over a
    single-tenant journal therefore returns nothing for ``acme`` and
    everything for ``default``.
    """
    tenant = str(tenant)
    return [
        r for r in records
        if str(r.get("tenant_id", DEFAULT_TENANT)) == tenant
    ]


def _fmt(v: Any) -> str:
    """Stable scalar formatting: %.6g for floats, json for the rest."""
    if isinstance(v, bool) or v is None:
        return json.dumps(v)
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def promotion_hindsight(
    config_ids: List[Any],
    scores: List[Optional[float]],
    mask: List[bool],
    next_budget: Any,
    lineages: Dict[Tuple[int, ...], Dict[str, Any]],
) -> Dict[str, Any]:
    """Judge one promotion (ranking ``scores``, promotion ``mask``)
    against next-budget results: rank-1 (incumbent) regret and pairwise
    rank inversions among the promoted configs that were actually
    evaluated further. THE one implementation — the report's
    promotion-regret table and the replay harness
    (``promote/replay.py``) both call it, so the two views of a journal
    cannot drift.

    Ties in ``scores`` break by candidate order — this is load-bearing:
    Pareto's integer domination counts tie across a whole front, and
    breaking by the next loss would hand every tied group a free zero
    regret and hide within-tie inversions. Callers resolve their own
    score fallbacks (e.g. raw losses) before passing.
    """
    from hpbandster_tpu.obs.audit import config_key

    # (rank value, candidate index, next loss)
    pairs: List[Tuple[float, int, float]] = []
    if isinstance(next_budget, (int, float)):
        for idx, (cid, score, promoted) in enumerate(
            zip(config_ids, scores, mask)
        ):
            if not promoted:
                continue
            rank_value = _finite(score)
            key = config_key(cid)
            nxt = (
                _finite(
                    (lineages.get(key) or {})
                    .get("results", {})
                    .get(float(next_budget))
                )
                if key else None
            )
            if rank_value is not None and nxt is not None:
                pairs.append((rank_value, idx, nxt))
    rank1_regret = None
    inversions = None
    if pairs:
        ordered = sorted(pairs)
        best_next = min(p[2] for p in pairs)
        rank1_regret = round(ordered[0][2] - best_next, 6)
        inv = 0
        for i in range(len(ordered)):
            for j in range(i + 1, len(ordered)):
                if ordered[i][2] > ordered[j][2]:
                    inv += 1
        inversions = inv
    return {
        "evaluated_promoted": len(pairs),
        "rank1_regret": rank1_regret,
        "inversions": inversions,
    }


# ----------------------------------------------------------------- sections
def _incumbent_trajectory(
    records: List[Dict[str, Any]],
    lineages: Dict[Tuple[int, ...], Dict[str, Any]],
    t0: Optional[float],
) -> List[Dict[str, Any]]:
    best: Optional[float] = None
    rows: List[Dict[str, Any]] = []
    n_results = 0
    for rec in records:
        # the loss-carrying record is the result's authoritative telling
        # (master funnel / fused replay — worker-side twins carry
        # compute_s, deliberately no loss): one record per result
        if rec.get("event") != E.JOB_FINISHED or "loss" not in rec:
            continue
        loss = _finite(rec.get("loss"))
        if loss is None:
            continue
        n_results += 1
        if best is not None and loss >= best:
            continue
        best = loss
        key = config_key(rec.get("config_id"))
        sampled = (lineages.get(key) or {}).get("sampled") if key else None
        tw = rec.get("t_wall")
        rows.append({
            "at_s": (
                round(float(tw) - t0, 3)
                if isinstance(tw, (int, float)) and t0 is not None else None
            ),
            "n_results": n_results,
            "config_id": list(key) if key else None,
            "budget": rec.get("budget"),
            "loss": loss,
            "model_based": (
                bool(sampled.get("model_based_pick"))
                if sampled and "model_based_pick" in sampled else None
            ),
        })
    return rows


def _model_vs_random(
    lineages: Dict[Tuple[int, ...], Dict[str, Any]],
) -> Dict[str, Any]:
    per_budget: Dict[float, Dict[str, List[float]]] = {}
    unattributed = 0
    for lineage in lineages.values():
        sampled = lineage["sampled"]
        if sampled is None or "model_based_pick" not in sampled:
            if lineage["results"]:
                unattributed += 1
            continue
        arm = "model" if sampled["model_based_pick"] else "random"
        for budget, loss in lineage["results"].items():
            if _finite(loss) is None:
                continue
            per_budget.setdefault(budget, {"model": [], "random": []})[
                arm
            ].append(float(loss))

    budgets_out = {}
    for budget in sorted(per_budget):
        model = sorted(per_budget[budget]["model"])
        random = sorted(per_budget[budget]["random"])
        # P(model < random) over all cross pairs, O(n log n): for each
        # model loss, count random losses above/equal via bisect on the
        # sorted random side (100k-event journals make O(n·m) minutes)
        wins = ties = 0.0
        for m in model:
            lo = bisect.bisect_left(random, m)
            hi = bisect.bisect_right(random, m)
            wins += len(random) - hi
            ties += hi - lo
        pairs = len(model) * len(random)
        budgets_out[f"{budget:g}"] = {
            "n_model": len(model),
            "n_random": len(random),
            "best_model": model[0] if model else None,
            "best_random": random[0] if random else None,
            "mean_model": (
                round(sum(model) / len(model), 6) if model else None
            ),
            "mean_random": (
                round(sum(random) / len(random), 6) if random else None
            ),
            "model_win_rate": (
                round((wins + 0.5 * ties) / pairs, 4) if pairs else None
            ),
        }
    return {"budgets": budgets_out, "unattributed_configs": unattributed}


def _promotion_regret(
    records: List[Dict[str, Any]],
    lineages: Dict[Tuple[int, ...], Dict[str, Any]],
) -> Dict[str, Any]:
    rows: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("event") != E.PROMOTION_DECISION:
            continue
        ids = rec.get("config_ids") or []
        losses = rec.get("losses") or []
        promoted = rec.get("promoted") or []
        next_budget = rec.get("next_budget")
        # hindsight must judge the ranking the rule ACTUALLY used: when
        # the record carries scores (H2BO extrapolation), rank by those;
        # the raw rung loss is the rule's ranking only for plain SH
        scores = rec.get("scores")
        ranks = scores if isinstance(scores, list) and len(scores) == len(losses) else losses
        # promoted configs with a result at the next budget: the only
        # hindsight available (terminated configs were never evaluated
        # further — regret is measured within the promoted set). Score
        # fallback: where the rule recorded no score, its ranking value
        # was the raw rung loss.
        resolved = [
            _finite(rank) if _finite(rank) is not None else _finite(loss)
            for rank, loss in zip(ranks, losses)
        ]
        hindsight = promotion_hindsight(
            list(ids), resolved, [bool(p) for p in promoted],
            next_budget, lineages,
        )
        rank1_regret = hindsight["rank1_regret"]
        inversions = hindsight["inversions"]
        rows.append({
            "iteration": rec.get("iteration"),
            "rung": rec.get("rung"),
            "budget": rec.get("budget"),
            "next_budget": next_budget,
            "rule": rec.get("rule"),
            "n_candidates": rec.get("n_candidates"),
            "n_promoted": rec.get("n_promoted"),
            "cut_threshold": rec.get("cut_threshold"),
            "evaluated_promoted": hindsight["evaluated_promoted"],
            "rank1_regret": rank1_regret,
            "rank_held": (
                rank1_regret <= 0.0 if rank1_regret is not None else None
            ),
            "inversions": inversions,
            # anomaly correlation (obs/audit.py straggler ledger): how
            # many of this rung's candidates the straggler rule flagged
            # before the decision — high regret WITH stalls reads very
            # differently from high regret on a healthy rung
            "stragglers_observed": len(
                rec.get("straggler_observed") or []
            ),
        })
    rows.sort(key=lambda r: (r["iteration"] or 0, r["rung"] or 0))

    per_rung: Dict[int, List[Dict[str, Any]]] = {}
    for r in rows:
        if r["rank1_regret"] is not None:
            per_rung.setdefault(int(r["rung"] or 0), []).append(r)
    aggregate = {}
    for rung in sorted(per_rung):
        rs = per_rung[rung]
        aggregate[str(rung)] = {
            "decisions": len(rs),
            "mean_rank1_regret": round(
                sum(r["rank1_regret"] for r in rs) / len(rs), 6
            ),
            "rank_held_rate": round(
                sum(1 for r in rs if r["rank_held"]) / len(rs), 4
            ),
        }
    return {"decisions": rows, "per_rung": aggregate}


def _brackets(
    records: List[Dict[str, Any]],
    lineages: Dict[Tuple[int, ...], Dict[str, Any]],
) -> List[Dict[str, Any]]:
    planned: Dict[int, Dict[str, Any]] = {}
    for rec in records:
        if rec.get("event") == "bracket_created":
            it = rec.get("iteration")
            if isinstance(it, int) and it not in planned:
                planned[it] = {
                    "num_configs": rec.get("num_configs"),
                    "budgets": rec.get("budgets"),
                }
    per_iter: Dict[int, Dict[str, Any]] = {}
    for key, lineage in sorted(lineages.items()):
        it = key[0]
        slot = per_iter.setdefault(it, {
            "sampled": 0, "model_based": 0, "completed": 0, "crashed": 0,
        })
        if lineage["sampled"] is not None:
            slot["sampled"] += 1
            if lineage["sampled"].get("model_based_pick"):
                slot["model_based"] += 1
        for loss in lineage["results"].values():
            if loss is None:
                slot["crashed"] += 1
            else:
                slot["completed"] += 1
    promotions: Dict[int, List[int]] = {}
    for rec in records:
        if rec.get("event") == E.PROMOTION_DECISION:
            it = rec.get("iteration")
            if isinstance(it, int):
                promotions.setdefault(it, []).append(
                    int(rec.get("n_promoted") or 0)
                )
    rows = []
    for it in sorted(set(planned) | set(per_iter)):
        plan = planned.get(it, {})
        stats = per_iter.get(it, {
            "sampled": 0, "model_based": 0, "completed": 0, "crashed": 0,
        })
        n_planned = plan.get("num_configs")
        planned_evals = (
            int(sum(n_planned)) if isinstance(n_planned, list) else None
        )
        evals = stats["completed"] + stats["crashed"]
        rows.append({
            "iteration": it,
            "planned_configs": n_planned,
            "budgets": plan.get("budgets"),
            "sampled": stats["sampled"],
            "model_based": stats["model_based"],
            "evaluations": evals,
            "crashed": stats["crashed"],
            "promotions_per_rung": promotions.get(it, []),
            "utilization": (
                round(evals / planned_evals, 4)
                if planned_evals else None
            ),
        })
    return rows


def _alert_digest(records: List[Dict[str, Any]], t0: Optional[float]) -> Dict[str, Any]:
    recorded = [r for r in records if r.get("event") == E.ALERT]
    source = "journal"
    alerts = recorded
    if not recorded:
        alerts = scan_records(records)
        source = "offline_scan"
    by_rule: Dict[str, int] = {}
    by_subject: Dict[str, int] = {}
    rows = []
    for a in alerts:
        rule = str(a.get("rule") or "?")
        subject = str(a.get("subject") or "?")
        by_rule[rule] = by_rule.get(rule, 0) + 1
        by_subject[f"{rule}:{subject}"] = by_subject.get(
            f"{rule}:{subject}", 0
        ) + 1
        tw = a.get("t_wall")
        rows.append({
            "at_s": (
                round(float(tw) - t0, 3)
                if isinstance(tw, (int, float)) and t0 is not None else None
            ),
            "rule": rule,
            "subject": subject,
            "source_event": a.get("source_event"),
        })
    return {
        "source": source,
        "total": len(alerts),
        "by_rule": dict(sorted(by_rule.items())),
        "top_subjects": dict(sorted(
            by_subject.items(), key=lambda kv: (-kv[1], kv[0])
        )[:5]),
        # full list: the text renderer caps its table and points at
        # --json, so the dict must actually carry everything
        "alerts": rows,
    }


def _slo_digest(
    records: List[Dict[str, Any]], t0: Optional[float]
) -> Dict[str, Any]:
    """The SLO story of a journal: the re-evaluated burn-rate verdict
    (scan_slo_records is deterministic, so two reports of one journal
    agree) plus the lifecycle transitions — journaled ``slo_alert``
    records when the run carried a live AlertManager, the offline scan's
    otherwise (the _alert_digest source convention)."""
    recorded = [r for r in records if r.get("event") == E.SLO_ALERT]
    mgr = scan_slo_records(records)
    source = "journal"
    transitions = recorded
    if not recorded:
        transitions = list(mgr.transitions)
        source = "offline_scan"
    snap = mgr.snapshot()
    rows = [
        {
            "at_s": (
                round(tr["t_wall"] - t0, 3)
                if t0 is not None and isinstance(
                    tr.get("t_wall"), (int, float)
                ) else None
            ),
            "slo": tr.get("slo"),
            "severity": tr.get("severity"),
            "state": tr.get("state"),
            "burn_short": tr.get("burn_short"),
            "burn_long": tr.get("burn_long"),
            "budget_remaining": tr.get("budget_remaining"),
        }
        for tr in transitions
    ]
    return {
        "source": source,
        "transitions": len(rows),
        "firing": snap["firing"],
        "worst_burn_rate": snap["worst_burn_rate"],
        "by_slo": snap["by_slo"],
        "rows": rows,
    }


# -------------------------------------------------------------------- report
def build_report(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate merged journal records into the report dict."""
    walls = [
        r["t_wall"] for r in records
        if isinstance(r.get("t_wall"), (int, float))
    ]
    t0 = min(walls) if walls else None
    window = (max(walls) - t0) if walls else 0.0
    lineages = config_lineage(records)
    audit_records = sum(
        1 for r in records
        if r.get("event") in (E.CONFIG_SAMPLED, E.PROMOTION_DECISION)
    )
    return {
        "events_total": len(records),
        "window_s": round(window, 3),
        "configs": len(lineages),
        "audit_records": audit_records,
        "incumbent_trajectory": _incumbent_trajectory(records, lineages, t0),
        "model_vs_random": _model_vs_random(lineages),
        "promotion_regret": _promotion_regret(records, lineages),
        "brackets": _brackets(records, lineages),
        # compile economics: a healthy shape-stable sweep compiles each
        # function once; a climbing count here is the journal-side echo
        # of the live recompile_storm rule (one shared aggregation with
        # the summarize CLI — the two views of one journal must agree)
        "runtime": compile_stats_from_records(records, window),
        # the device metrics plane (obs/device_metrics.py): decoded
        # in-trace telemetry of fused/resident sweeps — shared
        # aggregation with summarize, same drift rule as runtime
        "device": device_section_from_records(records),
        "alerts": _alert_digest(records, t0),
        "slo": _slo_digest(records, t0),
    }


def format_report(rep: Dict[str, Any]) -> str:
    lines = [
        "run report",
        f"  events: {rep['events_total']} over {_fmt(rep['window_s'])}s, "
        f"{rep['configs']} configs, {rep['audit_records']} audit records",
        "",
        "incumbent trajectory:",
    ]
    traj = rep["incumbent_trajectory"]
    if traj:
        lines.append(
            f"  {'#':>5} {'t+s':>10} {'config':<14} {'budget':>8} "
            f"{'loss':>12}  pick"
        )
        for row in traj:
            pick = (
                "model" if row["model_based"]
                else "random" if row["model_based"] is not None else "?"
            )
            lines.append(
                f"  {row['n_results']:>5} {_fmt(row['at_s']):>10} "
                f"{json.dumps(row['config_id']):<14} "
                f"{_fmt(row['budget']):>8} {_fmt(row['loss']):>12}  {pick}"
            )
    else:
        lines.append("  (no finished results with losses in this journal)")

    lines += ["", "model vs random (per budget):"]
    mvr = rep["model_vs_random"]["budgets"]
    if mvr:
        lines.append(
            f"  {'budget':>8} {'n_mod':>6} {'n_rnd':>6} {'best_mod':>12} "
            f"{'best_rnd':>12} {'win_rate':>9}"
        )
        for budget, row in mvr.items():
            lines.append(
                f"  {budget:>8} {row['n_model']:>6} {row['n_random']:>6} "
                f"{_fmt(row['best_model']):>12} {_fmt(row['best_random']):>12} "
                f"{_fmt(row['model_win_rate']):>9}"
            )
        if rep["model_vs_random"]["unattributed_configs"]:
            lines.append(
                "  (%d evaluated configs carry no sampling audit record)"
                % rep["model_vs_random"]["unattributed_configs"]
            )
    else:
        lines.append("  (no audit-attributed results in this journal)")

    lines += ["", "promotion regret (per rung decision):"]
    decisions = rep["promotion_regret"]["decisions"]
    if decisions:
        lines.append(
            f"  {'iter':>5} {'rung':>5} {'budget':>8} {'next':>8} "
            f"{'cand':>5} {'prom':>5} {'cut':>12} {'regret':>10} "
            f"{'held':>5} {'inv':>4} {'strag':>5}  rule"
        )
        for d in decisions:
            lines.append(
                f"  {_fmt(d['iteration']):>5} {_fmt(d['rung']):>5} "
                f"{_fmt(d['budget']):>8} {_fmt(d['next_budget']):>8} "
                f"{_fmt(d['n_candidates']):>5} {_fmt(d['n_promoted']):>5} "
                f"{_fmt(d['cut_threshold']):>12} {_fmt(d['rank1_regret']):>10} "
                f"{_fmt(d['rank_held']):>5} {_fmt(d['inversions']):>4} "
                f"{_fmt(d['stragglers_observed']):>5}  "
                f"{d['rule'] or '?'}"
            )
        for rung, agg in rep["promotion_regret"]["per_rung"].items():
            lines.append(
                f"  rung {rung}: {agg['decisions']} decisions, "
                f"mean rank-1 regret {_fmt(agg['mean_rank1_regret'])}, "
                f"rank held {_fmt(agg['rank_held_rate'])}"
            )
    else:
        lines.append("  (no promotion_decision audit records in this journal)")

    lines += ["", "bracket utilization:"]
    if rep["brackets"]:
        lines.append(
            f"  {'iter':>5} {'planned':<16} {'sampled':>8} {'model':>6} "
            f"{'evals':>6} {'crashed':>8} {'util':>6}  promotions"
        )
        for b in rep["brackets"]:
            lines.append(
                f"  {b['iteration']:>5} "
                f"{json.dumps(b['planned_configs']):<16} "
                f"{b['sampled']:>8} {b['model_based']:>6} "
                f"{b['evaluations']:>6} {b['crashed']:>8} "
                f"{_fmt(b['utilization']):>6}  "
                f"{json.dumps(b['promotions_per_rung'])}"
            )
    else:
        lines.append("  (no bracket records in this journal)")

    rt = rep.get("runtime") or {}
    lines += ["", "xla runtime:"]
    if rt.get("compiles"):
        share = rt.get("compile_share_of_wall")
        lines.append(
            f"  {rt['compiles']} compiles, {_fmt(rt['compile_s'])}s compile time"
            + (
                f" ({_fmt(round(100 * share, 2))}% of run wall-clock)"
                if share is not None else ""
            )
        )
        lines.append(
            f"  {'fn':<32} {'compiles':>9} {'recompiles':>11} {'seconds':>10}"
        )
        for row in rt.get("top_recompilers") or []:
            lines.append(
                f"  {row['fn']:<32} {row['compiles']:>9} "
                f"{row['recompiles']:>11} {_fmt(row['compile_s']):>10}"
            )
    else:
        lines.append("  (no xla_compile records in this journal)")

    device = rep.get("device")
    if device:
        lines += [""] + format_device_section(device)

    al = rep["alerts"]
    lines += [
        "",
        f"alert digest ({al['source']}): {al['total']} alerts "
        + json.dumps(al["by_rule"]),
    ]
    for a in al["alerts"][:20]:
        lines.append(
            f"  t+{_fmt(a['at_s'])}s {a['rule']}: {a['subject']} "
            f"(from {a['source_event']})"
        )
    if al["total"] > 20:
        lines.append(f"  ... {al['total'] - 20} more (use --json for all)")

    slo = rep.get("slo") or {}
    if slo.get("by_slo"):
        lines += [
            "",
            "slo verdict ({}): {} firing, worst burn {}".format(
                slo["source"], slo["firing"],
                _fmt(slo["worst_burn_rate"]),
            ),
        ]
        for name, row in slo["by_slo"].items():
            lines.append(
                f"  {name}: burn={_fmt(row.get('burn_rate'))} "
                f"budget_remaining={_fmt(row.get('budget_remaining'))} "
                f"state={row.get('state')}"
            )
        for tr in slo["rows"][:10]:
            lines.append(
                f"  t+{_fmt(tr['at_s'])}s {tr['slo']}[{tr['severity']}] "
                f"-> {tr['state']} (burn {_fmt(tr['burn_short'])}/"
                f"{_fmt(tr['burn_long'])})"
            )
        if slo["transitions"] > 10:
            lines.append(
                f"  ... {slo['transitions'] - 10} more transitions "
                "(use --json for all)"
            )
    lines.append("")
    return "\n".join(lines)
