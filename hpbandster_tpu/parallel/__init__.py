"""Parallel execution tiers.

ICI tier: batched on-device evaluation over a device mesh
(``VmapBackend`` + ``BatchedExecutor``). DCN tier: the asynchronous host
worker pool (``Dispatcher`` + ``NameServer`` + ``Worker``), preserving the
reference's elastic master/worker semantics (SURVEY.md §2).
"""

from hpbandster_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    config_mesh,
    config_model_mesh,
    is_multiprocess_mesh,
    pad_to_shards,
    shard_count,
)
from hpbandster_tpu.parallel.backends import VmapBackend  # noqa: F401
from hpbandster_tpu.parallel.batched_executor import BatchedExecutor  # noqa: F401
from hpbandster_tpu.parallel.batched_worker import (  # noqa: F401
    RPCBatchBackend,
    TPUBatchedWorker,
)
from hpbandster_tpu.parallel.chaos import (  # noqa: F401
    ChaosMonkey,
    ChaosProxy,
    ChaosSchedule,
)
from hpbandster_tpu.parallel.dispatcher import Dispatcher  # noqa: F401
from hpbandster_tpu.parallel.rpc import (  # noqa: F401
    CommunicationError,
    RPCError,
    RPCProxy,
    RPCServer,
)
