"""Multi-host (DCN-tier) support.

SURVEY.md §2 "Distributed communication backend" prescribes two tiers for
the rebuild: the ICI tier (sharded batched evaluation inside one jit — see
``backends.py``) and a DCN tier for multi-host pods. This module wires the
DCN tier the JAX-native way:

* :func:`initialize_multihost` — ``jax.distributed.initialize`` bootstrap;
  after it, ``jax.devices()`` spans the pod and a ``Mesh`` built from them
  makes the same ``VmapBackend`` code scale across hosts (XLA routes
  collectives over ICI within a slice and DCN between slices).
* :class:`MultiHostBatchedExecutor` — SPMD driver pattern: every host runs
  the same Master loop deterministically (same seeds), each jitted wave is
  a global computation over the pod-wide mesh, and only process 0 talks to
  result loggers — so there is no extra coordination protocol beyond XLA's.

The *elastic* worker pool (dynamic join/leave) intentionally stays on the
host RPC tier (``dispatcher.py``): JAX's SPMD model requires static mesh
membership per run (SURVEY.md §7 "Multi-host elasticity").
"""

from __future__ import annotations

import logging
from typing import Optional

from hpbandster_tpu.parallel.batched_executor import BatchedExecutor

logger = logging.getLogger("hpbandster_tpu.multihost")

__all__ = ["initialize_multihost", "MultiHostBatchedExecutor", "is_primary_host"]


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join the pod; returns this process's id. Safe to call when already
    initialized or in single-process mode (returns 0)."""
    import jax

    if num_processes is None or num_processes <= 1:
        return 0
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:  # already initialized
        logger.debug("jax.distributed.initialize: %s", e)
    return jax.process_index()


def is_primary_host() -> bool:
    import jax

    return jax.process_index() == 0


class MultiHostBatchedExecutor(BatchedExecutor):
    """BatchedExecutor for SPMD multi-host runs.

    Every host must construct the identical optimizer (same seeds/settings)
    and call ``run()`` — the Master's control flow is deterministic, so all
    hosts issue the same global computations in the same order. Side effects
    (result logging, checkpointing) fire only on process 0.
    """

    def __init__(self, backend, configspace, **kwargs):
        super().__init__(backend, configspace, **kwargs)
        import jax

        #: use this to gate side effects (result_logger, checkpoints):
        #: pass them to the Master only when primary is True
        self.primary = jax.process_index() == 0
