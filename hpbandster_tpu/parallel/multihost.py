"""Multi-host (DCN-tier) support.

SURVEY.md §2 "Distributed communication backend" prescribes two tiers for
the rebuild: the ICI tier (sharded batched evaluation inside one jit — see
``backends.py``) and a DCN tier for multi-host pods. This module wires the
DCN tier the JAX-native way:

* :func:`initialize_multihost` — ``jax.distributed.initialize`` bootstrap;
  after it, ``jax.devices()`` spans the pod and a ``Mesh`` built from them
  makes the same ``VmapBackend`` code scale across hosts (XLA routes
  collectives over ICI within a slice and DCN between slices).
* :class:`MultiHostBatchedExecutor` — SPMD driver pattern: every host runs
  the same Master loop deterministically (same seeds), each jitted wave is
  a global computation over the pod-wide mesh, and only process 0 talks to
  result loggers — so there is no extra coordination protocol beyond XLA's.

The *elastic* worker pool (dynamic join/leave) intentionally stays on the
host RPC tier (``dispatcher.py``): JAX's SPMD model requires static mesh
membership per run (SURVEY.md §7 "Multi-host elasticity").
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

from hpbandster_tpu.parallel.batched_executor import BatchedExecutor

logger = logging.getLogger("hpbandster_tpu.multihost")

#: process-wide sharded-sweep fn cache — one traced program per
#: (objective, chunk schedule, space, mesh, knobs), same policy as
#: ops.fused._FUSED_FN_CACHE / FusedBOHB._SWEEP_EXE_CACHE
from hpbandster_tpu.utils.lru import LRUCache as _LRUCache

_SHARDED_FN_CACHE: _LRUCache = _LRUCache(maxsize=16)

__all__ = [
    "initialize_multihost",
    "MultiHostBatchedExecutor",
    "is_primary_host",
    "run_sharded_fused_sweep",
    "publish_device_balance",
]


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join the pod; returns this process's id. Safe to call when already
    initialized or in single-process mode (returns 0)."""
    import jax

    if num_processes is None or num_processes <= 1:
        return 0
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:  # already initialized
        logger.debug("jax.distributed.initialize: %s", e)
    return jax.process_index()


def is_primary_host() -> bool:
    import jax

    return jax.process_index() == 0


class MultiHostBatchedExecutor(BatchedExecutor):
    """BatchedExecutor for SPMD multi-host runs.

    Every host must construct the identical optimizer (same seeds/settings)
    and call ``run()`` — the Master's control flow is deterministic, so all
    hosts issue the same global computations in the same order. Side effects
    (result logging, checkpointing) fire only on process 0.
    """

    def __init__(self, backend, configspace, **kwargs):
        super().__init__(backend, configspace, **kwargs)
        import jax

        #: use this to gate side effects (result_logger, checkpoints):
        #: pass them to the Master only when primary is True
        self.primary = jax.process_index() == 0

    def run_sharded_sweep(self, n_configs: int, **kwargs) -> Dict[str, Any]:
        """Run one mesh-sharded fused sweep over the WHOLE pod.

        Every host calls this with identical arguments (the SPMD driver
        contract above); the sweep is a single global computation over a
        pod-wide 'config' mesh — losses reduce over ICI within a slice and
        DCN between slices, and only the final incumbent (a ``d``-vector +
        scalar loss, replicated to every rank) leaves the device loop.
        Per-device balance gauges are published for this process's local
        devices only; a fleet collector aggregates the rest.
        """
        eval_fn = kwargs.pop("eval_fn", None) or self.backend.eval_fn
        return run_sharded_fused_sweep(
            eval_fn, self.configspace, n_configs=n_configs, **kwargs
        )


def publish_device_balance(
    mesh,
    axis: str,
    per_shard_configs: List[int],
    per_shard_pad: List[int],
) -> Optional[float]:
    """Publish per-device config counts + compute-balance gauges.

    ``per_shard_configs[s]`` is the number of TRUE config rows shard ``s``
    evaluated this sweep; ``per_shard_pad[s]`` its padding rows (evaluated
    but never reported). Gauges land as ``sweep.device.<id>.configs`` /
    ``.pad_rows`` for this process's LOCAL devices (each pod rank owns its
    own), the Prometheus renderer re-expresses them as the
    ``sweep_device_*{device=}`` label family, and the fleet collector
    derives ``fleet.device_compute_skew`` — the compute-balance sibling of
    ``fleet.device_mem_skew``. On an SPMD mesh all devices step in
    lockstep, so the per-device row count IS the step-time balance: a
    nonzero skew means some device spends its steps on padding or an
    uneven shard. Returns the mesh-wide shard skew ((max-min)/max over
    ``per_shard_configs`` — identical on every rank, which is why every
    rank may publish the same ``sweep.balance_skew`` gauge; None if
    unmeasurable).
    """
    import jax

    from hpbandster_tpu.obs.metrics import get_metrics
    from hpbandster_tpu.parallel.mesh import shard_count

    n_shards = shard_count(mesh, axis)
    if len(per_shard_configs) != n_shards:
        raise ValueError(
            f"{len(per_shard_configs)} shard counts for a {n_shards}-shard "
            f"'{axis}' axis"
        )
    reg = get_metrics()
    # devices along the sharded axis, in axis order: shard s's rows live on
    # mesh.devices[... s ...] (a 1-D config mesh is the common case; on a
    # 2-D mesh each shard's rows replicate over the other axes, so every
    # device in the slice reports the shard's count)
    try:
        axis_index = list(mesh.axis_names).index(axis)
    except ValueError:
        return None
    import numpy as np

    devices = np.moveaxis(np.asarray(mesh.devices), axis_index, 0)
    devices = devices.reshape(n_shards, -1)
    proc = jax.process_index()
    for s in range(n_shards):
        for dev in devices[s]:
            if dev.process_index != proc:
                continue
            reg.gauge(f"sweep.device.{dev.id}.configs").set(
                float(per_shard_configs[s])
            )
            reg.gauge(f"sweep.device.{dev.id}.pad_rows").set(
                float(per_shard_pad[s])
            )
    hi = max(per_shard_configs) if per_shard_configs else 0
    skew = None if hi <= 0 else (hi - min(per_shard_configs)) / hi
    if skew is not None:
        reg.gauge("sweep.balance_skew").set(round(float(skew), 6))
    return skew


def run_sharded_fused_sweep(
    eval_fn,
    configspace,
    *,
    n_configs: int,
    n_brackets: int = 1,
    min_budget: float = 1.0,
    max_budget: float = 9.0,
    eta: float = 3.0,
    seed: int = 0,
    mesh=None,
    axis: str = "config",
    model: bool = False,
    num_samples: int = 64,
    chunk_brackets: Optional[int] = None,
    publish_gauges: bool = True,
    resident: bool = False,
    device_metrics: Optional[bool] = None,
    stateful_eval=None,
    program_name: Optional[str] = None,
) -> Dict[str, Any]:
    """Mesh-sharded fused successive halving at 100k-1M config scale.

    One deep bracket of ``n_configs`` (stage counts mesh-aligned,
    :func:`~hpbandster_tpu.ops.bracket.mesh_aligned_plan`) repeated
    ``n_brackets`` times, compiled as ONE sharded device program per chunk
    shape: per-shard on-device sampling (no candidate bytes cross the host
    link), per-stage sharding constraints over ``axis`` (rung promotions
    reduce across shards over ICI/DCN), and an ``incumbent_only`` payload —
    the winning vector + loss is the only thing fetched. ``model=True``
    turns the BOHB KDE on (observation buffers then shard over the config
    axis and, with ``chunk_brackets``, thread device-to-device between
    chunks under the PR-6 donation contract); the default is
    HyperBand-style random proposals, the honest mode at 1M configs where
    a KDE fit over the full observation set would dominate.

    ``resident=True`` fuses the whole multi-bracket OUTER loop in-trace
    (``ops/sweep.py`` ``resident=True``): the repeated bracket is traced
    once and a ``lax.scan`` drives all ``n_brackets`` rounds on device,
    so the sweep is ONE dispatch + ONE incumbent fetch however many
    brackets run — where the chunked path surfaces to host once per
    chunk. The per-sweep transfer gauges
    (``sweep.transfer_bytes.{h2d,d2h}`` / ``sweep.host_syncs``) are
    published and returned, and the incumbent payload is journaled as a
    ``sweep_incumbent`` audit record (``obs replay`` re-scores it) —
    the flat-d2h claim is measured, not asserted. Replaces
    ``chunk_brackets`` (passing both is an error).

    ``device_metrics`` (default: ``HPB_DEVICE_METRICS``) turns the
    in-trace metrics plane on (``ops/sweep.py`` ``device_metrics=True``):
    per-rung loss histograms and crash/promotion counts accumulate on
    device and ride the incumbent's d2h — an O(schedule) constant, so
    the flat-host-link bill stays flat in config count WITH telemetry
    enabled (the ``resident_100k`` bench tier measures exactly that).
    The decoded record is published as gauges, journaled as
    ``device_telemetry``, and returned under ``"device_telemetry"``.

    ``stateful_eval`` (exclusive with ``eval_fn``, pass ``eval_fn=None``)
    runs the sweep over a warm-continuation ensemble
    (``ops.fused.StatefulEval`` — e.g. ``workloads.ensemble``): every
    rung trains live models in-trace and promotions carry their weights.
    The ensemble state is bracket-local device scratch, so the flat
    host-link bill above is untouched. ``program_name`` labels the
    compiled program in the obs ledger (roofline attribution).

    Returns a stats dict (incumbent, per-device balance, chunk timings).
    SPMD multi-host: call on every rank with identical arguments over a
    pod-spanning mesh; the returned incumbent is identical on all ranks.
    """
    import jax
    import numpy as np

    from hpbandster_tpu.obs.runtime import note_transfer
    from hpbandster_tpu.ops.bracket import mesh_aligned_plan
    from hpbandster_tpu.ops.sweep import (
        build_space_codec,
        make_fused_sweep_fn,
        plan_additions,
        pow2_capacities,
    )
    from hpbandster_tpu.parallel.mesh import (
        batch_sharding,
        config_mesh,
        shard_count,
    )

    if mesh is None:
        mesh = config_mesh()
    n_shards = shard_count(mesh, axis)
    plan = mesh_aligned_plan(n_configs, min_budget, max_budget, eta, n_shards)
    plans = [plan] * max(int(n_brackets), 1)
    codec = build_space_codec(configspace)
    d = int(codec.kind.shape[0])
    rng = np.random.default_rng(seed)
    codec_sig = codec.signature

    if resident and chunk_brackets is not None:
        raise ValueError(
            "resident=True replaces chunking (one scanned program for the "
            "whole schedule) — drop chunk_brackets"
        )
    chunk = (
        len(plans)
        if (chunk_brackets is None or resident)
        else max(int(chunk_brackets), 1)
    )
    dynamic = resident or chunk_brackets is not None
    from hpbandster_tpu.obs.device_metrics import device_metrics_default

    use_dm = (
        device_metrics_default()
        if device_metrics is None else bool(device_metrics)
    )
    sweep_kwargs: Dict[str, Any] = dict(
        num_samples=num_samples,
        mesh=mesh,
        axis=axis,
        shard_sampling=True,
        incumbent_only=True,
        # HyperBand mode: an unreachable gate keeps the KDE out of the
        # trace entirely (any_trainable=False) — pure sample/eval/promote
        min_points_in_model=None if model else 2**30,
    )
    caps = None
    if dynamic:
        # one capacity map for the WHOLE schedule (pow2, floor 256): every
        # chunk shares buffer shapes, so the run is one executable and the
        # threaded state never re-uploads (ops/sweep.py return_state)
        caps = pow2_capacities(plan_additions(plans))

    def _empty_state_args():
        """Zero-observation warm buffers, built PER SHARD SLICE via
        ``make_array_from_callback`` — no host allocation ever holds a
        full capacity buffer (the bounded-RSS contract the bench tier's
        RSS probe checks). Returns ``(warm_v, warm_l, warm_n,
        host_bytes)`` — the bytes the host link actually carries, so the
        transfer ledger measures the warm upload instead of asserting it
        (same accounting as ``FusedBOHB._stream_warm_args``)."""
        from jax.sharding import NamedSharding, PartitionSpec

        shard = batch_sharding(mesh, axis)
        rep = NamedSharding(mesh, PartitionSpec())
        warm_v, warm_l, warm_n = {}, {}, {}
        host_bytes = 0
        for b, cap in caps.items():
            sh = shard if cap % n_shards == 0 else rep
            warm_v[b] = jax.make_array_from_callback(
                (cap, d), sh,
                lambda idx, cap=cap: np.zeros(
                    _slice_shape(idx, (cap, d)), np.float32
                ),
            )
            warm_l[b] = jax.make_array_from_callback(
                (cap,), sh,
                lambda idx, cap=cap: np.full(
                    _slice_shape(idx, (cap,)), np.inf, np.float32
                ),
            )
            warm_n[b] = np.int32(0)
            host_bytes += cap * d * 4 + cap * 4 + 4
        return warm_v, warm_l, warm_n, host_bytes

    from hpbandster_tpu.obs.runtime import (
        publish_sweep_transfers,
        transfer_counters,
    )

    link0 = transfer_counters()
    fns: Dict[int, Any] = {}
    chunks: List[Dict[str, Any]] = []
    best: Optional[Dict[str, Any]] = None
    per_bracket_all: List[float] = []
    dm_parts: List[Any] = []
    dm_execute_s = 0.0
    state = None
    remaining = list(plans)
    bracket_base = 0
    while remaining:
        chunk_plans, remaining = remaining[:chunk], remaining[chunk:]
        if len(chunk_plans) not in fns:
            # process-wide reuse (same policy as the other fused tiers):
            # bench repeats of the same (objective, schedule, mesh, knobs)
            # must not retrace/recompile — the compile-count acceptance
            # (<= one program per chunk shape) is per PROCESS, not per call
            from hpbandster_tpu.ops.kde import _pallas_fit_requested

            cache_key = (
                # exactly one is non-None; the pair keys stateless and
                # stateful (warm-continuation) executables apart
                (eval_fn, stateful_eval),
                tuple((p.num_configs, p.budgets) for p in chunk_plans),
                codec_sig, mesh, axis, bool(model), int(num_samples),
                dynamic, bool(resident),
                None if caps is None else tuple(sorted(caps.items())),
                # trace-time flag (ops/kde.py): an env flip must miss
                # the cache, not serve the other fit path's executable
                _pallas_fit_requested(),
                # telemetry adds outputs to the traced program — the
                # metrics-on executable must never serve a metrics-off
                # call (or vice versa)
                use_dm,
                # the ledger label is part of what the caller asked for:
                # a relabeled request must not serve a fn tracked under
                # the old name (roofline attribution would lie)
                program_name,
            )
            cached = _SHARDED_FN_CACHE.get(cache_key)
            if cached is None:
                cached = make_fused_sweep_fn(
                    eval_fn, chunk_plans, codec,
                    dynamic_counts=dynamic,
                    capacities=caps,
                    # resident runs the whole schedule in one dispatch:
                    # there is no next chunk to thread state into
                    return_state=dynamic and not resident,
                    resident=resident,
                    device_metrics=use_dm,
                    stateful_eval=stateful_eval,
                    program_name=program_name,
                    **sweep_kwargs,
                )
                _SHARDED_FN_CACHE[cache_key] = cached
            fns[len(chunk_plans)] = cached
        fn = fns[len(chunk_plans)]
        seed_val = np.uint32(rng.integers(2**32, dtype=np.uint32))
        upload_bytes = int(seed_val.nbytes)
        if dynamic:
            if state is not None:
                # device-resident thread: nothing but the seed goes up
                args = (seed_val,) + state
            elif resident:
                # cold resident sweep: with no warm inputs the dynamic
                # init zeroes the observation buffers IN-TRACE
                # (ops/sweep.py init_obs_state's absent-budget branch),
                # so the whole upload is the 4-byte seed — h2d is flat
                # in config count, like the incumbent-only d2h
                args = (seed_val,)
            else:
                warm_v, warm_l, warm_n, host_bytes = _empty_state_args()
                args = (seed_val, warm_v, warm_l, warm_n)
                upload_bytes += host_bytes
        else:
            args = (seed_val,)
        note_transfer("h2d", upload_bytes)
        t0 = time.perf_counter()
        out = fn(*args)
        dm_dev = None
        if dynamic and not resident:
            if use_dm:
                inc, dm_dev, state = out
            else:
                inc, state = out
        elif use_dm:
            inc, dm_dev = out
        else:
            inc = out
        inc = jax.device_get(inc)
        dm_host = jax.device_get(dm_dev) if dm_dev is not None else None
        execute_s = time.perf_counter() - t0
        dm_leaves = (
            list(jax.tree_util.tree_leaves(dm_host))
            if dm_host is not None else []
        )
        if dm_host is not None:
            dm_parts.append((
                dm_host,
                [(p.num_configs, p.budgets) for p in chunk_plans],
            ))
            dm_execute_s += execute_s
        note_transfer(
            "d2h",
            sum(int(np.asarray(l).nbytes) for l in inc)
            + sum(int(np.asarray(l).nbytes) for l in dm_leaves),
            buffers=len(inc) + len(dm_leaves),
        )
        loss = float(np.asarray(inc.loss))
        cand = {
            "vector": np.asarray(inc.vector, np.float32).tolist(),
            "loss": loss,
            "bracket": bracket_base + int(np.asarray(inc.bracket)),
        }
        per_bracket_all.extend(
            float(x) for x in np.asarray(inc.per_bracket_loss)
        )
        # NaN = every candidate crashed; never beats a real incumbent
        if best is None or (
            not np.isnan(loss) and (
                best["loss"] is None or np.isnan(best["loss"])
                or loss < best["loss"]
            )
        ):
            best = cand
        chunks.append({
            "brackets": len(chunk_plans),
            "execute_fetch_s": round(execute_s, 4),
            # 4 bytes (the seed) once the state threads device-to-device
            "warm_upload_bytes": upload_bytes,
        })
        bracket_base += len(chunk_plans)

    # geometry-derived balance: every stage splits its (mesh-aligned) rows
    # evenly, so shard s owns sum(widths)/S rows per bracket. Every row is
    # a REAL sampled config (the sweep path samples the full aligned
    # width — alignment surplus rows are extra exploration, not dead
    # padding), so pad_rows is 0 here and the surplus over the pure
    # eta-decay ladder is reported separately, uncounted in configs.
    pure = []
    for j in range(len(plan.num_configs)):
        pure.append(max(int(n_configs * float(eta) ** (-j)), 1))
    per_shard_rows = sum(plan.num_configs) // n_shards * len(plans)
    surplus_total = (sum(plan.num_configs) - sum(pure)) * len(plans)
    per_shard_configs = [per_shard_rows] * n_shards
    skew = None
    if publish_gauges:
        skew = publish_device_balance(
            mesh, axis, per_shard_configs, [0] * n_shards
        )

    # per-sweep host-link bill: gauges for the scraper, deltas in the
    # stats dict, and — since the incumbent is this sweep's ONLY decision
    # payload — a sweep_incumbent audit record the replay harness can
    # re-score (per-rung decisions never left the device)
    link = publish_sweep_transfers(link0)
    host_syncs = link["transfers_h2d"] + link["transfers_d2h"]
    decoded_dm = None
    if dm_parts:
        # the metrics plane's host half: one decoded record per sweep —
        # gauges for the scraper, a device_telemetry journal record for
        # summarize/report and the anomaly rules (every rank publishes
        # its own copy, like the incumbent record: SPMD values are
        # identical on all ranks)
        from hpbandster_tpu.obs.device_metrics import (
            decode_device_metrics,
            emit_device_telemetry,
            publish_device_metrics,
        )

        decoded_dm = decode_device_metrics(
            dm_parts, execute_s=dm_execute_s
        )
        publish_device_metrics(decoded_dm)
        emit_device_telemetry(decoded_dm)
    if best is not None:
        from hpbandster_tpu.obs.audit import emit_sweep_incumbent

        emit_sweep_incumbent(
            vector=best["vector"],
            loss=best["loss"],
            bracket=best["bracket"],
            per_bracket_loss=per_bracket_all,
            evaluations=int(sum(sum(p.num_configs) for p in plans)),
            n_configs=int(n_configs),
            d2h_bytes=link["transfer_bytes_d2h"],
            h2d_bytes=link["transfer_bytes_h2d"],
            host_syncs=host_syncs,
        )

    return {
        "incumbent": best,
        "evaluations": int(sum(sum(p.num_configs) for p in plans)),
        "requested_configs": int(n_configs),
        "aligned_stage_counts": list(plan.num_configs),
        "budgets": list(plan.budgets),
        "n_brackets": len(plans),
        "n_devices": int(np.asarray(mesh.devices).size),
        "n_shards": n_shards,
        "per_device_configs": per_shard_configs,
        # rows evaluated beyond the pure eta ladder due to mesh alignment
        # (whole schedule, all shards) — already included in
        # per_device_configs/evaluations, never add them together
        "alignment_surplus_rows": int(surplus_total),
        "balance_skew": 0.0 if skew is None else round(float(skew), 6),
        "chunks": chunks,
        "execute_fetch_s": round(
            sum(c["execute_fetch_s"] for c in chunks), 4
        ),
        "resident": bool(resident),
        "device_telemetry": decoded_dm,
        "per_bracket_loss": per_bracket_all,
        # measured host-link bill for THIS sweep (note_transfer deltas):
        # the resident tier's flat-d2h / constant-host-sync evidence
        "h2d_bytes": int(link["transfer_bytes_h2d"]),
        "d2h_bytes": int(link["transfer_bytes_d2h"]),
        "host_syncs": int(host_syncs),
    }


def _slice_shape(idx, shape) -> tuple:
    """Concrete shape of the shard slice ``make_array_from_callback``
    asks for — the per-shard allocation unit of the streamed uploads."""
    out = []
    for sl, n in zip(idx, shape):
        start, stop, _ = sl.indices(n)
        out.append(stop - start)
    return tuple(out)
