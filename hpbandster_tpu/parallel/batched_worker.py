"""TPUBatchedWorker + RPCBatchBackend — the multi-host batched tier.

This is the bridge named in the north star (SURVEY.md §0 / §2 "Task
parallel" row): where the reference evaluates strictly one config per
worker per RPC round-trip, a ``TPUBatchedWorker`` is one host process per
TPU slice that evaluates a whole *vector* of configurations per job — the
batch runs as a single sharded XLA dispatch on the worker's local mesh, and
only the loss vector rides the (DCN-tier) RPC link back.

Two halves:

* :class:`TPUBatchedWorker` — a :class:`~hpbandster_tpu.core.worker.Worker`
  subclass that owns a :class:`~hpbandster_tpu.parallel.backends.VmapBackend`
  over its local devices and exposes an ``evaluate_batch`` RPC. It remains
  fully compatible with the plain dispatcher: single-config jobs submitted
  through ``start_computation`` are evaluated as a batch of one, so a pool
  may mix CPU dict-workers and TPU batched workers behind one nameserver.
* :class:`RPCBatchBackend` — the master-side counterpart implementing the
  same ``evaluate(vectors, budget) -> losses`` protocol as ``VmapBackend``,
  so it plugs straight into ``BatchedExecutor`` (stage batching, bracket
  interleaving, crashed-as-NaN semantics all carry over). Each wave is
  split across the registered batched workers proportional to their device
  counts; worker death mid-wave retries the shard on the survivors and only
  NaN-fills when nobody is left (the reference's elastic requeue behavior,
  SURVEY.md §5, lifted to shard granularity).

Elasticity note: each worker's mesh is local to its process, so workers can
join/leave between waves without any global SPMD membership change — the
SURVEY §7 "confine elasticity to the host tier" rule.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from hpbandster_tpu import obs
from hpbandster_tpu.core.worker import Worker
from hpbandster_tpu.parallel.rpc import (
    CommunicationError,
    RPCError,
    RPCProxy,
    RPCServer,
    format_uri,
)

__all__ = ["TPUBatchedWorker", "RPCBatchBackend"]


class TPUBatchedWorker(Worker):
    """A worker that evaluates a vector of configs per job on local devices.

    ``eval_fn(config_vector: f32[d], budget: f32[]) -> loss: f32[]`` must be
    jittable (same contract as ``VmapBackend``). ``configspace`` supplies the
    dict -> unit-hypercube codec for single-config (plain dispatcher) jobs.

    By default the backend shards each batch over ALL local devices with a
    1-D ``('config',)`` mesh; pass ``mesh=`` to control placement (e.g. a
    ('config', 'model') mesh where each config's training step is itself
    tensor-parallel) or ``mesh=None, devices=1`` for single-device tests.
    """

    def __init__(
        self,
        run_id: str,
        eval_fn: Callable,
        configspace=None,
        mesh: Any = "auto",
        static_budget: bool = False,
        min_pad: int = 8,
        **worker_kwargs: Any,
    ):
        super().__init__(run_id, **worker_kwargs)
        from hpbandster_tpu.parallel.backends import VmapBackend
        from hpbandster_tpu.utils.compile_cache import (
            enable_persistent_compile_cache,
        )

        # this worker compiles a device program per batch shape — warm the
        # persistent XLA cache before the first one (docs/perf_notes.md)
        enable_persistent_compile_cache()

        if mesh == "auto":
            import jax

            devices = jax.devices()
            if len(devices) > 1:
                from hpbandster_tpu.parallel.mesh import config_mesh

                mesh = config_mesh(devices)
            else:
                mesh = None
        self.configspace = configspace
        self.backend = VmapBackend(
            eval_fn, mesh=mesh, static_budget=static_budget, min_pad=min_pad
        )

    # ------------------------------------------------------------ rpc surface
    def _extra_rpc(self, server: RPCServer) -> None:
        server.register("evaluate_batch", self._rpc_evaluate_batch)
        server.register("capabilities", self._rpc_capabilities)

    def _rpc_capabilities(self) -> Dict[str, Any]:
        return {"batch": True, "devices": int(self.backend.parallelism)}

    def _rpc_evaluate_batch(
        self, vectors: List[List[float]], budget: float
    ) -> Dict[str, Any]:
        """One wave: ``f32[n, d]`` unit-hypercube vectors -> ``f32[n]`` losses.

        Per-config crashes surface as non-finite losses (the caller maps
        them to crashed jobs); a backend-level failure raises and is
        marshalled back as an RPCError for the master to retry elsewhere.
        Holds the busy lock for the duration: concurrent waves serialize on
        the local devices, the dispatcher's ``is_busy`` probe reports the
        truth, and the idle-timeout watchdog cannot fire mid-evaluation.
        """
        arr = np.asarray(vectors, dtype=np.float32)
        if arr.ndim != 2:
            raise ValueError(f"vectors must be [n, d], got shape {arr.shape}")
        with self._busy_lock:
            self._last_active = time.monotonic()
            t0 = time.perf_counter()
            with obs.span("worker_evaluate_batch", n=len(arr), budget=float(budget)):
                losses = self.backend.evaluate(arr, float(budget))
            self.logger.debug(
                "evaluate_batch: %d configs at budget %g in %.3fs",
                len(arr), budget, time.perf_counter() - t0,
            )
            self._last_active = time.monotonic()
        # stdlib json round-trips NaN/Infinity literals exactly, so crashed
        # (NaN) and diverged (+/-inf) losses survive the wire unchanged and
        # both backends agree on identical inputs
        return {"losses": [float(x) for x in losses]}

    # --------------------------------------------------------------- user API
    def compute(
        self,
        config_id: Any,
        config: Dict[str, Any],
        budget: float,
        working_directory: str,
    ) -> Dict[str, Any]:
        """Plain-dispatcher compatibility: one config = a batch of one."""
        if self.configspace is None:
            raise RuntimeError(
                "single-config jobs need configspace= for the dict->vector codec"
            )
        vec = np.nan_to_num(
            self.configspace.to_vector(config), nan=0.0
        ).astype(np.float32)
        loss = float(self.backend.evaluate(vec[None, :], float(budget))[0])
        if not np.isfinite(loss):
            raise RuntimeError(f"non-finite loss {loss!r} at budget {budget}")
        return {"loss": loss, "info": {"batched": True}}


class _BatchWorkerProxy:
    """Master-side record of one batched worker."""

    def __init__(self, name: str, uri: str, devices: int):
        self.name = name
        self.uri = uri
        self.devices = max(int(devices), 1)

    def evaluate(self, vectors: np.ndarray, budget: float, timeout: float) -> np.ndarray:
        reply = RPCProxy(self.uri, timeout=timeout).call(
            "evaluate_batch",
            vectors=[[float(x) for x in row] for row in vectors],
            budget=float(budget),
        )
        # None tolerated defensively for non-stdlib peers that cannot emit
        # NaN/Infinity literals
        losses = np.array(
            [np.nan if x is None else x for x in reply["losses"]], dtype=np.float32
        )
        if losses.shape != (len(vectors),):
            raise CommunicationError(
                f"worker {self.name} returned {losses.shape[0]} losses for "
                f"{len(vectors)} configs"
            )
        return losses


class RPCBatchBackend:
    """``evaluate(vectors, budget) -> losses`` over a pool of batched workers.

    Discovery mirrors the dispatcher (SURVEY.md §2 "Dispatcher" row): the
    nameserver is polled for ``hpbandster.run_<id>.worker.*`` registrations
    and each candidate is probed once for the ``capabilities`` RPC — only
    batch-capable workers join the pool, so plain dict-workers behind the
    same nameserver are simply ignored. Waves are split proportionally to
    per-worker device counts and issued concurrently; a failed shard is
    retried on the surviving workers before NaN-filling.
    """

    def __init__(
        self,
        run_id: str,
        nameserver: str,
        nameserver_port: int,
        logger: Optional[logging.Logger] = None,
        rpc_timeout: float = 600.0,
        refresh_interval: float = 1.0,
        max_retries: int = 2,
    ):
        self.run_id = run_id
        self.nameserver = nameserver
        self.nameserver_port = nameserver_port
        self.logger = logger or logging.getLogger("hpbandster_tpu.rpc_batch_backend")
        self.rpc_timeout = float(rpc_timeout)
        self.refresh_interval = float(refresh_interval)
        self.max_retries = int(max_retries)
        self._workers: Dict[str, _BatchWorkerProxy] = {}
        self._probed_not_batch: set = set()
        #: names with an in-flight capability probe (don't re-probe)
        self._probing: set = set()
        #: name -> earliest next-probe time after a transient failure, so an
        #: unreachable candidate doesn't get re-probed every refresh.
        #: MONOTONIC clock throughout the backoff/deadline math here: a
        #: wall-clock jump (NTP step, suspend/resume) must not expire — or
        #: indefinitely extend — a backoff window (Job.timestamps stays
        #: wall-clock verbatim; only internal arithmetic is monotonic)
        self._probe_backoff: Dict[str, float] = {}
        self.probe_backoff_s = 5.0
        self._last_refresh = 0.0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- discovery
    @property
    def _prefix(self) -> str:
        return f"hpbandster.run_{self.run_id}.worker."

    def refresh_workers(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < self.refresh_interval:
                return
            # claim the slot before the (slow, unlocked) nameserver RPC so a
            # concurrent caller inside the same tick skips instead of issuing
            # a duplicate listing; an unreachable nameserver then also backs
            # off for one interval rather than re-stalling the hot path
            self._last_refresh = now
        try:
            listing = RPCProxy(
                format_uri(self.nameserver, self.nameserver_port), timeout=5
            ).call("list", prefix=self._prefix)
        except (CommunicationError, RPCError) as e:
            self.logger.warning("nameserver unreachable: %r", e)
            return
        with self._lock:
            gone = set(self._workers) - set(listing)
            for name in gone:
                self.logger.info("batched worker %s left the pool", name)
                obs.emit(obs.WORKER_DROPPED, worker=name, reason="unregistered")
                del self._workers[name]
            to_probe = []
            for name, uri in listing.items():
                if name in self._workers:
                    if self._workers[name].uri != uri:
                        self._workers[name].uri = uri
                elif (
                    name not in self._probed_not_batch
                    and name not in self._probing
                    and now >= self._probe_backoff.get(name, 0.0)
                ):
                    self._probing.add(name)
                    to_probe.append((name, uri))

        # Probe OUTSIDE the lock, concurrently, and WITHOUT joining:
        # refresh runs on the evaluate() hot path, so one unreachable-but-
        # registered candidate must never stall a wave behind its 5 s
        # connect timeout. A confirmed worker folds itself into the pool
        # when its probe lands; wait_for_workers()'s poll loop picks it up.
        def probe(name: str, uri: str) -> None:
            try:
                try:
                    caps = RPCProxy(uri, timeout=5).call("capabilities")
                except RPCError:
                    # a live worker without the method is definitively not
                    # batch-capable — cache the verdict
                    with self._lock:
                        self._probed_not_batch.add(name)
                    return
                except (CommunicationError, OSError):
                    # transient (connect timeout, mid-restart): don't
                    # blacklist, but back off so the stall can't recur on
                    # every refresh tick
                    with self._lock:
                        self._probe_backoff[name] = (
                            time.monotonic() + self.probe_backoff_s
                        )
                    return
                if not isinstance(caps, dict) or not caps.get("batch"):
                    with self._lock:
                        self._probed_not_batch.add(name)
                    return
                proxy = _BatchWorkerProxy(name, uri, caps.get("devices", 1))
                with self._lock:
                    self._workers[name] = proxy
                    self._probe_backoff.pop(name, None)
                obs.emit(
                    obs.WORKER_DISCOVERED, worker=name, devices=proxy.devices
                )
                self.logger.info(
                    "batched worker %s joined (%d devices)", name, proxy.devices
                )
            finally:
                with self._lock:
                    self._probing.discard(name)

        for c in to_probe:
            threading.Thread(target=probe, args=c, daemon=True).start()

    @property
    def parallelism(self) -> int:
        """Total devices across the pool (BatchedExecutor's worker count)."""
        self.refresh_workers()
        with self._lock:
            return sum(w.devices for w in self._workers.values()) or 0

    def wait_for_workers(self, min_n_workers: int = 1, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.refresh_workers(force=True)
            with self._lock:
                if len(self._workers) >= min_n_workers:
                    return
            time.sleep(0.1)
        raise TimeoutError(
            f"fewer than {min_n_workers} batched workers after {timeout}s"
        )

    # ------------------------------------------------------------ evaluation
    @staticmethod
    def _split(
        n: int, workers: List[_BatchWorkerProxy]
    ) -> List[Tuple[_BatchWorkerProxy, int, int]]:
        """Contiguous shard bounds over ``range(n)``, proportional to device
        counts — at most ONE shard per worker."""
        total = sum(w.devices for w in workers)
        bounds, acc = [], 0
        for w in workers:
            share = round(n * w.devices / total)
            bounds.append((w, acc, min(acc + share, n)))
            acc = min(acc + share, n)
        # remainder (rounding) goes to the last worker
        if bounds and acc < n:
            w, lo, _ = bounds[-1]
            bounds[-1] = (w, lo, n)
        return [(w, lo, hi) for w, lo, hi in bounds if hi > lo]

    def evaluate(self, vectors: np.ndarray, budget: float) -> np.ndarray:
        with obs.span("wave_evaluate", n=len(vectors), budget=float(budget)):
            return self._evaluate(vectors, budget)

    def _evaluate(self, vectors: np.ndarray, budget: float) -> np.ndarray:
        vectors = np.asarray(vectors, dtype=np.float32)
        n = len(vectors)
        losses = np.full(n, np.nan, dtype=np.float32)
        #: global indices still awaiting a result; retry rounds re-split this
        #: merged set so each worker receives at most one RPC per attempt
        pending = np.arange(n)
        #: workers that failed DURING this wave: excluded from its retries
        #: even if a forced refresh re-discovers them (a straggler would just
        #: block the retry behind its busy lock and time out again)
        failed_names: set = set()

        for attempt in range(self.max_retries + 1):
            self.refresh_workers(force=attempt > 0)
            with self._lock:
                workers = [
                    w for w in self._workers.values() if w.name not in failed_names
                ]
            if not workers:
                # probes are async now — if one is in flight (e.g. a fresh
                # worker replacing the crashed pool), give it a moment to
                # land before declaring the wave dead
                deadline = time.monotonic() + self.probe_backoff_s
                while time.monotonic() < deadline:
                    with self._lock:
                        probing = bool(self._probing)
                        workers = [
                            w
                            for w in self._workers.values()
                            if w.name not in failed_names
                        ]
                    if workers or not probing:
                        break
                    time.sleep(0.05)
            if not workers:
                self.logger.error("no batched workers alive; wave crashes as NaN")
                break

            shards = [
                (w, pending[lo:hi])
                for w, lo, hi in self._split(len(pending), workers)
            ]
            failed: List[np.ndarray] = []
            failed_lock = threading.Lock()

            def run_shard(w: _BatchWorkerProxy, idx: np.ndarray) -> None:
                # broad catch: a malformed reply (KeyError/TypeError) must
                # enter the retry path exactly like a vanished peer
                try:
                    losses[idx] = w.evaluate(vectors[idx], budget, self.rpc_timeout)
                except Exception as e:
                    self.logger.warning(
                        "shard of %d configs failed on %s: %r", len(idx), w.name, e
                    )
                    obs.emit(
                        obs.WORKER_DROPPED,
                        worker=w.name, reason="shard failed", n_configs=len(idx),
                    )
                    with failed_lock:
                        failed.append(idx)
                        failed_names.add(w.name)
                    with self._lock:
                        self._workers.pop(w.name, None)

            threads = [
                threading.Thread(target=run_shard, args=s, daemon=True)
                for s in shards
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

            if not failed:
                return losses
            pending = np.concatenate(failed)
            obs.emit(
                obs.RPC_RETRY, attempt=attempt + 1,
                max_retries=self.max_retries, pending=len(pending),
            )
            obs.get_metrics().counter("rpc.batch_shard_retries").inc()
            self.logger.info(
                "retrying %d failed config(s), attempt %d/%d",
                len(pending), attempt + 1, self.max_retries,
            )
        return losses
