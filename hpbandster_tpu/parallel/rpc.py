"""Minimal JSON-over-TCP RPC — the transport under the host worker pool.

Replaces the reference's Pyro4 + serpent substrate (SURVEY.md §2 L0/L1)
with a dependency-free stdlib implementation: one connection per call,
newline-delimited JSON frames, exceptions marshalled back as error strings.
Connection-per-call keeps liveness detection trivial (a vanished peer is a
``ConnectionError``), which the dispatcher's elastic worker handling relies
on — the same failure surface Pyro4's ``CommunicationError`` gave the
reference.

Trace and tenant context (``hpbandster_tpu.obs.trace``) ride every call
as an optional ``_obs`` field beside ``method``/``params``: the proxy
injects the caller's current trace (and, in the serving tier, the current
tenant id), the server runs the handler under them. Peers that predate
the field — or the ``tenant`` key inside it — ignore it
(``.get``-based parsing), so the wire format stays backward compatible
in both directions.
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import threading
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

from hpbandster_tpu.obs import events as obs_events
from hpbandster_tpu.obs import get_metrics
from hpbandster_tpu.obs.trace import (
    WIRE_FIELD,
    current_wire,
    extract_tenant,
    extract_wire,
    use_tenant,
    use_trace,
)

__all__ = ["RPCServer", "RPCProxy", "RPCError", "CommunicationError", "parse_uri", "format_uri"]

logger = logging.getLogger("hpbandster_tpu.rpc")


def _count(name: str) -> None:
    # looked up per call (one dict access under the registry lock, noise
    # next to a TCP round-trip) rather than cached at import: a cached
    # instrument would be orphaned by MetricsRegistry.reset()
    get_metrics().counter(name).inc()

_MAX_FRAME = 64 * 1024 * 1024  # 64 MiB per message


def parse_uri(uri: str) -> Tuple[str, int]:
    """``host:port`` -> ``(host, port)``, RFC 3986 bracket form included.

    ``[::1]:9090`` parses to ``('::1', 9090)`` — a plain ``rsplit(':')``
    would split inside the address. Bare IPv6 without brackets is rejected
    (ambiguous: every colon is a candidate separator).
    """
    if uri.startswith("["):
        host, sep, port = uri[1:].rpartition("]:")
        if not sep or not port:
            raise ValueError(f"malformed bracketed uri {uri!r}")
        return host, int(port)
    host, sep, port = uri.rpartition(":")
    if not sep or ":" in host:
        raise ValueError(f"malformed uri {uri!r} (bracket IPv6 hosts: '[::1]:9090')")
    return host, int(port)


def format_uri(host: str, port: int) -> str:
    """Inverse of :func:`parse_uri`: brackets IPv6 hosts."""
    return f"[{host}]:{port}" if ":" in host else f"{host}:{port}"


class RPCError(Exception):
    """The remote method raised; carries the remote traceback string."""


class CommunicationError(Exception):
    """The peer is unreachable / vanished (connect or read failure)."""


def _read_frame(sock: socket.socket) -> Optional[bytes]:
    chunks = []
    total = 0
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            if chunks:
                # the peer closed mid-frame: surface it as the transport
                # failure it is, not as the json.JSONDecodeError the
                # partial payload would later raise
                raise CommunicationError(
                    f"truncated frame: peer closed after {total} bytes"
                )
            return None
        chunks.append(chunk)
        total += len(chunk)
        if total > _MAX_FRAME:
            raise CommunicationError("frame too large")
        if chunk.endswith(b"\n"):
            return b"".join(chunks)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "RPCServer" = self.server  # type: ignore[assignment]
        try:
            raw = _read_frame(self.request)
            if not raw:
                return
            msg = json.loads(raw.decode("utf-8"))
            method = msg.get("method", "")
            params = msg.get("params", {})
            _count("rpc.server_requests")
            fn = server.methods.get(method)
            if fn is None:
                _count("rpc.server_unknown_method")
                reply = {"error": f"unknown method {method!r}"}
            else:
                try:
                    # run the handler under the caller's trace AND tenant
                    # context (the optional _obs envelope beside
                    # method/params); a missing or malformed envelope is
                    # simply no trace / no tenant
                    wire = msg.get(WIRE_FIELD)
                    with use_trace(extract_wire(wire)), use_tenant(
                        extract_tenant(wire)
                    ):
                        reply = {"result": fn(**params)}
                except Exception:
                    _count("rpc.server_handler_errors")
                    reply = {"error": traceback.format_exc()}
            self.request.sendall(json.dumps(reply).encode("utf-8") + b"\n")
        except (CommunicationError, ConnectionError, OSError, json.JSONDecodeError) as e:
            logger.debug("rpc handler error: %r", e)


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class _ThreadingTCP6Server(_ThreadingTCPServer):
    address_family = socket.AF_INET6


class RPCServer:
    """Serve a dict of callables over TCP; one daemon thread per connection.

    IPv6 hosts (any host containing ':') bind an AF_INET6 socket and render
    their :attr:`uri` in bracket form, round-tripping through
    :func:`parse_uri` on the proxy side.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.methods: Dict[str, Callable[..., Any]] = {}
        server_cls = _ThreadingTCP6Server if ":" in host else _ThreadingTCPServer
        self._server = server_cls((host, port), _Handler)
        self._server.methods = self.methods  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def register(self, name: str, fn: Callable[..., Any]) -> None:
        self.methods[name] = fn

    def register_instance(self, obj: Any, prefix: str = "") -> None:
        """Expose every public method of ``obj`` (Pyro4 'expose' analog)."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self.methods[prefix + name] = fn

    @property
    def uri(self) -> str:
        return format_uri(self.host, self.port)

    def start(self) -> "RPCServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name=f"rpc-server-{self.port}",
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)


class RPCProxy:
    """Call methods on a remote RPCServer; connection per call."""

    def __init__(self, uri: str, timeout: float = 10.0):
        self.addr: Tuple[str, int] = parse_uri(uri)
        self.uri = uri
        self.timeout = timeout

    def call(self, method: str, **params: Any) -> Any:
        msg: Dict[str, Any] = {"method": method, "params": params}
        wire = current_wire()  # one ContextVar read when no trace is active
        if wire is not None:
            msg[WIRE_FIELD] = wire
        payload = json.dumps(msg).encode("utf-8")
        _count("rpc.client_calls")
        try:
            # the flight-recorder hop span (obs/timeline.py renders it as
            # the RPC-phase slice of a trace's row): span() is near-free
            # when no sink listens — no clock reads, no event. peer rides
            # the record so the rpc_retry_rate SLO's journal evidence can
            # be cut per endpoint post-hoc.
            with obs_events.span(
                obs_events.RPC_CLIENT_CALL, method=method, peer=self.uri
            ):
                with socket.create_connection(
                    self.addr, timeout=self.timeout
                ) as sock:
                    sock.sendall(payload + b"\n")
                    raw = _read_frame(sock)
        except CommunicationError:
            # _read_frame's own failures (truncated / oversized frame) are
            # communication errors too — count them like every other one
            _count("rpc.client_comm_errors")
            raise
        except (ConnectionError, OSError) as e:
            _count("rpc.client_comm_errors")
            raise CommunicationError(f"cannot reach {self.uri}: {e!r}") from e
        if not raw:
            _count("rpc.client_comm_errors")
            raise CommunicationError(f"{self.uri} closed the connection")
        reply = json.loads(raw.decode("utf-8"))
        if "error" in reply:
            _count("rpc.client_remote_errors")
            raise RPCError(reply["error"])
        return reply.get("result")

    def __getattr__(self, name: str) -> Callable[..., Any]:
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda **params: self.call(name, **params)
