"""Batched evaluation backends: configs -> losses as one XLA computation.

This is the north-star component (SURVEY.md §0): where the reference
evaluates strictly one config per worker per Pyro4 RPC, these backends
evaluate a whole wave of configurations as a single jitted, sharded
dispatch — vmapped over the config batch, sharded over the 'config' axis of
a device mesh, with per-config crash masking via non-finite losses.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from hpbandster_tpu.obs.runtime import note_transfer, tracked_jit
from hpbandster_tpu.utils.lru import LRUCache

__all__ = ["VmapBackend"]

#: process-wide compiled-batch cache: backend instances come and go
#: (warmups, repeated optimizer runs), but an (objective, batch shape,
#: budget-mode, mesh) combination should compile exactly once per process —
#: same policy as ops.fused._FUSED_FN_CACHE. Hits require the SAME eval_fn
#: object (rebuild closures once, not per optimizer run); bounded LRU so
#: misses from throwaway closures cannot pin their captured datasets and
#: compiled executables forever.
_BATCH_FN_CACHE: LRUCache = LRUCache(maxsize=64)


class VmapBackend:
    """Evaluate a jittable objective over a batch of config vectors.

    ``eval_fn(config_vector: f32[d], budget: f32[]) -> loss: f32[]`` must be
    traceable by JAX (use ``lax`` control flow for budget-dependent loops).
    Budgets arrive as a *traced* scalar by default so one compilation covers
    every rung of the budget ladder; pass ``static_budget=True`` when the fn
    needs the budget as a Python number (e.g. a static trip count) — that
    costs one recompile per distinct budget, of which there are only
    ``max_SH_iter``.

    With a mesh, the batch is sharded over ``axis`` and each device evaluates
    its shard; without one, a single-device ``jit(vmap(...))`` runs. Batch
    sizes are padded to the next power of two (and to a multiple of the mesh
    size) so recompilation stays logarithmic in the largest stage.
    """

    def __init__(
        self,
        eval_fn: Callable[[jax.Array, jax.Array], jax.Array],
        mesh: Optional[Mesh] = None,
        axis: str = "config",
        static_budget: bool = False,
        min_pad: int = 8,
    ):
        self.eval_fn = eval_fn
        self.mesh = mesh
        self.axis = axis
        self.static_budget = bool(static_budget)
        self.min_pad = int(min_pad)
        self._compiled = _BATCH_FN_CACHE

    # ------------------------------------------------------------------ info
    @property
    def parallelism(self) -> int:
        if self.mesh is not None:
            return int(np.prod(list(self.mesh.shape.values())))
        return 1

    @property
    def _multiprocess(self) -> bool:
        """True when the mesh spans more than one JAX process (DCN tier)."""
        from hpbandster_tpu.parallel.mesh import is_multiprocess_mesh

        return is_multiprocess_mesh(self.mesh)

    def _padded_size(self, n: int) -> int:
        size = self.min_pad
        while size < n:
            size *= 2
        if self.mesh is not None:
            m = self.parallelism
            size = ((size + m - 1) // m) * m
        return size

    # ------------------------------------------------------------------ jit
    def _build(self, n_pad: int, budget_static: Optional[float]) -> Callable:
        def batch_fn(vectors: jax.Array, budget: jax.Array) -> jax.Array:
            if budget_static is not None:
                losses = jax.vmap(lambda v: self.eval_fn(v, budget_static))(vectors)
            else:
                losses = jax.vmap(lambda v: self.eval_fn(v, budget))(vectors)
            return losses.astype(jnp.float32)

        if self.mesh is not None:
            shard = NamedSharding(self.mesh, PartitionSpec(self.axis))
            rep = NamedSharding(self.mesh, PartitionSpec())
            # DCN tier: the SPMD host driver on EVERY process needs the full
            # loss vector for promotion decisions, so replicate the output
            # (XLA inserts the all-gather; losses are tiny) — a sharded
            # output would not be addressable outside its home process
            out = rep if self._multiprocess else shard
            # donation contract (docs/perf_notes.md): the f32[n] losses
            # output cannot alias the [n, d] batch input — declined
            # explicitly rather than warned about per dispatch
            return tracked_jit(
                batch_fn,
                name="vmap_batch_sharded",
                in_shardings=(shard, rep),
                out_shardings=out,
                donate_argnums=(),
            )
        return tracked_jit(batch_fn, name="vmap_batch", donate_argnums=())

    def evaluate(self, vectors: np.ndarray, budget: float) -> np.ndarray:
        """``f32[n, d]`` config vectors -> ``f32[n]`` losses (NaN = crashed)."""
        vectors = np.asarray(vectors, np.float32)
        n, d = vectors.shape
        n_pad = self._padded_size(n)
        key = (
            self.eval_fn,
            n_pad,
            d,
            float(budget) if self.static_budget else None,
            self.mesh,
            self.axis,
        )
        # fetch-then-call on a local ref: the shared LRU may evict the entry
        # between a membership check and the call under concurrent waves
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build(n_pad, float(budget) if self.static_budget else None)
            self._compiled[key] = fn
        padded = np.zeros((n_pad, d), np.float32)
        padded[:n] = vectors
        note_transfer("h2d", padded.nbytes)
        if self._multiprocess:
            # every process holds the identical full batch (deterministic
            # SPMD driver); assemble the global sharded array from the
            # local slice each shard's home process owns
            shard = NamedSharding(self.mesh, PartitionSpec(self.axis))
            batch = jax.make_array_from_callback(
                (n_pad, d), shard, lambda idx: padded[idx]
            )
        else:
            batch = jnp.asarray(padded)
        losses = fn(batch, jnp.float32(budget))
        out = np.asarray(losses)
        note_transfer("d2h", out.nbytes)
        return out[:n]
