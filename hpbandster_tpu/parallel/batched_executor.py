"""BatchedExecutor — the Master-facing adapter for on-device evaluation.

Implements the executor seam (see ``core/master.py``): jobs submitted by the
Master are buffered; when the Master runs out of ready work it calls
``flush()``, which groups the buffer by budget, encodes configs to vectors,
runs each budget group as ONE backend dispatch, and fires the result
callback for every job synchronously. Non-finite losses become crashed jobs
(result ``None`` + exception string), reproducing the reference's
crashed-evaluation semantics (SURVEY.md §5) inside the batch.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

import numpy as np

from hpbandster_tpu.core.job import Job
from hpbandster_tpu.space import ConfigurationSpace

__all__ = ["BatchedExecutor"]


class BatchedExecutor:
    #: tells the Master not to throttle submissions on a worker-sized queue
    unbounded_queue = True
    #: one bracket at a time: every fresh sample sees all earlier results
    #: (most sample-efficient; a stage is still one big device batch).
    #: Raise via Master.parallel_brackets to trade sample efficiency for
    #: cross-bracket batching on large meshes.
    preferred_parallel_brackets = 1
    #: stage quotas are filled through get_config_batch (one vmapped
    #: proposal kernel) instead of per-config get_config calls
    prefers_batched_sampling = True

    def __init__(
        self,
        backend,
        configspace: ConfigurationSpace,
        logger: Optional[logging.Logger] = None,
    ):
        self.backend = backend
        self.configspace = configspace
        self.logger = logger or logging.getLogger("hpbandster_tpu.batched_executor")
        self.buffer: List[Job] = []
        self._new_result_callback: Optional[Callable[[Job], None]] = None
        self.total_evaluated = 0

    # -------------------------------------------------------- executor seam
    def start(self, new_result_callback, new_worker_callback) -> None:
        self._new_result_callback = new_result_callback
        new_worker_callback(self.number_of_workers())

    def number_of_workers(self) -> int:
        return max(int(getattr(self.backend, "parallelism", 1)), 1)

    def submit_job(self, job: Job) -> None:
        self.buffer.append(job)

    def n_waiting(self) -> int:
        return len(self.buffer)

    def flush(self) -> bool:
        """Evaluate everything buffered; returns True if any job ran."""
        if not self.buffer:
            return False
        jobs, self.buffer = self.buffer, []

        by_budget: Dict[float, List[Job]] = {}
        for job in jobs:
            by_budget.setdefault(float(job.kwargs["budget"]), []).append(job)

        for budget, group in sorted(by_budget.items()):
            vectors = np.stack(
                [
                    np.nan_to_num(
                        self.configspace.to_vector(j.kwargs["config"]), nan=0.0
                    )
                    for j in group
                ]
            )
            for j in group:
                j.time_it("started")
            try:
                losses = self.backend.evaluate(vectors, budget)
            except Exception as e:  # backend-level failure crashes the wave
                self.logger.exception("batched evaluation failed at budget %g", budget)
                losses = np.full(len(group), np.nan)
                for j in group:
                    j.exception = f"batched evaluation failed: {e!r}"
            self.total_evaluated += len(group)
            for j, loss in zip(group, losses):
                j.time_it("finished")
                if np.isfinite(loss):
                    j.result = {"loss": float(loss), "info": {}}
                else:
                    j.result = None
                    j.exception = j.exception or (
                        f"non-finite loss {loss!r} at budget {budget}"
                    )
                self._new_result_callback(j)
        return True

    def shutdown(self, shutdown_workers: bool = False) -> None:
        if self.buffer:
            self.logger.warning(
                "shutdown with %d unevaluated buffered jobs", len(self.buffer)
            )
