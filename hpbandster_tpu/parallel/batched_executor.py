"""BatchedExecutor — the Master-facing adapter for on-device evaluation.

Implements the executor seam (see ``core/master.py``): jobs submitted by the
Master are buffered; when the Master runs out of ready work it calls
``flush()``, which evaluates the buffer on-device and fires the result
callback for every job synchronously. Non-finite losses become crashed jobs
(result ``None`` + exception string), reproducing the reference's
crashed-evaluation semantics (SURVEY.md §5) inside the batch.

Two evaluation modes:

* **stage batching** (always on): buffered jobs group by budget; each group
  is one backend dispatch.
* **bracket fusion** (``fuse_brackets=True``, default): when the buffer is a
  complete stage-0 wave of one bracket, the WHOLE bracket — every stage plus
  the top-k promotion decisions — runs as one jitted computation
  (``ops/fused.py``). Later-stage results are then served from a cache the
  instant the Master's own (identical) promotion rule re-queues the
  survivors. If the host promotes a different set (e.g. H2BO's
  learning-curve rule), the mismatching configs simply fall back to the
  stage-batched path — fusion is an optimization, never a semantics change.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from hpbandster_tpu import obs
from hpbandster_tpu.core.job import Job
from hpbandster_tpu.space import ConfigurationSpace

__all__ = ["BatchedExecutor"]


class BatchedExecutor:
    #: tells the Master not to throttle submissions on a worker-sized queue
    unbounded_queue = True
    #: stage quotas are filled through get_config_batch (one vmapped
    #: proposal kernel) instead of per-config get_config calls
    prefers_batched_sampling = True

    def __init__(
        self,
        backend,
        configspace: ConfigurationSpace,
        fuse_brackets: bool = True,
        parallel_brackets: int = 1,
        bucket_brackets: bool = True,
        logger: Optional[logging.Logger] = None,
    ):
        from hpbandster_tpu.utils.compile_cache import (
            enable_persistent_compile_cache,
        )

        # the executor IS a device-program factory: warm the persistent
        # XLA cache before the first compile (idempotent; HPB_XLA_CACHE=0
        # opts out — docs/perf_notes.md "Persistent compile cache")
        enable_persistent_compile_cache()
        self.backend = backend
        self.configspace = configspace
        self.fuse_brackets = bool(fuse_brackets) and hasattr(backend, "eval_fn")
        #: shape-bucketed fused brackets (ops/buckets.py): when the Master
        #: announces the remaining schedule (prepare_schedule), its bracket
        #: shapes pad up to a small geometric bucket set compiled ONCE per
        #: bucket — and AOT-precompiled in the background, overlapped with
        #: stage-0 sampling — instead of one program per shape
        self.bucket_brackets = bool(bucket_brackets) and self.fuse_brackets
        # >1 pipelines brackets: bracket k+1's stage-0 wave is sampled (from
        # a one-bracket-stale model — the reference's own asynchrony) and
        # dispatched before bracket k's results are fetched, overlapping
        # device work with transfers on high-latency links
        self.preferred_parallel_brackets = max(int(parallel_brackets), 1)
        self.logger = logger or logging.getLogger("hpbandster_tpu.batched_executor")
        self.buffer: List[Job] = []
        self._new_result_callback: Optional[Callable[[Job], None]] = None
        self.total_evaluated = 0
        #: (config_id, budget) -> loss computed ahead of time by a fused bracket
        self._fused_cache: Dict[Tuple[Any, float], float] = {}
        #: (num_configs, budgets) -> compiled fused bracket fn
        self._fused_fns: Dict[Tuple, Callable] = {}
        self.fused_brackets_run = 0
        #: of which, brackets served by a shared bucket program
        self.bucketed_brackets_run = 0
        #: every plan prepare_schedule has seen (the bucket set rebuilds
        #: over the union, so a second run() widens rather than resets)
        self._bucket_plans: List = []
        self._bucket_set = None
        self._bucket_precompile = None

    # -------------------------------------------------------- executor seam
    def start(self, new_result_callback, new_worker_callback) -> None:
        self._new_result_callback = new_result_callback
        new_worker_callback(self.number_of_workers())

    def number_of_workers(self) -> int:
        return max(int(getattr(self.backend, "parallelism", 1)), 1)

    def submit_job(self, job: Job) -> None:
        self.buffer.append(job)

    def n_waiting(self) -> int:
        return len(self.buffer)

    # ------------------------------------------------------------- delivery
    def _crash_wave(self, jobs: List[Job], exc: Exception, where: str) -> None:
        """A bracket-level failure crashes only its own wave's jobs (the
        stage-batched path's containment, lifted to fused brackets)."""
        self.logger.exception("%s failed; wave of %d crashes", where, len(jobs))
        for j in jobs:
            j.exception = f"{where} failed: {exc!r}"
            self._finish(j, float("nan"))

    def _finish(self, job: Job, loss: float) -> None:
        job.time_it("finished")
        if np.isfinite(loss):
            job.result = {"loss": float(loss), "info": {}}
        else:
            job.result = None
            job.exception = job.exception or (
                f"non-finite loss {loss!r} at budget {job.kwargs['budget']}"
            )
        self.total_evaluated += 1
        # burst delivery: all of a flush's results land before the Master
        # can propose again (flush runs synchronously inside Master.run), so
        # the model records each observation now and refits ONCE at the next
        # proposal instead of after every result — the proposal fits over
        # the same observations, skipping the N-1 fits nothing could read
        # (see BOHBKDE._dirty_budgets for the conditional-space RNG caveat)
        self._new_result_callback(job, update_model=False)

    # -------------------------------------------------------- bucketed path
    def prepare_schedule(self, plans) -> None:
        """Master.run seam: the remaining schedule's bracket shapes, known
        before any sampling starts. Builds the geometric bucket set over
        every plan seen so far and kicks off a BACKGROUND AOT compile of
        the bucket programs (``ops/buckets.py``), so the compile overlaps
        the optimizer's stage-0 sampling instead of serializing in front
        of the first dispatch. Safe to skip entirely — brackets then fall
        back to one compiled program per shape, exactly as before."""
        if not self.bucket_brackets:
            return
        from hpbandster_tpu.ops.buckets import (
            build_bucket_set,
            precompile_buckets,
        )

        fusable = [p for p in plans if len(p.num_configs) >= 2]
        if not fusable:
            return
        self._bucket_plans.extend(fusable)
        mesh = getattr(self.backend, "mesh", None)
        axis = getattr(self.backend, "axis", "config")
        # pad stage-0 widths to the SHARDED axis size only — on a 2-D
        # ('config', 'model') mesh the model axis replicates the batch, so
        # padding to the total device count would evaluate dead rows
        mesh_size = 1
        if mesh is not None:
            mesh_size = int(dict(mesh.shape).get(axis, 1))
        self._bucket_set = build_bucket_set(
            self._bucket_plans, mesh_size=mesh_size
        )
        self._bucket_precompile = precompile_buckets(
            self.backend.eval_fn,
            self._bucket_set,
            d=self.configspace.dim,
            mesh=mesh,
            axis=axis,
            background=True,
        )
        self.logger.debug(
            "bucket set prepared: %d shapes -> %d programs",
            len(self._bucket_set.assignment), len(self._bucket_set.buckets),
        )

    def _bucket_runner_for(self, info):
        """The (runner, plan, entry) serving this bracket shape, or None
        when bucketing is off / unprepared / does not cover the shape."""
        if self._bucket_set is None:
            return None
        placed = self._bucket_set.lookup(info["num_configs"], info["budgets"])
        if placed is None:
            return None
        from hpbandster_tpu.ops.bracket import BracketPlan
        from hpbandster_tpu.ops.buckets import make_bucketed_bracket_fn

        bucket_idx, entry = placed
        runner = make_bucketed_bracket_fn(
            self.backend.eval_fn,
            self._bucket_set.buckets[bucket_idx],
            mesh=getattr(self.backend, "mesh", None),
            axis=getattr(self.backend, "axis", "config"),
        )
        plan = BracketPlan(
            num_configs=tuple(info["num_configs"]),
            budgets=tuple(info["budgets"]),
        )
        return runner, plan, entry

    # ---------------------------------------------------------- fused path
    def _try_fuse(self, jobs: List[Job]) -> Optional[List[Job]]:
        """Fuse every complete stage-0 bracket wave found in ``jobs``.

        Multiple brackets may be buffered at once (``parallel_brackets > 1``):
        each complete wave becomes its own fused computation, ALL of them
        dispatched before the first result fetch so their device work and
        transfers overlap. Returns the leftover (non-fused) jobs, or None if
        nothing was fused."""
        from hpbandster_tpu.ops.fused import _unpack_stages, make_fused_bracket_fn

        groups: Dict[int, List[Job]] = {}
        leftovers: List[Job] = []
        for j in jobs:
            info = getattr(j, "bracket_info", None)
            if info is None or info["stage"] != 0 or len(info["num_configs"]) < 2:
                leftovers.append(j)
            else:
                groups.setdefault(j.id[0], []).append(j)

        dispatched = []
        crashed = False
        for iteration, gjobs in sorted(groups.items()):
            info = gjobs[0].bracket_info
            complete = (
                all(getattr(j, "bracket_info", None) == info for j in gjobs)
                and len(gjobs) == info["num_configs"][0]
            )
            if not complete:
                leftovers.extend(gjobs)
                continue
            jobs_sorted = sorted(gjobs, key=lambda j: j.id)
            vectors = np.stack(
                [
                    np.nan_to_num(
                        self.configspace.to_vector(j.kwargs["config"]), nan=0.0
                    )
                    for j in jobs_sorted
                ]
            ).astype(np.float32)
            for j in jobs_sorted:
                j.time_it("started")

            # bucketed first: the shape shares a precompiled bucket program
            # (ops/buckets.py) when the Master announced the schedule. Any
            # bucketed failure falls back to the per-shape path — bucketing
            # is an optimization, never a semantics (or liveness) change.
            fetch = None
            bucketed = self._bucket_runner_for(info)
            if bucketed is not None:
                runner, plan, entry = bucketed
                from hpbandster_tpu.ops.buckets import member_counts_for

                counts = member_counts_for(runner.bucket, plan, entry)
                try:
                    with obs.span(
                        "fused_dispatch", iteration=iteration,
                        n=len(jobs_sorted), bucketed=True,
                    ):
                        packed = runner.dispatch(vectors, counts)
                    from hpbandster_tpu.ops.buckets import slice_member_stages

                    fetch = (
                        lambda packed=packed, runner=runner, plan=plan,
                        entry=entry: slice_member_stages(
                            runner.unpack(packed), plan, entry
                        )
                    )
                    self.bucketed_brackets_run += 1
                except Exception:
                    self.logger.exception(
                        "bucketed dispatch failed; falling back to the "
                        "per-shape fused program"
                    )

            if fetch is None:
                shape_key = (info["num_configs"], info["budgets"])
                if shape_key not in self._fused_fns:
                    self._fused_fns[shape_key] = make_fused_bracket_fn(
                        self.backend.eval_fn,
                        info["num_configs"],
                        info["budgets"],
                        mesh=getattr(self.backend, "mesh", None),
                        axis=getattr(self.backend, "axis", "config"),
                    )
                try:
                    # the dispatch span brackets the tracked-jit boundary
                    # (ops/fused.py): a first-wave tick here that dwarfs the
                    # steady state is compile time, and the xla_compile event
                    # the tracker journals says so explicitly
                    with obs.span(
                        "fused_dispatch", iteration=iteration,
                        n=len(jobs_sorted),
                    ):
                        packed = self._fused_fns[shape_key].dispatch(vectors)
                except Exception as e:  # contain: only THIS wave crashes
                    self._crash_wave(jobs_sorted, e, "fused dispatch")
                    crashed = True
                    continue
                fetch = (
                    lambda packed=packed, nc=info["num_configs"]:
                    _unpack_stages(packed, nc)
                )
            dispatched.append((iteration, info, jobs_sorted, fetch))

        if not dispatched and not crashed:
            # nothing fused, nothing consumed: let the caller stage-batch
            return None

        for iteration, info, jobs_sorted, fetch in dispatched:
            try:
                # fetch span: the device->host transfer (counted in bytes
                # by the runners' runtime.transfer_* counters)
                with obs.span("fused_fetch", iteration=iteration):
                    stages = fetch()
            except Exception as e:
                self._crash_wave(jobs_sorted, e, "fused fetch")
                continue
            self.fused_brackets_run += 1
            # stage 0 results feed back immediately; stages >= 1 fill the cache
            stage0_losses = np.asarray(stages[0][1])
            for s, (idx, losses) in enumerate(stages[1:], start=1):
                budget = info["budgets"][s]
                for i, loss in zip(np.asarray(idx), np.asarray(losses)):
                    cid = jobs_sorted[int(i)].id
                    self._fused_cache[(cid, float(budget))] = float(loss)
            self.logger.debug(
                "fused bracket %d: %s evals in one dispatch",
                iteration, sum(len(np.asarray(i)) for i, _ in stages),
            )
            for j, loss in zip(jobs_sorted, stage0_losses):
                self._finish(j, loss)
        return leftovers

    # -------------------------------------------------------------- flush
    def flush(self) -> bool:
        """Evaluate everything buffered; returns True if any job ran."""
        if not self.buffer:
            return False
        jobs, self.buffer = self.buffer, []

        # serve results a fused bracket already computed
        remaining: List[Job] = []
        for job in jobs:
            key = (job.id, float(job.kwargs["budget"]))
            if key in self._fused_cache:
                job.time_it("started")
                self._finish(job, self._fused_cache.pop(key))
            else:
                remaining.append(job)
        if not remaining:
            return True

        if self.fuse_brackets:
            fused_rest = self._try_fuse(remaining)
            if fused_rest is not None:
                remaining = fused_rest
                if not remaining:
                    return True

        by_budget: Dict[float, List[Job]] = {}
        for job in remaining:
            by_budget.setdefault(float(job.kwargs["budget"]), []).append(job)

        for budget, group in sorted(by_budget.items()):
            vectors = np.stack(
                [
                    np.nan_to_num(
                        self.configspace.to_vector(j.kwargs["config"]), nan=0.0
                    )
                    for j in group
                ]
            )
            for j in group:
                j.time_it("started")
            try:
                with obs.span("stage_batch", n=len(group), budget=budget):
                    losses = self.backend.evaluate(vectors, budget)
            except Exception as e:  # backend-level failure crashes the wave
                self.logger.exception("batched evaluation failed at budget %g", budget)
                losses = np.full(len(group), np.nan)
                for j in group:
                    j.exception = f"batched evaluation failed: {e!r}"
            for j, loss in zip(group, losses):
                self._finish(j, loss)
        return True

    def shutdown(self, shutdown_workers: bool = False) -> None:
        if self.buffer:
            self.logger.warning(
                "shutdown with %d unevaluated buffered jobs", len(self.buffer)
            )
