"""Device-mesh helpers for the batched evaluation path.

The rebuild's scale axis is the *config batch* (SURVEY.md §5 "long-context"
row: the reference has no sequence dimension; scaling configs-per-bracket is
the analog). These helpers build 1-D ("config") and 2-D ("config", "model")
meshes over whatever devices are visible — real TPU chips or the virtual
CPU devices used in tests.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "config_mesh",
    "config_model_mesh",
    "batch_sharding",
    "is_multiprocess_mesh",
    "shard_count",
    "pad_to_shards",
]


def is_multiprocess_mesh(mesh: Optional[Mesh]) -> bool:
    """True when ``mesh`` spans more than one JAX process (the DCN tier).

    The single definition of "is this a multi-host run" — VmapBackend's
    output replication, the fused sweep's replicated in/out shardings, and
    FusedBOHB's global-array argument assembly all branch on this, and they
    must agree or ranks deadlock fetching shards homed on other processes.
    """
    if mesh is None:
        return False
    return any(
        d.process_index != jax.process_index() for d in mesh.devices.flat
    )


def config_mesh(devices: Optional[Sequence] = None,
                axis_name: str = "config") -> Mesh:
    """1-D mesh over all devices. The default 'config' axis shards the
    config batch; ``ops.ring_attention.seq_mesh`` reuses this with a
    'seq' axis for the long-context path."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), axis_names=(axis_name,))


def config_model_mesh(
    config_parallel: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """2-D mesh: shard configs over 'config', shard each model over 'model'.

    Used when a single config's training step itself is tensor-sharded
    (large models) while still batching many configs.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if config_parallel is None:
        config_parallel = n
    if n % config_parallel != 0:
        raise ValueError(f"{n} devices not divisible by config_parallel={config_parallel}")
    arr = np.asarray(devices).reshape(config_parallel, n // config_parallel)
    return Mesh(arr, axis_names=("config", "model"))


def batch_sharding(mesh: Mesh, axis: str = "config") -> NamedSharding:
    """Sharding that splits a leading batch dim over ``axis``, replicating rest."""
    return NamedSharding(mesh, PartitionSpec(axis))


def shard_count(mesh: Optional[Mesh], axis: str = "config") -> int:
    """Number of shards along ``axis`` (1 for no mesh / absent axis).

    The ONE definition of "how many ways is the config batch split" — the
    sharded samplers (``ops.sweep.random_unit_sharded``), the per-stage
    sharding constraints in the fused kernels, and the per-device balance
    gauges all derive their geometry from this, and they must agree or a
    shard's PRNG stream and its device placement drift apart.
    """
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get(axis, 1))


def pad_to_shards(n: int, mesh: Optional[Mesh], axis: str = "config") -> int:
    """``n`` rounded up to a multiple of the ``axis`` shard count."""
    m = shard_count(mesh, axis)
    return ((int(n) + m - 1) // m) * m
