"""Dispatcher — master-side job broker for the elastic host worker pool.

Reference: ``core/dispatcher.py`` (SURVEY.md §2/§3): a discovery loop polls
the nameserver ~1/s for worker registrations (elastic join/leave), a job
runner matches queued jobs to idle workers, results arrive via RPC from
workers and are forwarded to the Master's callback. Vanished workers are
dropped and their in-flight jobs requeued — the reference's failure
semantics (SURVEY.md §5 "Failure detection" row).

Implements the same executor seam as ``parallel.BatchedExecutor``, so the
identical Master drives either tier.

Observability (docs/observability.md): jobs are dispatched under their
:class:`~hpbandster_tpu.obs.trace.TraceContext` (the ``_obs`` RPC envelope
carries it to the worker), ``job_started`` reports ``queue_wait_s`` /
``dispatch_s``, queue-depth and in-flight gauges track scheduling
pressure, the ping loop doubles as the fleet heartbeat collector
(``obs_snapshot`` per worker, ``dispatcher.workers_alive`` / last-seen-age
gauges), and the dispatcher's own RPC server answers ``obs_snapshot``.

Elastic recovery (docs/fault_tolerance.md): result ingestion is
exactly-once — every copy of a result (late arrivals from presumed-dead
workers, worker delivery retries racing a slow ack, chaos-duplicated
frames) resolves through the job's idempotency key, the first copy joins
the run, later copies are counted and acked. A late result for a
requeued-but-not-yet-redispatched job claims it straight from the
waiting queue (work is never redone just because the ack was lost), and
dead letters are keyed so a resubmitted job joins its stranded payload
back on submit. Requeues carry a capped-backoff retry budget; exhausting
it fails the job instead of hot-looping it through the pool. When an
attached anomaly detector fires ``worker_flapping``, the named worker is
quarantined — dropped AND banned from rediscovery until the quarantine
expires — instead of being rediscovered into the same crash loop.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from hpbandster_tpu import obs
from hpbandster_tpu.core.job import Job
from hpbandster_tpu.core.recovery import (
    DeadLetterBox,
    ExactlyOnceGate,
    idempotency_key,
)
from hpbandster_tpu.obs.health import HealthEndpoint
from hpbandster_tpu.parallel.rpc import (
    CommunicationError,
    RPCError,
    RPCProxy,
    RPCServer,
    format_uri,
)

__all__ = ["Dispatcher", "WorkerProxy"]


class WorkerProxy:
    """Master-side record of one discovered worker."""

    def __init__(self, name: str, uri: str):
        self.name = name
        self.uri = uri
        self.proxy = RPCProxy(uri, timeout=30)
        self.runs_job: Optional[Any] = None  # config_id or None
        #: heartbeat state (written only by the ping loop / discovery)
        self.last_seen_mono: float = time.monotonic()
        self.last_snapshot: Optional[Dict[str, Any]] = None
        self._supports_obs_snapshot = True  # optimistic until an RPCError

    def is_alive(self) -> bool:
        try:
            self.proxy.call("ping")
        except (CommunicationError, RPCError):
            return False
        self.last_seen_mono = time.monotonic()
        return True

    def heartbeat(self) -> bool:
        """One liveness probe, preferring the ``obs_snapshot`` fleet-health
        endpoint (worker metrics + ring tail + in-flight job retained on
        :attr:`last_snapshot`); falls back to plain ``ping`` for older
        peers that predate the endpoint."""
        try:
            if self._supports_obs_snapshot:
                try:
                    self.last_snapshot = self.proxy.call("obs_snapshot")
                except RPCError:
                    # older worker without the endpoint: remember, fall back
                    self._supports_obs_snapshot = False
                    self.proxy.call("ping")
            else:
                self.proxy.call("ping")
        except (CommunicationError, RPCError):
            return False
        self.last_seen_mono = time.monotonic()
        return True

    def shutdown(self) -> None:
        try:
            self.proxy.call("shutdown")
        except (CommunicationError, RPCError):
            pass


class Dispatcher:
    def __init__(
        self,
        run_id: str,
        nameserver: str = "127.0.0.1",
        nameserver_port: Optional[int] = None,
        host: Optional[str] = None,
        ping_interval: float = 10.0,
        discover_interval: float = 1.0,
        logger: Optional[logging.Logger] = None,
        anomaly: Any = None,
        dead_letter_capacity: int = 64,
        max_job_requeues: int = 8,
        requeue_backoff: float = 0.25,
        requeue_backoff_cap: float = 8.0,
        quarantine_s: float = 60.0,
    ):
        self.run_id = run_id
        self.nameserver_uri = format_uri(nameserver, nameserver_port)
        self.host = host or "127.0.0.1"
        self.ping_interval = ping_interval
        self.discover_interval = discover_interval
        self.logger = logger or logging.getLogger("hpbandster_tpu.dispatcher")

        self.prefix = f"hpbandster.run_{run_id}.worker."
        self.workers: Dict[str, WorkerProxy] = {}
        self.waiting_jobs: List[Job] = []
        self.running_jobs: Dict[Any, Job] = {}

        #: dead-letter trail for results that arrive for unknown jobs (the
        #: worker already computed them — the payload must not vanish):
        #: counted in obs metrics, retained for post-mortems, and KEYED so
        #: a resubmitted job can claim its stranded payload on submit.
        #: Capacity is a knob; overflow counts dispatcher.dead_letters_dropped
        #: instead of silently discarding computed work
        self.dead_letters = DeadLetterBox(capacity=dead_letter_capacity)
        #: exactly-once result ingestion: first copy of each idempotency
        #: key joins the run, every later copy is a counted duplicate
        self._gate = ExactlyOnceGate()

        #: requeue retry budget (capped exponential backoff): a job whose
        #: workers keep dying redispatches at most this many times before
        #: it fails with an exception result instead of looping forever
        self.max_job_requeues = int(max_job_requeues)
        self.requeue_backoff = float(requeue_backoff)
        self.requeue_backoff_cap = float(requeue_backoff_cap)

        #: quarantine ledger: worker name -> monotonic expiry. Quarantined
        #: names are skipped by discovery until expiry, so a flapping host
        #: cannot rejoin the pool faster than it crashes out of it
        self.quarantine_s = float(quarantine_s)
        self._quarantined: Dict[str, float] = {}

        self._cond = threading.Condition()
        self._shutdown_event = threading.Event()
        self._server: Optional[RPCServer] = None
        self._threads: List[threading.Thread] = []
        self._new_result_callback: Optional[Callable[[Job], None]] = None
        self._new_worker_callback: Optional[Callable[[int], None]] = None

        #: opt-in streaming anomaly detection (obs/anomaly.py): truthy
        #: subscribes a detector to the process bus for the run's lifetime
        #: and surfaces its alert tally in this dispatcher's obs_snapshot
        #: (pass AnomalyRules to tune thresholds, True for defaults)
        self.anomaly_detector = None
        self._anomaly_detach: Optional[Callable[[], None]] = None
        self._alert_detach: Optional[Callable[[], None]] = None
        if anomaly:
            from hpbandster_tpu.obs.anomaly import AnomalyDetector, AnomalyRules

            self.anomaly_detector = AnomalyDetector(
                rules=anomaly if isinstance(anomaly, AnomalyRules) else None,
                bus=obs.get_bus(),
            )

    # --------------------------------------------------------- executor seam
    def start(
        self,
        new_result_callback: Callable[[Job], None],
        new_worker_callback: Callable[[int], None],
    ) -> None:
        self._new_result_callback = new_result_callback
        self._new_worker_callback = new_worker_callback

        self._server = RPCServer(self.host, 0)
        self._server.register("register_result", self._rpc_register_result)
        self._server.register("ping", lambda: "pong")
        if self.anomaly_detector is not None:
            self._anomaly_detach = obs.get_bus().subscribe(self.anomaly_detector)
            # close the loop: the detector's alerts were previously only
            # counted — now worker_flapping quarantines the worker it
            # names (drop + rediscovery ban + requeue of its job)
            self._alert_detach = obs.get_bus().subscribe(self._on_alert)
        # fleet health: the dispatcher introspects like any other process
        HealthEndpoint(
            component="dispatcher",
            identity=obs.process_identity(run_id=self.run_id),
            ring=self.dead_letters,
            in_flight=self._health_in_flight,
            anomaly=self.anomaly_detector,
        ).register(self._server)
        self._server.start()

        for target, name in (
            (self._discover_loop, "discover"),
            (self._job_runner_loop, "job-runner"),
            (self._ping_loop, "ping"),
        ):
            t = threading.Thread(
                target=target, daemon=True, name=f"dispatcher-{name}-{self.run_id}"
            )
            t.start()
            self._threads.append(t)

    def submit_job(self, job: Job) -> None:
        if job.idem_key is None:
            job.idem_key = idempotency_key(job.id, job.kwargs.get("budget", 0.0))
        # exactly-once dead-letter replay: a resubmitted job (crash-restart
        # re-dispatching its unfinished configs) whose result already
        # arrived — and was dead-lettered because nobody knew the job —
        # joins that payload back instead of re-running the evaluation
        letter = self.dead_letters.take(job.idem_key)
        if letter is not None and not self._gate.admit(job.idem_key):
            # the key was already ingested once — the letter is a stale
            # duplicate copy, not recoverable work
            obs.get_metrics().counter("recovery.duplicates_dropped").inc()
            letter = None
        if letter is not None:
            self.logger.info(
                "job %s joined its dead-lettered result on submit", job.id
            )
            obs.emit(
                obs.RESULT_REPLAYED,
                config_id=list(job.id), budget=job.kwargs.get("budget"),
                source="dead_letter", key=job.idem_key,
            )
            obs.get_metrics().counter("recovery.replayed_results").inc()
            self._deliver(job, letter.get("result") or {})
            return
        with self._cond:
            self.waiting_jobs.append(job)
            self._update_queue_gauges()
            self._cond.notify_all()

    def _deliver(self, job: Job, payload: Dict[str, Any]) -> None:
        """Hand a terminal payload to the master's callback (shared by the
        normal ingest path, dead-letter joins, and budget exhaustion)."""
        if "started" not in job.timestamps:
            job.time_it("started")
        job.time_it("finished")
        job.result = payload.get("result")
        job.exception = payload.get("exception")
        self._new_result_callback(job)

    def _update_queue_gauges(self) -> None:
        # callers hold self._cond; the gauges' own registry lock nests
        # inside it (metrics code never takes dispatcher locks, so the
        # ordering is acyclic)
        m = obs.get_metrics()
        m.gauge("dispatcher.queue_depth").set(len(self.waiting_jobs))  # graftlint: disable=lock-coverage — every caller holds self._cond
        m.gauge("dispatcher.jobs_in_flight").set(len(self.running_jobs))  # graftlint: disable=lock-coverage — every caller holds self._cond

    def _health_in_flight(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "running": [list(cid) for cid in self.running_jobs],
                "waiting": len(self.waiting_jobs),
                "workers": len(self.workers),
                "quarantined": sorted(self._quarantined),
            }

    def number_of_workers(self) -> int:
        with self._cond:
            return len(self.workers)

    def n_waiting(self) -> int:
        with self._cond:
            return len(self.waiting_jobs)

    def shutdown(self, shutdown_workers: bool = False) -> None:
        self._shutdown_event.set()
        if shutdown_workers:
            with self._cond:
                targets = list(self.workers.values())
            for w in targets:
                w.shutdown()
        with self._cond:
            self._cond.notify_all()
        if self._anomaly_detach is not None:
            self._anomaly_detach()
            self._anomaly_detach = None
        if self._alert_detach is not None:
            self._alert_detach()
            self._alert_detach = None
        if self._server is not None:
            self._server.shutdown()
            self._server = None

    # ------------------------------------------------------------- discovery
    def _discover_loop(self) -> None:
        ns = RPCProxy(self.nameserver_uri, timeout=5)
        while not self._shutdown_event.wait(0.0):
            try:
                listing: Dict[str, str] = ns.call("list", prefix=self.prefix)
            except (CommunicationError, RPCError) as e:
                self.logger.debug("nameserver unreachable: %r", e)
                listing = None
            if listing is not None:
                self._sync_workers(listing)
            if self._shutdown_event.wait(self.discover_interval):
                return

    def _sync_workers(self, listing: Dict[str, str]) -> None:
        with self._cond:
            known = set(self.workers)
            now = time.monotonic()
            # expire served quarantines; anything still listed is banned
            self._quarantined = {
                n: t for n, t in self._quarantined.items() if t > now
            }
            quarantined = set(self._quarantined)
        added = 0
        for name, uri in listing.items():
            if name in known:
                continue
            if name in quarantined:
                self.logger.debug(
                    "worker %s still quarantined; not rediscovering", name
                )
                continue
            w = WorkerProxy(name, uri)
            if not w.is_alive():
                self.logger.debug("listed worker %s unreachable; skipping", name)
                continue
            with self._cond:
                self.workers[name] = w
            added += 1
            obs.emit(obs.WORKER_DISCOVERED, worker=name, uri=uri)
            obs.get_metrics().counter("dispatcher.workers_discovered").inc()
            self.logger.info("discovered worker %s at %s", name, uri)
        vanished = known - set(listing)
        for name in vanished:
            self._drop_worker(name, reason="unregistered")
        if added or vanished:
            with self._cond:
                n = len(self.workers)
                self._cond.notify_all()
            self._new_worker_callback(n)

    # ------------------------------------------------- bounded retry budget
    def _stamp_requeue(self, job: Job) -> bool:
        """Consume one requeue attempt: True = still within budget (the
        job now carries its capped-exponential-backoff eligibility
        instant), False = budget exhausted (the caller must fail the job
        via :meth:`_fail_exhausted`). ONE implementation for the
        worker-death and dispatch-failure paths — the retry contract
        (docs/fault_tolerance.md) must not be able to diverge."""
        job.requeue_count += 1
        if job.requeue_count > self.max_job_requeues:
            return False
        job.not_before_mono = time.monotonic() + min(
            self.requeue_backoff * (2.0 ** (job.requeue_count - 1)),
            self.requeue_backoff_cap,
        )
        return True

    def _note_requeued(self, job: Job, worker: str, reason: str) -> None:
        obs.emit(
            obs.JOB_REQUEUED,
            config_id=list(job.id), worker=worker, reason=reason,
            attempt=job.requeue_count, max_attempts=self.max_job_requeues,
        )
        obs.get_metrics().counter("recovery.requeues").inc()

    def _fail_exhausted(self, job: Job, worker: str, reason: str) -> None:
        """Terminal failure once the retry budget is gone — through the
        exactly-once gate, so a genuinely-late result arriving after the
        failure reads as a duplicate, never a double registration."""
        obs.get_metrics().counter("recovery.requeue_budget_exhausted").inc()
        self.logger.error(
            "job %s exhausted its requeue budget (%d attempts); failing",
            job.id, job.requeue_count,
        )
        if self._gate.admit(job.idem_key or ""):
            self._deliver(job, {
                "result": None,
                "exception": (
                    f"requeue budget exhausted: {job.requeue_count} "
                    f"dispatch attempts all failed "
                    f"(last: {worker}, {reason})"
                ),
            })

    def _drop_worker(self, name: str, reason: str) -> None:
        failed_job: Optional[Job] = None
        with self._cond:
            w = self.workers.pop(name, None)
            if w is None:
                return
            job = self.running_jobs.pop(tuple(w.runs_job), None) if w.runs_job else None
            if job is not None:
                if self._stamp_requeue(job):
                    # elastic failure handling: requeue the orphaned job
                    # under capped backoff (a config that kills its
                    # workers must not hot-loop the survivors)
                    self.logger.warning(
                        "worker %s vanished (%s); requeueing job %s "
                        "(attempt %d/%d)",
                        name, reason, job.id,
                        job.requeue_count, self.max_job_requeues,
                    )
                    self.waiting_jobs.insert(0, job)
                else:
                    # retry budget exhausted: fail the job instead of
                    # cycling it through the pool forever — the bracket
                    # records it crashed-as-worst and moves on
                    failed_job = job
                self._update_queue_gauges()
            else:
                self.logger.info("worker %s dropped (%s)", name, reason)
            self._cond.notify_all()
        obs.emit(
            obs.WORKER_DROPPED,
            worker=name, reason=reason,
            # only report a requeue that actually happened: a job failed
            # for exhausting its retry budget was NOT requeued
            requeued=(
                list(job.id)
                if job is not None and failed_job is None else None
            ),
        )
        obs.get_metrics().counter("dispatcher.workers_dropped").inc()
        if job is not None and failed_job is None:
            self._note_requeued(job, name, reason)
        # a departed worker's last-seen-age gauge must leave with it, or
        # elastic churn leaks stale frozen metrics without bound
        obs.get_metrics().remove(f"dispatcher.worker_last_seen_age_s.{name}")
        if failed_job is not None:
            self._fail_exhausted(failed_job, name, reason)

    def _ping_loop(self) -> None:
        """Heartbeat collector: detect dying workers (requeue their jobs)
        and keep the fleet-health gauges current."""
        while not self._shutdown_event.wait(self.ping_interval):
            self._heartbeat_round()

    def _heartbeat_round(self) -> None:
        """One sweep over every known worker: ``obs_snapshot`` (or ``ping``
        for older peers) each one, drop the unreachable — a dead idle
        worker must leave the pool, not just a dead busy one — and feed
        the ``dispatcher.workers_alive`` / per-worker last-seen-age
        gauges."""
        with self._cond:
            targets = list(self.workers.items())
        alive = 0
        for name, w in targets:
            if w.heartbeat():
                alive += 1
            else:
                self._drop_worker(name, reason="heartbeat failed")
        m = obs.get_metrics()
        m.gauge("dispatcher.workers_alive").set(alive)
        now = time.monotonic()
        with self._cond:
            survivors = list(self.workers.values())
        for w in survivors:
            m.gauge(f"dispatcher.worker_last_seen_age_s.{w.name}").set(
                round(now - w.last_seen_mono, 3)
            )

    # ------------------------------------------------------------ job runner
    def _idle_worker(self) -> Optional[WorkerProxy]:
        # sole caller is _job_runner_loop, inside `with self._cond:`
        for w in self.workers.values():  # graftlint: disable=lock-coverage
            if w.runs_job is None:
                return w
        return None

    def _job_runner_loop(self) -> None:
        while not self._shutdown_event.is_set():
            with self._cond:
                job = None
                worker = None
                if self.waiting_jobs:
                    worker = self._idle_worker()
                    if worker is not None:
                        # first ELIGIBLE job: requeued jobs sit out their
                        # capped backoff window while fresh jobs behind
                        # them keep the pool busy
                        now = time.monotonic()
                        for i, candidate in enumerate(self.waiting_jobs):
                            if candidate.not_before_mono <= now:
                                job = self.waiting_jobs.pop(i)
                                break
                        if job is not None:
                            worker.runs_job = job.id
                            self.running_jobs[tuple(job.id)] = job
                            self._update_queue_gauges()
                if job is None:
                    self._cond.wait(0.2)
                    continue
            # RPC outside the lock: the worker spawns a compute thread and
            # returns immediately
            job.time_it("started")
            job.worker_name = worker.name
            queue_wait = job.mono_duration("submitted", "started")
            try:
                # under the job's trace AND tenant: the RPC proxy injects
                # the _obs envelope, so the worker's half of the timeline
                # carries the same trace_id (and, in the serving tier,
                # journals under the right tenant)
                with obs.use_tenant(
                    getattr(job, "tenant_id", None)
                ), obs.use_trace(getattr(job, "trace", None)):
                    t0 = time.monotonic()
                    worker.proxy.call(
                        "start_computation",
                        callback_uri=self._server.uri,
                        id=list(job.id),
                        **job.kwargs,
                    )
                    obs.emit(
                        obs.JOB_STARTED,
                        config_id=list(job.id), worker=worker.name,
                        queue_wait_s=(
                            round(queue_wait, 6) if queue_wait is not None else None
                        ),
                        dispatch_s=round(time.monotonic() - t0, 6),
                    )
                self.logger.debug("job %s -> %s", job.id, worker.name)
            except (CommunicationError, RPCError) as e:
                self.logger.warning(
                    "dispatch of %s to %s failed (%r)", job.id, worker.name, e
                )
                with self._cond:
                    self.running_jobs.pop(tuple(job.id), None)
                    worker.runs_job = None
                if isinstance(e, CommunicationError):
                    self._drop_worker(worker.name, reason="dispatch failed")
                # same bounded-retry contract as a worker death: a job
                # whose dispatch keeps failing (e.g. a kwargs payload the
                # server rejects every time) must back off and eventually
                # fail, not hot-loop through the next idle worker
                self._requeue_or_fail(
                    job, worker.name, reason=f"dispatch failed: {e!r}"
                )

    def _requeue_or_fail(self, job: Job, worker: str, reason: str) -> None:
        """Bounded requeue for a job whose dispatch attempt failed: the
        same budget/backoff contract as the worker-death path in
        ``_drop_worker`` (shared via ``_stamp_requeue``/``_fail_exhausted``)."""
        if not self._stamp_requeue(job):
            self._fail_exhausted(job, worker, reason)
            return
        with self._cond:
            self.waiting_jobs.insert(0, job)
            self._update_queue_gauges()
            self._cond.notify_all()
        self._note_requeued(job, worker, reason)

    # ---------------------------------------------------------- result inflow
    def _rpc_register_result(
        self, id: Any, result: Dict[str, Any], key: Optional[str] = None
    ) -> bool:
        """Exactly-once result ingestion.

        ``key`` is the job's idempotency key, stamped by the worker
        (``core/worker.py`` sends it on every delivery attempt; older
        workers omit it and the dispatcher recovers it from its own job
        records). Resolution order:

        1. job running under this cid AND matching this key -> gate-admit,
           deliver (duplicates counted + ACKED so the delivering worker
           stops retrying);
        2. matching job requeued and still WAITING -> claim it from the
           queue and deliver (a late result from a presumed-dead worker
           means the work is done — never redo it);
        3. no matching job, key already ingested -> duplicate, acked;
        4. no matching job, unknown key -> dead-letter (keyed, bounded,
           overflow counted), awaiting a resubmit to join back.

        The claim is KEY-aware, not just cid-aware: a config re-runs at
        every rung with the same cid, so a late duplicate of its
        budget-1 delivery must never claim (and discard) its live
        budget-3 job — a cross-budget copy falls through to 3/4 instead.
        A keyless delivery (old worker) matches by cid alone, the
        pre-key behavior.
        """

        def matches(candidate: Job) -> bool:
            return (
                key is None
                or candidate.idem_key is None
                or key == candidate.idem_key
            )

        cid = tuple(id)
        duplicate = False
        with self._cond:
            job = self.running_jobs.get(cid)
            if job is not None and matches(job):
                del self.running_jobs[cid]
            else:
                job = None
                # a requeued-but-not-redispatched job can still claim its
                # late result: the evaluation is DONE, drop it from the
                # queue instead of re-running it
                for i, waiting in enumerate(self.waiting_jobs):
                    if tuple(waiting.id) == cid and matches(waiting):
                        job = self.waiting_jobs.pop(i)
                        break
            if job is not None:
                if job.idem_key is None:
                    job.idem_key = idempotency_key(
                        job.id, job.kwargs.get("budget", 0.0)
                    )
                # admit under the SAME lock as the claim: a concurrent
                # copy of this delivery either still sees the job (and
                # queues behind this claim) or sees the admitted key —
                # never the neither-window that would dead-letter an
                # already-ingested payload as a phantom unknown result
                admitted = self._gate.admit(
                    key if key is not None else job.idem_key
                )
                for w in self.workers.values():
                    if w.runs_job is not None and tuple(w.runs_job) == cid:
                        w.runs_job = None
                self._update_queue_gauges()
                self._cond.notify_all()
            else:
                # late retry of an already-ingested delivery (e.g. the
                # ack of the first copy was lost): duplicate, acked,
                # never re-joined. Checked under _cond for the same
                # race-closure as the admit above.
                duplicate = key is not None and self._gate.seen(key)
        if job is not None:
            if not admitted:
                self._note_duplicate(cid, key or job.idem_key)
                return True  # ACK: the result is ingested, stop retrying
            self._deliver(job, result)
            return True
        if duplicate:
            self._note_duplicate(cid, key)
            return True
        # dead-letter, don't drop: a worker computed this (e.g. a late
        # result landing after its worker was declared dead, requeued,
        # and re-discovered) — count it and retain the payload for
        # post-mortems AND replay: a later submit of the same key joins
        # it back exactly once. Outside the lock: sinks do I/O, and a
        # journal write must not stall the job-runner loop on self._cond.
        # The delivering worker's trace and tenant (the _obs envelope on
        # this very RPC) are retained with it, so the dead letter joins
        # back onto the merged timeline — and a multi-tenant post-mortem
        # can attribute the orphaned payload to the sweep that paid for it.
        tc = obs.current_trace()
        self.dead_letters.append({
            "config_id": list(cid), "result": result, "key": key,
            "trace_id": tc.trace_id if tc is not None else None,
            "tenant_id": obs.current_tenant() or obs.DEFAULT_TENANT,
        })
        obs.get_metrics().counter("dispatcher.unknown_results").inc()
        obs.emit(obs.UNKNOWN_RESULT, config_id=list(cid))
        self.logger.warning(
            "result for unknown job %s dead-lettered (%d retained)",
            cid, len(self.dead_letters),
        )
        return False

    def _note_duplicate(self, cid: Any, key: Optional[str]) -> None:
        obs.get_metrics().counter("recovery.duplicates_dropped").inc()
        obs.emit(obs.DUPLICATE_RESULT, config_id=list(cid), key=key)
        self.logger.info("duplicate result for %s (key %s) dropped", cid, key)

    # ------------------------------------------------------------ quarantine
    def _on_alert(self, event: Any) -> None:
        """Bus sink closing the anomaly loop: a ``worker_flapping`` alert
        quarantines the worker it names (this dispatcher's prefix only —
        a foreign journal's worker ids are not ours to act on)."""
        try:
            if getattr(event, "name", None) != "alert":
                return
            fields = getattr(event, "fields", None) or {}
            if fields.get("rule") != "worker_flapping":
                return
            subject = str(fields.get("subject") or "")
            if subject.startswith(self.prefix):
                self.quarantine_worker(subject, reason="worker_flapping")
        except Exception:
            # bus sinks must never raise (events.py contract)
            self.logger.exception("alert-driven quarantine failed")

    def quarantine_worker(
        self, name: str, reason: str, duration_s: Optional[float] = None
    ) -> None:
        """Drop ``name`` (its in-flight job requeues under the normal
        retry budget) and ban it from rediscovery for ``duration_s``
        (default ``quarantine_s``) — a flapping host must sit out, not
        cycle through discover/crash/requeue."""
        duration = self.quarantine_s if duration_s is None else float(duration_s)
        with self._cond:
            already = name in self._quarantined
            self._quarantined[name] = time.monotonic() + duration
        self._drop_worker(name, reason=f"quarantined ({reason})")
        if not already:
            obs.emit(
                obs.WORKER_QUARANTINED,
                worker=name, reason=reason, duration_s=duration,
            )
            obs.get_metrics().counter("recovery.quarantines").inc()
            self.logger.warning(
                "worker %s quarantined for %.1fs (%s)", name, duration, reason
            )
